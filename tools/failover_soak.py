"""Full failover soak (the crash-restart PR's acceptance workload).

Runs the 500-pod two-replica leader-election churn twice, killing the
leader at EVERY registered crash point in turn (chaos/faults.py
CRASH_POINTS), and checks:
  - every pod bound exactly once per incarnation, no half-bound gang;
  - recovery bounded (lease expiry + cold-start, in driver iterations);
  - the drift detector reports zero unrepaired divergence after every
    recovery and on its periodic cadence;
  - determinism: both runs kill at the same hits, inject the same faults,
    and converge to the same signature.

The tier-1 suite runs a 30-pod variant of the same harness
(tests/test_recovery.py); the 500-pod version is marked `slow` there and
runs here instead:

    python tools/failover_soak.py [SEED]
"""

import sys

sys.path.insert(0, ".")

from kubernetes_tpu.recovery.failover import KILL_ORDER, run_failover_soak  # noqa: E402

SEED = int(sys.argv[1]) if len(sys.argv) > 1 else 7
CFG = dict(n_plain=472, n_gangs=3, gang_size=4, overflow_gang_size=16,
           n_nodes=124, batch_size=64, group_max_size=16,
           phase_cap=1500, max_iterations=20000)


def report(tag, r):
    status = "CONVERGED" if r.converged else "FAILED"
    print(f"[{tag}] {status}: {r.bound}/{r.pods} bound, "
          f"{r.duplicate_binds} duplicate binds, "
          f"crashes={len(r.crashes)}/{len(KILL_ORDER)}, "
          f"recoveries={r.recoveries}, "
          f"max_recovery_iters={r.max_recovery_iterations}, "
          f"drift={r.drift_divergent}/{r.drift_unrepaired} "
          f"(found/unrepaired), events_lost={r.events_lost}, "
          f"{r.wall_seconds:.1f}s")
    print(f"[{tag}] crash order: {r.crashes}")
    print(f"[{tag}] injected: {dict(sorted(r.injected.items()))}")
    return r.converged and r.crashes == list(KILL_ORDER)


r1 = run_failover_soak(seed=SEED, **CFG)
ok1 = report("run1", r1)
r2 = run_failover_soak(seed=SEED, **CFG)
ok2 = report("run2", r2)

deterministic = r1.determinism_signature() == r2.determinism_signature()
print(f"deterministic replay: {deterministic}")
if not deterministic:
    print(f"  run1: {r1.determinism_signature()}")
    print(f"  run2: {r2.determinism_signature()}")
sys.exit(0 if (ok1 and ok2 and deterministic) else 1)
