"""Assemble BENCH_r06_AB.json from paired baseline/round-6 bench JSONL runs.

Usage:
    python tools/build_r6_ab.py BASE_FILE:NEW_FILE [BASE2:NEW2 ...]

Each file holds one bench.py JSON line per suite; rows are paired by
workload name.  The output artifact drives the COMPONENTS.md Round-6 A/B
table via tools/render_perf_docs.py (generate, don't transcribe).
"""

from __future__ import annotations

import json
import os
import sys


def load_rows(path):
    """workload → list of passes (VERDICT r5 weak #5: commit the band, not
    the best window — a suite appearing on several lines keeps them all)."""
    out = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)["detail"]
            out.setdefault(d["workload"], []).append(d)
    return out


def median_pass(passes):
    s = sorted(passes, key=lambda d: d["throughput_pods_per_s"])
    return s[len(s) // 2]


def subset(d):
    keep = {
        "throughput_pods_per_s": d["throughput_pods_per_s"],
        "attempt_ms": d["attempt_ms"],
        "xla_compiles_in_window": d["xla_compiles_in_window"],
        "nodes": d["nodes"],
        "measure_pods": d["measure_pods"],
    }
    if "phase_wall_s" in d:
        keep["phase_wall_s"] = d["phase_wall_s"]
    return keep


def main(argv):
    import multiprocessing

    scales = json.loads(os.environ.get("AB_SCALES", "{}"))
    rows = []
    for pair in argv[1:]:
        base_p, new_p = pair.split(":")
        base, new = load_rows(base_p), load_rows(new_p)
        for suite in new:
            if suite not in base:
                continue
            b = median_pass(base[suite])
            n = median_pass(new[suite])
            rows.append({
                "suite": suite,
                "scale": scales.get(suite, 1.0),
                "baseline": subset(b),
                "round6": subset(n),
                "baseline_passes_pods_per_s": sorted(
                    p["throughput_pods_per_s"] for p in base[suite]),
                "round6_passes_pods_per_s": sorted(
                    p["throughput_pods_per_s"] for p in new[suite]),
                "speedup": round(
                    n["throughput_pods_per_s"]
                    / max(b["throughput_pods_per_s"], 1e-9), 3),
            })
    rows.sort(key=lambda r: r["suite"])
    artifact = {
        "environment": {
            "backend": "cpu",
            "cpus": multiprocessing.cpu_count(),
            "note": (
                "no TPU in this round's container; the 5k-node suites OOM "
                "on the CPU backend's materialized one-hot gathers, so both "
                "arms (pre-round-6 git worktree vs this build) ran at the "
                "scales below on the SAME machine — cross-hardware "
                "comparison against the round-5 TPU rows is not meaningful"
            ),
        },
        "scale_note": (
            "Affinity suites at scale 0.4 / batch 64 (multi-batch windows); "
            "SchedulingBasic + SchedulingExtender at their full 500-node "
            "size; NorthStar at scale 0.1.  `chain_affinity=\"auto\"` keeps "
            "affinity deep-chaining off on this CPU backend (its einsums "
            "are added compute with no dispatch latency to hide); the "
            "chained path is proven binding-identical in "
            "tests/test_deep_pipeline.py and enabled by default on "
            "accelerator backends."
        ),
        "rows": rows,
    }
    hostprep = os.environ.get("AB_HOSTPREP")
    if hostprep:
        artifact["host_prepare_scaling_ms"] = json.loads(hostprep)
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_r06_AB.json")
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {out} ({len(rows)} rows)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
