"""Two-follower WAL-shipping soak gate + the round-16 replica bench.

Gate mode (default) — the replication layer's CI gate, run fail-fast by
tools/run_suites.sh before any perf suite:

  - the two-follower failover soak at EVERY leader-kill boundary
    (shipped / unshipped / torn), 500 recording watchers per follower
    (1000 total — the acceptance shape tests/test_replication.py slow-marks),
    heavy ship-wire fault rates: zero lost/duplicated watch events across
    the incarnation boundary, zero overclaimed bookmarks, exactly-once
    binds, a fenced promotion race with one winner, the dead leader's
    unshipped suffix discarded exactly-once and divergence-probed clean;
  - a same-seed determinism replay of the unshipped run: identical
    injected-fault counts, winner, discard count, and final rv.

Bench mode (``--bench``) — multi-pass promotion-time and follower-read-
throughput measurement, median + per-pass band, written to
BENCH_r16_REPLICA.json and rendered into COMPONENTS.md by
tools/render_perf_docs.py:

  - promotion: a fresh follower incarnation over a shipped N-record log
    (the rejoin replay is setup, NOT timed) runs promote() — fence-free
    fsync + tail verification + WAL reattach, the write-unavailability
    window a failover pays;
  - follower reads: rv-pinned paged LIST walks against the follower's
    watch cache at the replication watermark, ops/s.

    python tools/replica_soak.py [SEED]
    python tools/replica_soak.py --bench [PASSES]
"""

import json
import os
import shutil
import statistics
import sys
import tempfile
import time

sys.path.insert(0, ".")

from kubernetes_tpu.chaos.replication import run_replication_soak  # noqa: E402

SOAK_CFG = dict(n_pods=120, n_nodes=6, n_watchers=500,
                drop_rate=0.15, torn_rate=0.1, lag_rate=0.1)


def report(tag, r):
    status = "CONVERGED" if r.converged else "FAILED"
    print(f"[{tag}] {status}: {r.bound}/{r.pods} bound, "
          f"lost={r.events_lost} dup={r.events_duplicated} "
          f"overclaims={r.bookmark_overclaims} "
          f"dup_binds={r.duplicate_binds} phantoms={len(r.phantoms)}, "
          f"promoted={r.promoted} (fenced={r.fenced_losers}, "
          f"{r.promotion_ticks} ticks), discarded={r.discarded_records}, "
          f"rolled_back={r.rolled_back_events}, final_rv={r.final_rv}, "
          f"{r.wall_seconds:.1f}s")
    print(f"[{tag}] injected: {dict(sorted(r.injected.items()))} "
          f"ship_errors: {dict(sorted(r.ship_errors.items()))}")
    return r.converged


def gate(seed: int) -> int:
    ok = True
    results = {}
    for kill_mode in ("shipped", "unshipped", "torn"):
        with tempfile.TemporaryDirectory() as wd:
            r = run_replication_soak(seed=seed, workdir=wd,
                                     kill_mode=kill_mode, **SOAK_CFG)
        results[kill_mode] = r
        ok &= report(kill_mode, r)
    with tempfile.TemporaryDirectory() as wd:
        replay = run_replication_soak(seed=seed, workdir=wd,
                                      kill_mode="unshipped", **SOAK_CFG)
    deterministic = (replay.determinism_signature()
                     == results["unshipped"].determinism_signature())
    print(f"deterministic replay: {deterministic}")
    if not deterministic:
        print(f"  run1: {results['unshipped'].determinism_signature()}")
        print(f"  run2: {replay.determinism_signature()}")
    return 0 if (ok and deterministic) else 1


# --- bench mode ---------------------------------------------------------------

BENCH_RECORDS = 2000
READ_OPS = 2000
PAGE_LIMIT = 100


def _build_shipped_pair(workdir: str, n_records: int):
    """Leader with ``n_records`` WAL records (create+bind mix), fully
    shipped to one follower; returns (leader_store, shipper, follower)."""
    from kubernetes_tpu.sim.replication import FollowerReplica, LogShipper
    from kubernetes_tpu.sim.store import ObjectStore
    from kubernetes_tpu.sim.wal import WriteAheadLog
    from kubernetes_tpu.testutil import make_node, make_pod

    wal = WriteAheadLog(os.path.join(workdir, "leader.wal"), fsync_every=0)
    store = ObjectStore(wal=wal)
    for i in range(4):
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "64", "pods": "256"}).obj())
    n_pods = (n_records - 4) // 2
    for i in range(n_pods):
        name = f"b{i:05d}"
        store.create("Pod", make_pod().name(name).uid(name)
                     .namespace("default").req({"cpu": "1"}).obj())
        store.bind_pod("default", name, f"n{i % 4}")
    ship = LogShipper(wal.path, batch_max_records=256)
    f = FollowerReplica("bench-f1", os.path.join(workdir, "f1.wal"))
    ship.attach(f)
    ship.pump_until_synced()
    assert f.applied_rv() == store.current_rv()
    return store, ship, f


def bench(passes: int) -> int:
    from kubernetes_tpu.sim.replication import FollowerReplica

    out = {
        "suite": "ReplicationR16",
        "generated_by": "tools/replica_soak.py --bench",
        "environment": {
            "backend": "cpu",
            "cpus": os.cpu_count(),
            "note": "single-host sim; promotion excludes the rejoin "
                    "replay (setup), reads are rv-pinned paged walks "
                    "at the replication watermark",
        },
        "records": BENCH_RECORDS,
        "read_ops": READ_OPS,
        "page_limit": PAGE_LIMIT,
    }
    with tempfile.TemporaryDirectory() as wd:
        store, ship, f = _build_shipped_pair(wd, BENCH_RECORDS)

        promo_ms = []
        for p in range(passes):
            cand_path = os.path.join(wd, f"cand{p}.wal")
            shutil.copyfile(f.wal_path, cand_path)
            cand = FollowerReplica(f"cand{p}", cand_path)  # rejoin: untimed
            t0 = time.perf_counter()
            cand.promote()
            promo_ms.append((time.perf_counter() - t0) * 1e3)
            cand.store.wal.close()
            cand.watch_cache.close()

        read_ops_s = []
        for _ in range(passes):
            t0 = time.perf_counter()
            done = 0
            tok = None
            while done < READ_OPS:
                page, rv, tok = f.watch_cache.list_page(
                    "Pod", limit=PAGE_LIMIT, continue_=tok or None)
                done += 1
                if not tok:
                    tok = None
            read_ops_s.append(READ_OPS / (time.perf_counter() - t0))
        f.close()

    out["promotion_ms"] = {
        "median": statistics.median(promo_ms),
        "passes": [round(v, 2) for v in promo_ms],
    }
    out["follower_read_pages_per_s"] = {
        "median": statistics.median(read_ops_s),
        "passes": [round(v, 1) for v in read_ops_s],
    }

    # one fast converged soak rides along for the rendered context line
    with tempfile.TemporaryDirectory() as wd:
        r = run_replication_soak(seed=11, workdir=wd, kill_mode="unshipped")
    out["soak"] = {
        "converged": r.converged,
        "pods": r.pods,
        "promoted": r.promoted,
        "promotion_ticks": r.promotion_ticks,
        "fenced_losers": r.fenced_losers,
        "discarded_records": r.discarded_records,
        "events_lost": r.events_lost,
        "events_duplicated": r.events_duplicated,
        "bookmark_overclaims": r.bookmark_overclaims,
        "injected": dict(sorted(r.injected.items())),
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_r16_REPLICA.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=2)
        fh.write("\n")
    print(json.dumps(out, indent=2))
    return 0 if r.converged else 1


if __name__ == "__main__":
    if "--bench" in sys.argv[1:]:
        rest = [a for a in sys.argv[1:] if a != "--bench"]
        sys.exit(bench(int(rest[0]) if rest else 5))
    sys.exit(gate(int(sys.argv[1]) if len(sys.argv) > 1 else 16))
