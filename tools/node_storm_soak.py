#!/usr/bin/env python
"""Node-storm soak gate (ISSUE 13 acceptance shape): 3 zones × 100 hollow
nodes on a fake clock — zone outage frozen (zero evictions under
FullDisruption), scattered failures metered by the secondary rate, a
downed gang repaired atomically and rebound exactly once, PDBs honored,
and a same-seed replay reaching identical final bindings.

Runs the same ``chaos.partition.run_node_storm`` definition as the tier-1
fast shape (tests/test_node_lifecycle.py), so the gate and the battery can
never drift apart.  Exit 0 = pass.
"""

import json
import sys

sys.path.insert(0, ".")

from kubernetes_tpu.chaos.partition import run_node_storm  # noqa: E402

SHAPE = dict(nodes_per_zone=100, n_zones=3, seed=7,
             web_replicas=400, gang_size=8, large_zone_threshold=50)


def main() -> int:
    a = run_node_storm(**SHAPE)
    checks = {
        "full_disruption_held": a.outage_zone_mode == "FullDisruption",
        "zone_outage_zero_evictions": a.outage_evictions == 0,
        "heal_cancelled_countdowns": a.cancelled_on_heal > 0,
        "scattered_partial_mode": a.scattered_zone_mode == "PartialDisruption",
        "scattered_rate_bounded":
            a.scattered_swept <= a.scattered_budget,
        "gang_repaired_once": a.gang_repairs == 1,
        "gang_rebound_exactly_once":
            all(c == 1 for c in a.gang_member_binds.values()),
        "pdb_floor_held": a.pdb_floor_held,
        "no_pdb_overrides": a.overridden_evictions == 0,
        "all_bound": not a.unbound,
    }
    # determinism: the same seed must replay the same kill sequence to the
    # same final bindings
    b = run_node_storm(**SHAPE)
    checks["deterministic_replay"] = (
        a.determinism_signature() == b.determinism_signature())
    report = {
        "shape": SHAPE,
        "nodes": a.nodes,
        "pods": a.pods,
        "kill_events": len(a.kill_log),
        "scattered_swept": a.scattered_swept,
        "scattered_budget": a.scattered_budget,
        "cancelled_on_heal": a.cancelled_on_heal,
        "wall_seconds": round(a.wall_seconds + b.wall_seconds, 2),
        "checks": checks,
        "ok": all(checks.values()),
    }
    print(json.dumps(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
