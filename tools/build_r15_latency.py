"""Round-15 same-hardware attempt-latency A/B → BENCH_r15_LATENCY.json.

Two arms of SchedulingBasic/5000Nodes in THIS container, fresh subprocess
each (same discipline as tools/build_r12_ab.py):

  baseline  BENCH_LATENCY_TARGET=0  — the round-14 shape: full 512-pod
            batches, synchronous-equivalent latency profile (the committed
            BENCH_r14_TRACE.json numbers re-measured on today's weather so
            the ratio is weather-paired, not transcribed)
  round15   suite default           — micro-bucket pipelined dispatch
            (latency_target_ms) + overlapped background snapshot/sync

Acceptance (ISSUE 15): attempt p99 ≥5× lower than baseline at ≥90% of
baseline throughput, zero in-window compiles, phase coverage ∈ [0.9, 1.1].
The artifact also carries the "gates" block tools/run_suites.sh
gate_attempt_p99 reads (budget = measured p99 × tolerance; NorthStar's
budget is a regression bound against the committed BENCH_r09_100K.json
p99 — the 100k suite has no same-hardware micro-bucket A/B yet).

Usage: python tools/build_r15_latency.py [--passes N] [--out FILE]
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SUITE, SIZE = "SchedulingBasic", "5000Nodes"


def _suite_target_ms() -> float:
    """The measured suite's configured micro-bucket latency target."""
    sys.path.insert(0, REPO)
    from kubernetes_tpu.perf.workloads import build_workload

    return build_workload(SUITE, SIZE).latency_target_ms or 0.0


def run_arm(latency_target: str | None) -> dict:
    env = dict(os.environ)
    env.update(BENCH_SUITE=SUITE, BENCH_SIZE=SIZE, BENCH_ORACLE_SAMPLE="2")
    if latency_target is not None:
        env["BENCH_LATENCY_TARGET"] = latency_target
    else:
        env.pop("BENCH_LATENCY_TARGET", None)
    out = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=3000, check=True,
    )
    line = out.stdout.strip().splitlines()[-1]
    return json.loads(line)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=2,
                    help="passes per arm; best-throughput pass is kept "
                         "(weather moves passes; pass 1 also warms the "
                         "persistent compile cache)")
    ap.add_argument("--out", default="BENCH_r15_LATENCY.json")
    args = ap.parse_args()

    passes = {"baseline": [], "round15": []}
    for i in range(args.passes):
        passes["baseline"].append(run_arm("0"))
        passes["round15"].append(run_arm(None))
        print(f"pass {i + 1}: baseline p99="
              f"{passes['baseline'][-1]['detail']['attempt_ms']['p99']:.0f}ms"
              f" {passes['baseline'][-1]['detail']['throughput_pods_per_s']:.0f}p/s"
              f" | round15 p99="
              f"{passes['round15'][-1]['detail']['attempt_ms']['p99']:.0f}ms"
              f" {passes['round15'][-1]['detail']['throughput_pods_per_s']:.0f}p/s",
              file=sys.stderr)

    def best(arm):  # steadiest signal: the best-throughput pass of the arm
        return max(passes[arm], key=lambda d: d["detail"]["throughput_pods_per_s"])

    base, new = best("baseline")["detail"], best("round15")["detail"]
    p99_ratio = base["attempt_ms"]["p99"] / max(new["attempt_ms"]["p99"], 1e-9)
    thr_ratio = new["throughput_pods_per_s"] / max(
        base["throughput_pods_per_s"], 1e-9)

    import multiprocessing

    r09_p99 = None
    try:
        r09_p99 = json.load(open(os.path.join(REPO, "BENCH_r09_100K.json")))[
            "live_suite"]["detail"]["attempt_ms"]["p99"]
    except (OSError, KeyError, json.JSONDecodeError) as e:
        # pre-round-9 tree: the NorthStar regression budget is simply
        # omitted from the gates block
        print(f"no BENCH_r09_100K baseline ({type(e).__name__}: {e}); "
              "omitting the NorthStar gate", file=sys.stderr)
    artifact = {
        "metric": "attempt_p99_ab",
        "suite": f"{SUITE}/{SIZE}",
        "environment": {
            "backend": new.get("backend", "?"),
            "cpus": multiprocessing.cpu_count(),
            "note": "both arms in THIS container, fresh subprocess each, "
                    "interleaved passes (weather-paired)",
        },
        "baseline": base,
        "round15": new,
        "baseline_passes_p99_ms": [
            d["detail"]["attempt_ms"]["p99"] for d in passes["baseline"]],
        "round15_passes_p99_ms": [
            d["detail"]["attempt_ms"]["p99"] for d in passes["round15"]],
        "baseline_passes_pods_per_s": [
            d["detail"]["throughput_pods_per_s"] for d in passes["baseline"]],
        "round15_passes_pods_per_s": [
            d["detail"]["throughput_pods_per_s"] for d in passes["round15"]],
        "p99_reduction_x": round(p99_ratio, 2),
        "throughput_vs_baseline": round(thr_ratio, 3),
        "acceptance": {
            "p99_reduction_ge_5x": p99_ratio >= 5.0,
            "throughput_ge_0p9x": thr_ratio >= 0.9,
            "zero_inwindow_compiles":
                new["xla_compiles_in_window"]["count"] == 0,
            "phase_coverage_in_band":
                0.9 <= new["attempt_phase_latency"]["coverage"] <= 1.1,
        },
        # CI budgets (tools/run_suites.sh gate_attempt_p99): the LOOSER of
        # measured p99 × weather tolerance and the suite's configured
        # latencyTargetMs × 1.25 — the policy legitimately holds any tier
        # fitting 0.9×target, so a compliant run on slower hardware may
        # sit near the target and must not fail a budget derived from one
        # machine's measurement alone.  NorthStar: no same-hardware
        # micro-bucket A/B at 100k yet — its budget is a pure regression
        # bound on the committed BENCH_r09_100K.json measurement.
        "gates": {
            "SchedulingBasic": {
                "budget_ms": round(max(new["attempt_ms"]["p99"] * 1.5,
                                       _suite_target_ms() * 1.25), 1),
                "provenance": "max(round15 measured p99 × 1.5 weather "
                              "tolerance, suite latencyTargetMs × 1.25 — "
                              "the policy's own compliance band)",
            },
            **({"NorthStar": {
                "budget_ms": round(r09_p99 * 1.25, 1),
                "provenance": "BENCH_r09_100K.json live p99 × 1.25 — "
                              "regression bound, micro-buckets not yet "
                              "armed at the 131k tier",
            }} if r09_p99 else {}),
        },
    }
    with open(os.path.join(REPO, args.out), "w") as f:
        json.dump(artifact, f, indent=1)
        f.write("\n")
    print(json.dumps({k: artifact[k] for k in (
        "p99_reduction_x", "throughput_vs_baseline", "acceptance")}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
