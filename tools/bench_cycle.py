"""Single-job timing of the fused cycle program for a suite's pod shape.

Usage: python tools/bench_cycle.py SUITE N B S [reps]
  SUITE in {anti, spread, basic}; N nodes; B batch; S pre-scheduled init pods.

Prints dispatch→ready latency (block_until_ready) for the fused program, with
NO other jobs sharing the TPU (run alone for trustworthy numbers).
"""
import sys, time
sys.path.insert(0, ".")
import numpy as np
import jax

from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.perf.workloads import (
    node_unique_hostname, node_zoned, node_default, pod_anti_affinity,
    pod_topology_spread, pod_default, ZONES3,
)
from kubernetes_tpu.framework.runtime import coupling_flags

suite = sys.argv[1]
N = int(sys.argv[2]); B = int(sys.argv[3]); S = int(sys.argv[4])
reps = int(sys.argv[5]) if len(sys.argv) > 5 else 5

node_tmpl = {"anti": node_unique_hostname, "spread": node_zoned(ZONES3),
             "basic": node_default}[suite]
pod_tmpl = {"anti": pod_anti_affinity("sched-0"), "spread": pod_topology_spread,
            "basic": pod_default}[suite]

store = ObjectStore()
sched = TPUScheduler(store, batch_size=B)
sched.presize(N, S + 4 * B)
for i in range(N):
    store.create("Node", node_tmpl(i))
for i in range(S):
    p = pod_tmpl(100000 + i)
    p.spec.node_name = f"node-{i % N:06d}"
    store.create("Pod", p)
for i in range(B):
    store.create("Pod", pod_tmpl(i))

infos = sched.queue.pop_batch(B)
changed = sched.cache.update_snapshot(sched.snapshot)
sched.encoder.sync(sched.snapshot, changed)
batch = sched.compiler.compile([qi.pod for qi in infos], pad_to=B)
profile = "default-scheduler"
fw = sched._framework(profile)
jt = sched._jitted_by[profile]
host_auxes = fw.host_prepare(batch, sched.snapshot, sched.encoder,
                             namespace_labels=sched.namespace_labels)
dsnap, upd = sched.encoder.to_device_deferred()
nom_rows, nom_req = sched._nominated_arrays(set())
order = np.arange(batch.size, dtype=np.int32)
coupling = coupling_flags(batch)
delta = sched._noop_delta()


def once(which):
    t0 = time.perf_counter()
    if which == "greedy":
        res, *_ = jt["greedy"](batch, dsnap, upd, nom_rows, nom_req,
                               delta, host_auxes, order, None)
    else:
        res, *_ = jt["batch"](batch, dsnap, upd, nom_rows, nom_req,
                              delta, host_auxes, order, coupling, None)
    jax.block_until_ready(res.node_row)
    return time.perf_counter() - t0


for which in ("greedy", "batch"):
    once(which)  # compile
    xs = [once(which) for _ in range(reps)]
    print(f"{suite} N={N} B={B} S={S} {which}: "
          + " ".join(f"{1e3*x:.0f}" for x in xs) + " ms")

import dataclasses

def fresh_inputs():
    b2 = dataclasses.replace(
        batch, **{f.name: np.array(getattr(batch, f.name))
                  for f in dataclasses.fields(batch)
                  if isinstance(getattr(batch, f.name), np.ndarray)})
    ha = {k: ({kk: np.array(vv) for kk, vv in v.items()} if isinstance(v, dict)
              else v) for k, v in host_auxes.items()}
    return b2, ha

def once_fresh():
    b2, ha = fresh_inputs()
    t0 = time.perf_counter()
    res, *_ = jt["greedy"](b2, dsnap, upd, nom_rows, nom_req, delta, ha, order, None)
    jax.block_until_ready(res.node_row)
    return time.perf_counter() - t0

once_fresh()
print("greedy fresh-arrays+block:", " ".join(f"{1e3*once_fresh():.0f}" for _ in range(reps)), "ms")

def once_poll():
    b2, ha = fresh_inputs()
    t0 = time.perf_counter()
    res, *_ = jt["greedy"](b2, dsnap, upd, nom_rows, nom_req, delta, ha, order, None)
    d = res.node_row
    if hasattr(d, "copy_to_host_async"):
        d.copy_to_host_async()
    while hasattr(d, "is_ready") and not d.is_ready():
        time.sleep(0.002)
    np.asarray(d)
    return time.perf_counter() - t0

once_poll()
print("greedy fresh+async-poll  :", " ".join(f"{1e3*once_poll():.0f}" for _ in range(reps)), "ms")

arr = np.zeros((128, 8192), np.float32)
def put_fresh():
    a = np.array(arr)
    t0 = time.perf_counter()
    jax.block_until_ready(jax.device_put(a))
    return time.perf_counter() - t0
put_fresh()
print("device_put 4MB fresh     :", " ".join(f"{1e3*put_fresh():.0f}" for _ in range(reps)), "ms")

# chained: each dispatch consumes the previous program's committed outputs
def chained(reps):
    global dsnap
    ds = dsnap
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        res, auxes_o, ds_out, dyn_o, diag = jt["greedy"](
            batch, ds, upd, nom_rows, nom_req, delta, host_auxes, order, None)
        jax.block_until_ready(res.node_row)
        ts.append(time.perf_counter() - t0)
        ds = ds_out
    return ts

chained(2)
print("greedy chained-dsnap     :", " ".join(f"{1e3*x:.0f}" for x in chained(reps)), "ms")

# chained + fetch node_row to host (np.asarray) like _complete does
def chained_fetch(reps):
    ds = dsnap
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        res, auxes_o, ds_out, dyn_o, diag = jt["greedy"](
            batch, ds, upd, nom_rows, nom_req, delta, host_auxes, order, None)
        jax.block_until_ready(res.node_row)
        np.asarray(res.node_row)
        ts.append(time.perf_counter() - t0)
        ds = ds_out
    return ts

chained_fetch(2)
print("greedy chained+asarray   :", " ".join(f"{1e3*x:.0f}" for x in chained_fetch(reps)), "ms")

# chained with k valid pods: separates per-step scan cost from fixed chain cost
for k in (1, 32, 128):
    if k > B: continue
    b2 = dataclasses.replace(batch, valid=np.asarray(np.arange(batch.size) < k, bool))
    def chained_k(reps, b2=b2):
        ds = dsnap
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            res, auxes_o, ds_out, dyn_o, diag = jt["greedy"](
                b2, ds, upd, nom_rows, nom_req, delta, host_auxes, order, None)
            jax.block_until_ready(res.node_row)
            ts.append(time.perf_counter() - t0)
            ds = ds_out
        return ts
    chained_k(2)
    print(f"greedy chained k={k:3d}      :", " ".join(f"{1e3*x:.0f}" for x in chained_k(reps)), "ms")
