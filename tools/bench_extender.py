"""Microbench: the extender callout path — sync vs async round walk ×
name-list (nodeCacheCapable) vs full-manifest payloads.  The round-12
tentpole's extender claim in one table (the `extender_callout_bench`
section of BENCH_r12_AB.json): moving the whole round walk off the device
cycle (TPUScheduler async_extenders) and keeping payloads on the
nodeCacheCapable name-list fast path (`pkg/scheduler/extender.go:277,416`)
are each worth a measured factor on the wire-bound suite shape.

The extender runs in a SUBPROCESS, as a real extender would — the cost
measured is the scheduler-side client + wire + a realistic peer, not a
handler sharing the scheduler's GIL.

    JAX_PLATFORMS=cpu python tools/bench_extender.py [pods]

Prints one JSON object:
    {"<sync|async>_<names|manifests>": {"pods_per_s": ..,
     "extender_wait_s": .., "walk_ms_per_pod": ..}, ...}
"""

import json
import multiprocessing as mp
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubernetes_tpu.extender import (
    ExtenderConfig,
    HTTPExtender,
    run_subprocess_score_server,
    uniform_score_fn,
)
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod

N_NODES = 200
BATCH = 128


def start_server():
    ctx = mp.get_context("spawn")
    parent, child = ctx.Pipe()
    proc = ctx.Process(
        target=partial(run_subprocess_score_server, uniform_score_fn),
        args=(child,), daemon=True)
    proc.start()
    if not parent.poll(60):
        proc.terminate()
        raise RuntimeError("extender subprocess failed to start")
    return proc, parent.recv()


def run_one(port: int, n_pods: int, async_walk: bool, capable: bool):
    store = ObjectStore()
    ext = HTTPExtender(ExtenderConfig(
        url_prefix=f"http://127.0.0.1:{port}", filter_verb="filter",
        prioritize_verb="prioritize", weight=1,
        node_cache_capable=capable,
    ))
    sched = TPUScheduler(store, batch_size=BATCH, pipeline=True,
                         extenders=[ext], async_extenders=async_walk)
    sched.presize(N_NODES, n_pods + 8)
    for i in range(N_NODES):
        store.create("Node", make_node().name(f"node-{i:05d}")
                     .capacity({"cpu": "32", "memory": "64Gi", "pods": "110"})
                     .obj())
    # warm: compile the fused extender programs outside the window
    for i in range(4):
        store.create("Pod", make_pod().name(f"warm-{i}").uid(f"warm-{i}")
                     .namespace("default").req({"cpu": "1m"}).obj())
    sched.run_until_idle()
    for i in range(n_pods):
        store.create("Pod", make_pod().name(f"p-{i:05d}").uid(f"p-{i:05d}")
                     .namespace("default")
                     .req({"cpu": "100m", "memory": "100Mi"}).obj())
    wait0 = sched.phase_wall["extender_wait"]
    t0 = time.perf_counter()
    sched.run_until_idle()
    wall = time.perf_counter() - t0
    wait = sched.phase_wall["extender_wait"] - wait0
    pods, _ = store.list("Pod")
    bound = sum(1 for p in pods if p.spec.node_name
                and p.metadata.name.startswith("p-"))
    sched.close()
    ext.close()
    assert bound == n_pods, f"only {bound}/{n_pods} bound"
    return {
        "pods_per_s": round(n_pods / wall, 1),
        "extender_wait_s": round(wait, 3),
        "walk_ms_per_pod": round(1000.0 * wait / n_pods, 3),
    }


def main(n_pods: int = 256) -> dict:
    proc, port = start_server()
    out = {}
    try:
        for async_walk in (False, True):
            for capable in (True, False):
                key = (("async" if async_walk else "sync") + "_"
                       + ("names" if capable else "manifests"))
                out[key] = run_one(port, n_pods, async_walk, capable)
    finally:
        proc.terminate()
        proc.join(timeout=5)
    return out


if __name__ == "__main__":
    pods = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    print(json.dumps(main(pods)))
