#!/usr/bin/env python
"""Thousand-watcher churn soak (ISSUE 11 acceptance; run_suites.sh gate).

Drives chaos/flood.watch_churn_soak at the acceptance shape — 1000
concurrent watchers on one WatchCache, object count grown 10× mid-soak —
and asserts the three scale properties (encode-once added round 19):

  - zero store-lock acquisitions on the list/watch-replay path
    (ObjectStore.read_ops delta over the whole soak);
  - resync cost flat across the 10× growth (a dropped watcher resumes by
    ring replay of its bounded gap, never an O(objects) relist):
    ratio < 3, with the absolute numbers printed for the record;
  - encode-once fan-out: every watcher pulls each event's serialized
    bytes, yet the soak costs ~1 json encode per event (the watch cache
    stamps one EncodedPayload per object version — api/wire.py).

No jax: pure control-plane layers, runs in seconds.  The smaller tier-1
shape lives in tests/test_watchcache.py; the slow-marked test runs this
exact configuration.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubernetes_tpu.chaos.flood import watch_churn_soak  # noqa: E402


def main() -> int:
    result = watch_churn_soak(
        n_watchers=int(os.environ.get("SOAK_WATCHERS", "1000")),
        n_objects=int(os.environ.get("SOAK_OBJECTS", "200")),
        growth=10, churn_rounds=2, resyncs=50)
    ok = (result["store_read_ops_delta"] == 0
          and result["watchers_complete"] == result["n_watchers"]
          and result["resync_ratio"] < 3.0
          # encode-once (round 19): the whole thousand-watcher fan-out
          # costs ~1 json encode per event, never ~n_watchers
          and result["encodes_per_event"] <= 1.5)
    result["watch_soak"] = "PASS" if ok else "FAIL"
    print(json.dumps(result))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
