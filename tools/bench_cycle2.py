"""Drive real schedule_cycle()s in isolation; print per-cycle phase splits.

Usage: python tools/bench_cycle2.py SUITE N B S PENDING [cycles]
"""
import sys, time
sys.path.insert(0, ".")
import numpy as np
import jax

from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.perf.workloads import (
    node_unique_hostname, node_zoned, node_default, pod_anti_affinity,
    pod_topology_spread, pod_default, ZONES3,
)

suite = sys.argv[1]
N = int(sys.argv[2]); B = int(sys.argv[3]); S = int(sys.argv[4])
PEND = int(sys.argv[5]); CYC = int(sys.argv[6]) if len(sys.argv) > 6 else 12

node_tmpl = {"anti": node_unique_hostname, "spread": node_zoned(ZONES3),
             "basic": node_default}[suite]
pod_tmpl = {"anti": pod_anti_affinity("sched-1"), "spread": pod_topology_spread,
            "basic": pod_default}[suite]

store = ObjectStore()
sched = TPUScheduler(store, batch_size=B, pipeline=True)
sched.presize(N, S + PEND + 64)
for i in range(N):
    store.create("Node", node_tmpl(i))
init_tmpl = {"anti": pod_anti_affinity("sched-0"), "spread": pod_default,
             "basic": pod_default}[suite]
for i in range(S):
    p = init_tmpl(100000 + i)
    p.spec.node_name = f"node-{i % N:06d}"
    store.create("Pod", p)
for i in range(PEND):
    store.create("Pod", pod_tmpl(i))

# instrument _complete's block vs asarray
orig_complete = TPUScheduler._complete
SPLITS = []

def patched_complete(self, fl):
    t0 = time.perf_counter()
    jax.block_until_ready(fl.node_row_dev)
    t_block = time.perf_counter() - t0
    out = orig_complete(self, fl)
    SPLITS.append((t_block, time.perf_counter() - t0 - t_block))
    return out

TPUScheduler._complete = patched_complete

print("cycle  total_ms  block_ms  rest_complete_ms  sched")
for c in range(CYC):
    t0 = time.perf_counter()
    stats = sched.schedule_cycle()
    dt = time.perf_counter() - t0
    blk, rest = SPLITS[-1] if SPLITS and stats.attempted else (0.0, 0.0)
    print(f"{c:5d} {1e3*dt:9.1f} {1e3*blk:9.1f} {1e3*rest:17.1f}  {stats.scheduled}")

