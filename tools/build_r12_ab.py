"""Assemble BENCH_r12_AB.json from paired baseline/round-12 bench JSONL runs.

Usage:
    AB_SCALES='{"Suite/Size": 0.4, ...}' \
    AB_EXT_BENCH=ext_bench.json \
    python tools/build_r12_ab.py BASE_FILE:NEW_FILE [BASE2:NEW2 ...]

Each file holds one bench.py JSON line per suite pass; rows are paired by
workload name with the MEDIAN pass per arm and the full pass band kept
(VERDICT r5 weak #5: commit the band, not the best window).  AB_EXT_BENCH
optionally embeds a tools/bench_extender.py result as the
``extender_callout_bench`` section.  The output drives the COMPONENTS.md
round-12 A/B table via tools/render_perf_docs.py (generate, don't
transcribe).
"""

from __future__ import annotations

import json
import os
import sys

from build_r6_ab import load_rows, median_pass, subset  # same pairing rules


def main(argv):
    import multiprocessing

    scales = json.loads(os.environ.get("AB_SCALES", "{}"))
    rows = []
    for pair in argv[1:]:
        base_p, new_p = pair.split(":")
        base, new = load_rows(base_p), load_rows(new_p)
        for suite in new:
            if suite not in base:
                continue
            b = median_pass(base[suite])
            n = median_pass(new[suite])
            rows.append({
                "suite": suite,
                "scale": scales.get(suite, 1.0),
                "baseline": subset(b),
                "round12": subset(n),
                "baseline_passes_pods_per_s": sorted(
                    p["throughput_pods_per_s"] for p in base[suite]),
                "round12_passes_pods_per_s": sorted(
                    p["throughput_pods_per_s"] for p in new[suite]),
                "speedup": round(
                    n["throughput_pods_per_s"]
                    / max(b["throughput_pods_per_s"], 1e-9), 3),
            })
    rows.sort(key=lambda r: r["suite"])
    artifact = {
        "environment": {
            "backend": "cpu",
            "cpus": multiprocessing.cpu_count(),
            "note": (
                "no TPU in this round's container; both arms (pre-round-12 "
                "git worktree vs this build) ran at the scales below on the "
                "SAME machine — the acceptance ratio is the same-hardware "
                "1.5× CPU stand-in, per the round-6 precedent; the "
                "≥1.0 vs_go_envelope_throughput clause applies on "
                "TPU-class hardware only"),
        },
        "scale_note": (
            "Affinity suites at scale 0.4 / batch 64 (multi-batch windows; "
            "5k shapes OOM the CPU backend), SchedulingExtender at its "
            "full 500-node size.  Both arms measured with identical env "
            "(BENCH_SCALE/BENCH_BATCH/BENCH_ORACLE_*)."),
        "rows": rows,
    }
    ext = os.environ.get("AB_EXT_BENCH")
    if ext:
        with open(ext) as f:
            artifact["extender_callout_bench"] = json.load(f)
        artifact["extender_callout_note"] = (
            "tools/bench_extender.py: 256 pods through a subprocess "
            "extender — async round walk × nodeCacheCapable name-list vs "
            "full-manifest ExtenderArgs payloads (extender.go:277,416)")
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_r12_AB.json")
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {out} ({len(rows)} rows)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
