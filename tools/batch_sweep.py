"""Batch-size frontier sweep (VERDICT r4 #8): run the NorthStar workload at
B ∈ {16, 64, 128, 256, 512, 1024}, record throughput + attempt quantiles per
point, write BATCH_SWEEP.json.  Turns the "per-attempt p99 is a batch-design
trade" prose into data: the artifact shows which operating point a
latency-sensitive profile would pick and what throughput it costs.

Runs bench.py per point in a subprocess (fresh program cache state per B;
the persistent compile cache makes repeats warm).  Run ALONE on the TPU —
a concurrent bench makes both runs' numbers garbage.

Usage: python tools/batch_sweep.py [out.json]
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BATCHES = [16, 64, 128, 256, 512, 1024]


def run_point(batch: int) -> dict:
    env = dict(os.environ, BENCH_BATCH=str(batch),
               BENCH_SUITE="NorthStar", BENCH_SIZE="5000Nodes/10000Pods")
    proc = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=1200,
    )
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    try:
        d = json.loads(line)
    except json.JSONDecodeError:
        return {"batch": batch, "error": proc.stderr[-500:]}
    dd = d["detail"]
    return {
        "batch": batch,
        "throughput_pods_per_s": dd["throughput_pods_per_s"],
        "attempt_ms": dd["attempt_ms"],
        "xla_compiles_in_window": dd["xla_compiles_in_window"],
        "vs_go_envelope_throughput":
            dd["go_envelope"]["vs_go_envelope_throughput"],
        "go_envelope_sampled_pods_per_s":
            dd["go_envelope"]["sampled"]["throughput_pods_per_s"],
    }


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        REPO, "BATCH_SWEEP.json")
    points = []
    for b in BATCHES:
        print(f"sweep: B={b} ...", flush=True)
        p = run_point(b)
        points.append(p)
        print(f"  -> {p.get('throughput_pods_per_s', p.get('error'))} pods/s, "
              f"p99 {p.get('attempt_ms', {}).get('p99')} ms", flush=True)
    artifact = {
        "workload": "NorthStar/5000Nodes/10000Pods",
        "note": (
            "one pass per point on the tunnel-attached chip; weather moves "
            "numbers ±2x between points — read the SHAPE (throughput rises "
            "with B until the latency knee), not single-point deltas"
        ),
        "points": points,
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
