"""Decompose the anti-affinity cycle's device cost at 5k nodes.

Times (post-warmup, blocked):
  prepare-only program        — plugin prepare planes (IPA matmuls etc.)
  full fused greedy program   — prepare + 128-step scan
  batch engine (auction)      — prepare + round-based program
"""
import sys, time
sys.path.insert(0, ".")
import numpy as np
import jax

from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.perf.workloads import node_unique_hostname, pod_anti_affinity
from kubernetes_tpu.framework.runtime import initial_dynamic_state, coupling_flags

N = int(sys.argv[1]) if len(sys.argv) > 1 else 5000
B = int(sys.argv[2]) if len(sys.argv) > 2 else 128
S = int(sys.argv[3]) if len(sys.argv) > 3 else 0  # pre-scheduled anti-affinity pods

store = ObjectStore()
sched = TPUScheduler(store, batch_size=B)
sched.presize(N, S + 4 * B)
for i in range(N):
    store.create("Node", node_unique_hostname(i))
tmpl = pod_anti_affinity("sched-0")
for i in range(S):
    p = tmpl(100000 + i)
    p.spec.node_name = f"node-{i % N:06d}"
    store.create("Pod", p)
pods = []
for i in range(B):
    p = tmpl(i)
    store.create("Pod", p)
    pods.append(p)

infos = sched.queue.pop_batch(B)
assert len(infos) == B
changed = sched.cache.update_snapshot(sched.snapshot)
sched.encoder.sync(sched.snapshot, changed)
batch = sched.compiler.compile([qi.pod for qi in infos], pad_to=B)
profile = "default-scheduler"
fw = sched._framework(profile)
jt = sched._jitted_by[profile]
host_auxes = fw.host_prepare(batch, sched.snapshot, sched.encoder,
                             namespace_labels=sched.namespace_labels)
dsnap, upd = sched.encoder.to_device_deferred()
nom_rows, nom_req = sched._nominated_arrays(set())
order = np.arange(batch.size, dtype=np.int32)
coupling = coupling_flags(batch)
delta = sched._noop_delta()


def timeit(label, fn, n=3):
    fn()  # warm (compile)
    jax.block_until_ready(fn())
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{label:36s} {1e3*dt:9.1f} ms")
    return dt


prep = jax.jit(lambda b, s, d, h: fw.prepare(b, s, initial_dynamic_state(s), h))
timeit("prepare only", lambda: prep(batch, dsnap, nom_rows * 0, host_auxes) if False else prep(batch, dsnap, None, host_auxes))

timeit("fused greedy (prepare+scan)", lambda: jt["greedy"](
    batch, dsnap, upd, nom_rows, nom_req, delta, host_auxes, order, None))

timeit("fused batch (prepare+auction)", lambda: jt["batch"](
    batch, dsnap, upd, nom_rows, nom_req, delta, host_auxes, order, coupling, None))

# scan with only K valid pods: reveals per-step cost
for k in (1, 8, 32):
    import dataclasses
    b2 = dataclasses.replace(batch, valid=np.asarray(
        np.arange(batch.size) < k, dtype=bool))
    timeit(f"fused greedy ({k} valid pods)", lambda b2=b2: jt["greedy"](
        b2, dsnap, upd, nom_rows, nom_req, delta, host_auxes, order, None))

# fresh-array variant: copies of host_auxes/batch each call (suite conditions —
# every cycle builds new numpy arrays, defeating jax's transfer cache)
import copy

def fresh_call():
    ha = {k: {kk: np.array(vv) for kk, vv in v.items()} if isinstance(v, dict)
          else v for k, v in host_auxes.items()}
    return jt["greedy"](batch, dsnap, upd, nom_rows, nom_req, delta, ha, order, None)

timeit("fused greedy (fresh host_auxes)", fresh_call)

import dataclasses
def fresh_batch_call():
    b2 = dataclasses.replace(
        batch, **{f.name: (np.array(getattr(batch, f.name))
                           if isinstance(getattr(batch, f.name), np.ndarray) else getattr(batch, f.name))
                  for f in dataclasses.fields(batch)
                  if isinstance(getattr(batch, f.name), np.ndarray)})
    return jt["greedy"](b2, dsnap, upd, nom_rows, nom_req, delta, host_auxes, order, None)

timeit("fused greedy (fresh batch arrays)", fresh_batch_call)

def fresh_both():
    ha = {k: {kk: np.array(vv) for kk, vv in v.items()} if isinstance(v, dict)
          else v for k, v in host_auxes.items()}
    b2 = dataclasses.replace(
        batch, **{f.name: np.array(getattr(batch, f.name))
                  for f in dataclasses.fields(batch)
                  if isinstance(getattr(batch, f.name), np.ndarray)})
    return jt["greedy"](b2, dsnap, upd, nom_rows, nom_req, delta, ha, order, None)

timeit("fused greedy (fresh both)", fresh_both)

# _complete-style fetch: dispatch, then poll is_ready + np.asarray
def fetch_style():
    res, auxes_o, dsnap_o, dyn_o, diag = jt["greedy"](
        batch, dsnap, upd, nom_rows, nom_req, delta, host_auxes, order, None)
    if hasattr(res.node_row, "copy_to_host_async"):
        res.node_row.copy_to_host_async()
    t0 = time.perf_counter()
    dev = res.node_row
    if hasattr(dev, "is_ready"):
        while not dev.is_ready():
            time.sleep(0.002)
    t_ready = time.perf_counter() - t0
    nr = np.asarray(dev)
    t_fetch = time.perf_counter() - t0 - t_ready
    return t_ready, t_fetch

fetch_style()
rs = [fetch_style() for _ in range(5)]
print("ready_ms", [round(1e3*a, 1) for a, b in rs])
print("fetch_ms", [round(1e3*b, 1) for a, b in rs])

# and a full cycle as the scheduler does it (dispatch k, complete k)
def cycle_like():
    t0 = time.perf_counter()
    res, auxes_o, dsnap_o, dyn_o, diag = jt["greedy"](
        batch, dsnap, upd, nom_rows, nom_req, delta, host_auxes, order, None)
    if hasattr(res.node_row, "copy_to_host_async"):
        res.node_row.copy_to_host_async()
    dev = res.node_row
    while hasattr(dev, "is_ready") and not dev.is_ready():
        time.sleep(0.002)
    nr = np.asarray(dev)
    return time.perf_counter() - t0

cycle_like()
print("cycle_ms", [round(1e3*cycle_like(), 1) for _ in range(5)])

import jax as _jax

def variant(label, finish):
    def one():
        res, *_ = jt["greedy"](
            batch, dsnap, upd, nom_rows, nom_req, delta, host_auxes, order, None)
        t0 = time.perf_counter()
        out = finish(res.node_row)
        return time.perf_counter() - t0
    one()
    print(label, [round(1e3*one(), 1) for _ in range(5)])

variant("block_then_asarray", lambda d: np.asarray(_jax.block_until_ready(d)))
variant("asarray_direct     ", lambda d: np.asarray(d))

def f3(d):
    d.copy_to_host_async()
    return np.asarray(d)
variant("async_then_asarray ", f3)

def f4(d):
    d.copy_to_host_async()
    while not d.is_ready():
        time.sleep(0.002)
    return np.asarray(d)
variant("async_poll_asarray ", f4)

def f5(d):
    while not d.is_ready():
        time.sleep(0.002)
    return np.asarray(d)
variant("poll_no_async      ", f5)
