#!/bin/bash
# Final artifact pass: every 5k suite + extender + density, one JSON line each.
# Writes suites_5k.out (the judge artifact) and density.json.  A failing or
# timed-out suite writes an explicit error marker line instead of silently
# vanishing, and the script exits non-zero if anything failed.
cd "$(dirname "$0")/.."
set -u
OUT=suites_5k.out
FAILED=0
: > "$OUT"
# static invariant gates first: new analyzer violations abort the whole
# pass before any expensive suite runs — a failure here is conclusive in
# seconds, so don't burn hours of 5k-node suites on a known-bad tree.
# Gate 1 is the DIFF-scoped run (files changed vs the merge base — the
# pre-commit-speed signal, and the one that names your own change);
# gate 2 is the authoritative full-tree ratchet (zero-finding baseline),
# the same one tier-1 enforces via tests/test_static_analysis.py.
python tools/analyze.py --diff origin/main --check all > /dev/null || { echo "FAILED: static analysis diff gate" >> suites_run.log; exit 1; }
python tools/analyze.py --check all > /dev/null || { echo "FAILED: static analysis gate" >> suites_run.log; exit 1; }
# thread-ownership gate: the four concurrency checks (thread-ownership,
# handoff-discipline, thread-local-context, daemon-lifecycle) are part of
# `--check all` above; the NAMED invocation keeps them conclusive even if
# someone narrows the gate list, and archives the ownership role map the
# runtime access sanitizer verifies against
python tools/analyze.py --check thread-ownership,handoff-discipline,thread-local-context,daemon-lifecycle > /dev/null \
  || { echo "FAILED: thread analysis gate" >> suites_run.log; exit 1; }
python tools/analyze.py --report-ownership > thread_ownership_report.json \
  || { echo "FAILED: thread ownership report" >> suites_run.log; exit 1; }
# gang-subsystem gate: the coscheduling battery (all-or-nothing, Permit
# holds, timeout requeue, CLI) is cheap and conclusive — fail fast before
# the expensive suites, same rationale as the analyzer gate above
JAX_PLATFORMS=cpu timeout 600 python -m pytest tests/test_gang.py tests/test_permit.py -q \
  || { echo "FAILED: gang test gate" >> suites_run.log; exit 1; }
# DRA gate: the named-claim battery (exactly-once CAS allocation, gang
# all-or-nothing co-allocation, whatif claim-plane parity, chaos storms,
# mid-commit crash recovery) — the DeviceClaimGang suite below is
# meaningless if claim allocation double-books, leaks, or diverges from
# the sequential path, so fail fast before any expensive suite runs
JAX_PLATFORMS=cpu timeout 900 python -m pytest tests/test_dra.py -q -m 'not slow' \
  || { echo "FAILED: DRA test gate" >> suites_run.log; exit 1; }
# descheduler gate: the eviction-API + planner-parity + disruption battery
# is cheap and conclusive — the Defrag suite below is meaningless if the
# planner's predictions or the PDB gate are broken
JAX_PLATFORMS=cpu timeout 600 python -m pytest tests/test_descheduler.py tests/test_disruption.py -q \
  || { echo "FAILED: descheduler test gate" >> suites_run.log; exit 1; }
# autoscaler gate: the whatif engine parity battery (vmapped K-fork ==
# sequential, victim/node-add/node-remove forks) + the autoscaler e2e/chaos
# battery — the AutoscaleGang suite below is meaningless if the engine's
# predictions or the scale decisions are broken
JAX_PLATFORMS=cpu timeout 600 python -m pytest tests/test_whatif.py tests/test_autoscaler.py -q \
  || { echo "FAILED: autoscaler test gate" >> suites_run.log; exit 1; }
# WAL crash-survival gate: a REAL kill -9 of a subprocess mid-bind (clean
# and torn-tail variants) followed by replay_on_boot — exactly-once binds,
# replayed store bit-identical to a never-crashed replica's.  Runs in ~2s
# with no jax; a control plane that loses acknowledged binds on process
# death makes every perf number below meaningless, so fail first.
timeout 300 python tools/wal_crash_gate.py \
  || { echo "FAILED: WAL crash-survival gate" >> suites_run.log; exit 1; }
# control-plane durability/flow gate: the WAL + watch-cache + flow-control
# batteries (torn tails, rv-consistent pagination, 410 relists, reader
# floods) — cheap and conclusive before the suites
JAX_PLATFORMS=cpu timeout 900 python -m pytest \
  tests/test_wal.py tests/test_watchcache.py tests/test_flowcontrol.py \
  -q -m 'not slow' \
  || { echo "FAILED: control-plane test gate" >> suites_run.log; exit 1; }
# wire-codec parity gate (round 19): the binary wire plane carries every
# list/watch/WAL byte the suites below produce — a codec that diverges
# from JSON by one field would corrupt stores silently, so pin round-trip
# parity for every registered kind on BOTH backends (native C extension
# and the KTPU_NO_NATIVE pure-Python fallback) before anything expensive
JAX_PLATFORMS=cpu timeout 600 python -m pytest tests/test_wire.py -q \
  || { echo "FAILED: wire codec parity gate" >> suites_run.log; exit 1; }
JAX_PLATFORMS=cpu KTPU_NO_NATIVE=1 timeout 600 python -m pytest tests/test_wire.py -q \
  || { echo "FAILED: wire pure-python parity gate" >> suites_run.log; exit 1; }
# wire bench: the committed 10x per-event codec win and the encode-once
# fanout property (1000 watchers, ~1 uncached encode per codec per event)
# re-proven on THIS tree -> BENCH_r19_WIRE.json
timeout 900 python tools/bench_wire.py \
  || { echo "FAILED: wire bench gate" >> suites_run.log; exit 1; }
# thousand-watcher churn soak: relist cost must stay FLAT across a 10x
# object-count growth and the list/watch-replay path must take zero
# store-lock reads (the "millions of users" control-plane property)
timeout 600 python tools/watch_soak.py \
  || { echo "FAILED: watch soak gate" >> suites_run.log; exit 1; }
# node-storm gate (round 13): the partition-tolerant lifecycle battery
# (zone states, tolerationSeconds taint manager, gang repair, the fast
# storm shape) followed by the 3-zone × 100-node acceptance soak with a
# same-seed determinism replay — an eviction storm that deletes a dark
# zone's workloads (or rebinds a gang twice) invalidates every suite below
JAX_PLATFORMS=cpu timeout 600 python -m pytest tests/test_node_lifecycle.py -q -m 'not slow' \
  || { echo "FAILED: node lifecycle test gate" >> suites_run.log; exit 1; }
JAX_PLATFORMS=cpu timeout 900 python tools/node_storm_soak.py \
  || { echo "FAILED: node storm soak gate" >> suites_run.log; exit 1; }
# crash-restart gate: the kill-point battery + cold-start reconstruction +
# the fast failover soak (leader killed at every registered crash point,
# exactly-once binding, zero unrepaired drift) — perf numbers from a tree
# whose recovery layer is broken would ship an un-survivable scheduler, so
# fail fast here; the full 500-pod soak runs behind the slow marker
JAX_PLATFORMS=cpu timeout 900 python -m pytest tests/test_recovery.py -q -m 'not slow' \
  || { echo "FAILED: recovery test gate" >> suites_run.log; exit 1; }
# replication gate (round 16): the two-follower WAL-shipping soak at every
# leader-kill boundary (shipped/unshipped/torn, 1000 recording watchers)
# plus a same-seed determinism replay — a follower that loses or
# double-delivers an event, overclaims a bookmark, or promotes without the
# fence would poison every read-scaling claim, so fail fast here; the fast
# unit battery rides tier-1 (tests/test_replication.py)
JAX_PLATFORMS=cpu timeout 900 python tools/replica_soak.py \
  || { echo "FAILED: replication soak gate" >> suites_run.log; exit 1; }
# sharding-parity gate: the node-sharded live runtime and the
# identity-class dedup path (round 9) must bind bit-for-bit with the
# unsharded/full paths — perf rows from a diverging program would be
# measuring a different scheduler, so fail fast before any suite runs
JAX_PLATFORMS=cpu timeout 900 python -m pytest \
  tests/test_sharding.py tests/test_sharding_runtime.py -q -m 'not slow' \
  || { echo "FAILED: sharding parity gate" >> suites_run.log; exit 1; }
# affinity-dedup parity gate (round 12): the coupled suites below now run
# the [C, N] dedup engine with class-level round updates and the
# parallel-safe auction relaxation — their rows are meaningless unless
# dedup == full path and chained/async == sync bindings hold bit-for-bit
JAX_PLATFORMS=cpu timeout 1200 python -m pytest \
  tests/test_batch_assign.py tests/test_deep_pipeline.py -q -m 'not slow' \
  || { echo "FAILED: affinity-dedup parity gate" >> suites_run.log; exit 1; }
# multi-tenant API gate (round 20): dynamic CRD kinds must ride the SAME
# serving paths as built-ins (CRUD+watch+pagination over real HTTP in both
# codecs, WAL replay minting kinds before CRs decode, crash/storm exactly-
# once registration) and the RBAC door must hold (401 before 403 before
# admission, bootstrap envelopes per controller) — the TrainingJobFlow
# suite below is meaningless if a tenant kind can ghost or a spoofed
# identity can write
JAX_PLATFORMS=cpu timeout 900 python -m pytest \
  tests/test_apiextensions.py tests/test_rbac.py -q -m 'not slow' \
  || { echo "FAILED: multi-tenant API gate" >> suites_run.log; exit 1; }
# tracer-overhead gate (round 14): the span tracer rides every suite below
# (the per-phase attempt-latency blocks come from it) — a disabled-tracer
# footprint >= 1% of per-pod cost would mean the observability tax leaked
# into the production path, so prove it cheap BEFORE measuring anything
JAX_PLATFORMS=cpu timeout 900 python tools/bench_trace_overhead.py > BENCH_r14_TRACE_OVERHEAD.json \
  || { echo "FAILED: tracer overhead gate" >> suites_run.log; exit 1; }
# every suite run below writes a Perfetto-loadable Chrome-trace JSONL
# artifact (harness ChromeTraceExporter) next to its bench row
export KTPU_TRACE_DIR=trace_artifacts
run() {
  local suite="$1" size="$2" line
  echo "=== $suite/$size $(date +%H:%M:%S) ===" >> suites_run.log
  line=$(BENCH_SUITE="$suite" BENCH_SIZE="$size" BENCH_ORACLE_SAMPLE=4 \
    timeout 3000 python bench.py 2>> suites_run.log | tail -1)
  if [ -z "$line" ] || ! python -c "import json,sys; json.loads(sys.argv[1])" "$line" 2>/dev/null; then
    echo "{\"error\": \"suite $suite/$size failed or timed out\"}" >> "$OUT"
    echo "FAILED: $suite/$size" >> suites_run.log
    FAILED=1
  else
    echo "$line" >> "$OUT"
  fi
}

# fail-fast compile gate for the coupled-affinity suites: their round-6 wins
# (incremental device-resident affinity tables + affinity deep-chaining) are
# only real at xla_compiles_in_window == 0 — a stray in-window compile means
# a program variant escaped the warmups and the whole pass's numbers for
# that suite are compile-tainted, so abort the pass loudly instead of
# committing a poisoned artifact
gate_zero_compiles() {
  local suite="$1" line
  line=$(grep "\"workload\": \"$suite/" "$OUT" | tail -1)
  if [ -z "$line" ]; then
    echo "FAILED: compile gate found no row for $suite" >> suites_run.log
    exit 1
  fi
  python - "$line" <<'PYEOF' || { echo "FAILED: $suite in-window compiles != 0" >> suites_run.log; exit 1; }
import json, sys
d = json.loads(sys.argv[1])
n = d["detail"]["xla_compiles_in_window"]["count"]
sys.exit(0 if n == 0 else 1)
PYEOF
}
# attempt-p99 latency gate (round 15): the suite's fresh row must keep
# attempt p99 under the budget committed in BENCH_r15_LATENCY.json
# ("gates": suite → budget_ms, each with provenance + tolerance baked in)
# — the micro-bucket + overlapped-sync win is held by CI, not re-argued
gate_attempt_p99() {
  local suite="$1" line
  line=$(grep "\"workload\": \"$suite/" "$OUT" | tail -1)
  if [ -z "$line" ]; then
    echo "FAILED: p99 gate found no row for $suite" >> suites_run.log
    exit 1
  fi
  python - "$suite" "$line" <<'PYEOF' || { echo "FAILED: $suite attempt p99 over budget" >> suites_run.log; exit 1; }
import json, sys
suite, line = sys.argv[1], sys.argv[2]
budgets = json.load(open("BENCH_r15_LATENCY.json")).get("gates", {})
budget = budgets.get(suite)
assert budget, f"no p99 budget for {suite} in BENCH_r15_LATENCY.json"
p99 = json.loads(line)["detail"]["attempt_ms"]["p99"]
assert p99 <= budget["budget_ms"], (
    f"{suite} attempt p99 {p99:.1f} ms over budget {budget['budget_ms']} ms "
    f"({budget.get('provenance', '')})")
sys.exit(0)
PYEOF
}
# span-observatory gate: each gated suite's bench row must carry the
# per-phase attempt-latency block reconstructed from spans — with the sum
# of tiling-phase p50s within 10% of the measured attempt p50 (no
# unattributed wall-clock) — and a non-empty Perfetto artifact on disk
gate_phase_block() {
  local suite="$1" line
  line=$(grep "\"workload\": \"$suite/" "$OUT" | tail -1)
  if [ -z "$line" ]; then
    echo "FAILED: phase gate found no row for $suite" >> suites_run.log
    exit 1
  fi
  python - "$line" <<'PYEOF' || { echo "FAILED: $suite attempt-phase block/trace artifact" >> suites_run.log; exit 1; }
import json, os, sys
d = json.loads(sys.argv[1])
apl = d["detail"].get("attempt_phase_latency") or {}
phases = apl.get("phases_ms") or {}
assert apl.get("records", 0) > 0, "no per-pod span records"
for ph in ("dispatch", "device", "bind"):
    q = phases.get(ph) or {}
    assert all(k in q for k in ("p50", "p90", "p99")), f"missing {ph} quantiles"
cov = apl.get("coverage", 0.0)
assert 0.9 <= cov <= 1.1, f"phase-sum coverage {cov} outside 10% of attempt p50"
art = apl.get("trace_artifact", "")
assert art and os.path.getsize(art) > 0, f"missing/empty trace artifact {art!r}"
sys.exit(0)
PYEOF
}
run SchedulingBasic 5000Nodes
gate_phase_block SchedulingBasic
gate_attempt_p99 SchedulingBasic
gate_zero_compiles SchedulingBasic
run SchedulingPodAntiAffinity 5000Nodes
gate_zero_compiles SchedulingPodAntiAffinity
gate_phase_block SchedulingPodAntiAffinity
run SchedulingPodAffinity 5000Nodes
gate_zero_compiles SchedulingPodAffinity
run TopologySpreading 5000Nodes
run PreferredTopologySpreading 5000Nodes
run SchedulingNodeAffinity 5000Nodes
run SchedulingPreferredPodAffinity 5000Nodes
gate_zero_compiles SchedulingPreferredPodAffinity
run Unschedulable 5000Nodes/200InitPods
run SchedulingWithMixedChurn 5000Nodes
run PreemptionBasic 5000Nodes
run GangBasic 5000Nodes
# named-device claims riding the gang path: the claim planes must stay
# inside the warm program variants (the warm-pool singleton gangs warm the
# gang+claim shape pre-window), so hold the suite to zero in-window
# compiles like the other coupled suites
run DeviceClaimGang 5000Nodes
gate_zero_compiles DeviceClaimGang
# TrainingJob custom workload (round 20): a tenant-defined CR expanded by
# a controller into PodGroup + members + claims, gang-scheduled through
# the identical warm path — the driven-pod window must stay compile-free
# exactly like DeviceClaimGang above (the CR plane adds zero jit shapes)
run TrainingJobFlow 5000Nodes
gate_zero_compiles TrainingJobFlow
run StatefulChurn 5000Nodes
run VolumeZoneSpread 5000Nodes
run Defrag 5000Nodes
run AutoscaleGang 5000Nodes
run SchedulingExtender 500Nodes
# the async-extender round walk (round 12) is only a win at zero in-window
# compiles — same discipline as the affinity suites above
gate_zero_compiles SchedulingExtender
gate_phase_block SchedulingExtender
# no-extender comparison point at the same shape
run SchedulingBasic 500Nodes
# the production-scale row (ROADMAP item 1): 100,352 nodes scheduled LIVE
# end to end; the zero-compile gate holds it to the same warm discipline
# as the 5k table — an in-window compile at a 131k-node tier is minutes
# of stall and taints the whole row
run NorthStar 100kNodes
gate_zero_compiles NorthStar
gate_phase_block NorthStar
gate_attempt_p99 NorthStar
dline=$(BENCH_SUITE=Density BENCH_SIZE=1000Nodes/30000Pods BENCH_ORACLE_SAMPLE=4 \
  timeout 3000 python bench.py 2>> suites_run.log | tail -1)
if [ -n "$dline" ] && python -c "import json,sys; json.loads(sys.argv[1])" "$dline" 2>/dev/null; then
  echo "$dline" > density.json
else
  echo "FAILED: Density" >> suites_run.log
  FAILED=1
fi
# re-render the doc tables FROM the fresh artifacts (generate, don't
# transcribe): no doc may cite a number its artifact doesn't contain
python tools/render_perf_docs.py || FAILED=1
echo "ALL DONE (failed=$FAILED) $(date +%H:%M:%S)" >> suites_run.log
exit $FAILED
