#!/usr/bin/env python
"""BENCH_r19_WIRE.json: binary wire codec vs JSON, per-event, plus the
thousand-watcher encode-once soak (ISSUE 19 acceptance; run_suites.sh gate).

Two measurements:

  codec — per-event encode+decode cost of realistic pod and node payloads
    (the shapes the watch plane actually moves: multi-container pods with
    resources/ports/conditions, nodes with images/conditions/taints) through
    both codecs.  Multi-pass; the committed number is the MEDIAN ratio with
    the min..max band riding along so weather is visible.  Acceptance:
    >= 10x on pod AND node.

  fanout — 1000 watchers on one WatchCache, a burst of writes, and the
    apiserver_wire_encode_total{codec,cached="false"} delta per event.
    Encode-once means the delta is ~1 per codec per event (every watcher
    serves the SAME EncodedPayload bytes), not ~n_watchers.

No jax: pure control-plane layers, runs in seconds.

Usage: python tools/bench_wire.py [--passes N] [--reps N] [--watchers N]
       [--out FILE]
"""

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubernetes_tpu.api import objects as v1  # noqa: E402
from kubernetes_tpu.api import wire  # noqa: E402
from kubernetes_tpu.api.scheme import default_scheme  # noqa: E402
from kubernetes_tpu.api.serialize import to_manifest  # noqa: E402
from kubernetes_tpu.metrics import scheduler_metrics as m  # noqa: E402
from kubernetes_tpu.sim.store import ObjectStore  # noqa: E402
from kubernetes_tpu.sim.watchcache import WatchCache  # noqa: E402

SCHEME = default_scheme()


def realistic_pod() -> v1.Pod:
    """A production-shaped pod (~1.1KB of JSON): two containers with
    resources and ports, labels/annotations, selector, priority, running
    status with conditions.  Toy 400-byte pods flatter neither codec."""
    return SCHEME.decode({
        "kind": "Pod", "apiVersion": "v1",
        "metadata": {
            "name": "web-7f9c4d8b6-x2k4q", "namespace": "prod",
            "uid": "0e1f2a3b-4c5d-6e7f-8091-a2b3c4d5e6f7",
            "labels": {"app": "web", "pod-template-hash": "7f9c4d8b6",
                       "tier": "frontend", "release": "stable"},
            "annotations": {
                "kubernetes.io/config.seen": "2026-08-07T10:11:12Z",
                "prometheus.io/scrape": "true",
                "prometheus.io/port": "9102"},
        },
        "spec": {
            "containers": [
                {"name": "web", "image": "registry.local/web:v1.42.3",
                 "resources": {"requests": {"cpu": "500m", "memory": "1Gi"},
                               "limits": {"cpu": "2", "memory": "2Gi"}},
                 "ports": [{"containerPort": 8080, "protocol": "TCP"},
                           {"containerPort": 9102, "protocol": "TCP"}]},
                {"name": "sidecar-proxy",
                 "image": "registry.local/proxy:v2.1.0",
                 "resources": {"requests": {"cpu": "100m",
                                            "memory": "128Mi"}},
                 "ports": [{"containerPort": 15001, "protocol": "TCP"}]},
            ],
            "nodeName": "node-17",
            "nodeSelector": {"pool": "general", "arch": "amd64"},
            "priority": 1000, "priorityClassName": "production",
            "schedulerName": "default-scheduler",
        },
        "status": {
            "phase": "Running", "podIP": "10.4.17.23",
            "conditions": [
                {"type": "Initialized", "status": "True"},
                {"type": "Ready", "status": "True"},
                {"type": "ContainersReady", "status": "True"},
                {"type": "PodScheduled", "status": "True"},
            ],
        },
    })


def realistic_node() -> v1.Node:
    return SCHEME.decode({
        "kind": "Node", "apiVersion": "v1",
        "metadata": {
            "name": "node-3",
            "uid": "9a8b7c6d-5e4f-3a2b-1c0d-e9f8a7b6c5d4",
            "labels": {"kubernetes.io/hostname": "node-3",
                       "topology.kubernetes.io/zone": "us-central2-b",
                       "cloud.google.com/gke-tpu-topology": "2x4",
                       "pool": "tpu-v5e"},
        },
        "spec": {
            "podCIDR": "10.4.3.0/24",
            "taints": [{"key": "google.com/tpu", "value": "present",
                        "effect": "NoSchedule"}],
        },
        "status": {
            "capacity": {"cpu": "224", "memory": "393216Mi",
                         "google.com/tpu": "8", "pods": "110"},
            "allocatable": {"cpu": "223", "memory": "380000Mi",
                            "google.com/tpu": "8", "pods": "110"},
            "conditions": [
                {"type": "Ready", "status": "True"},
                {"type": "MemoryPressure", "status": "False"},
                {"type": "DiskPressure", "status": "False"},
                {"type": "PIDPressure", "status": "False"},
                {"type": "NetworkUnavailable", "status": "False"},
            ],
            "images": [
                {"names": ["registry.local/web:v1.42.3"],
                 "sizeBytes": 187654321},
                {"names": ["registry.local/proxy:v2.1.0"],
                 "sizeBytes": 43210987},
            ],
        },
    })


def _time_loop(fn, reps: int, inner: int = 5) -> float:
    """Per-call microseconds: best of `inner` timed blocks of reps calls
    each.  One block would let a scheduler hiccup inflate a 7-microsecond
    path 2x; per-call timers would swamp it with overhead.  Best-of within
    a pass measures the code; median ACROSS passes reports the weather."""
    best = float("inf")
    for _ in range(inner):
        t0 = time.perf_counter()
        for _ in range(reps):
            fn()
        best = min(best, (time.perf_counter() - t0) / reps * 1e6)
    return best


def bench_codec_pass(obj, reps: int) -> dict:
    manifest = to_manifest(obj, SCHEME)
    json_blob = json.dumps(manifest).encode()
    wire_blob = wire.encode_object(obj, SCHEME)
    assert SCHEME.decode(wire.wire_decode(wire_blob)).metadata.name \
        == obj.metadata.name  # parity guard before trusting the numbers

    json_us = (_time_loop(lambda: json.dumps(to_manifest(obj, SCHEME))
                          .encode(), reps)
               + _time_loop(lambda: SCHEME.decode(json.loads(json_blob)),
                            reps))
    wire_us = (_time_loop(lambda: wire.encode_object(obj, SCHEME), reps)
               + _time_loop(lambda: wire.decode_object(wire_blob, SCHEME),
                            reps))
    return {"json_us": round(json_us, 2), "wire_us": round(wire_us, 2),
            "ratio": round(json_us / wire_us, 2),
            "json_bytes": len(json_blob), "wire_bytes": len(wire_blob)}


def bench_codec(obj, passes: int, reps: int) -> dict:
    runs = [bench_codec_pass(obj, reps) for _ in range(passes)]
    ratios = sorted(r["ratio"] for r in runs)
    return {
        "passes": runs,
        "median_ratio": round(statistics.median(ratios), 2),
        "band_ratio": [ratios[0], ratios[-1]],
        "median_json_us": round(statistics.median(
            r["json_us"] for r in runs), 2),
        "median_wire_us": round(statistics.median(
            r["wire_us"] for r in runs), 2),
        "json_bytes": runs[0]["json_bytes"],
        "wire_bytes": runs[0]["wire_bytes"],
    }


def fanout_soak(n_watchers: int, n_events: int) -> dict:
    """n_watchers on one cache; every watcher pulls BOTH codecs' bytes for
    every event (worst case: a mixed-codec audience).  Encode-once holds
    when uncached encodes per event per codec stay ~1."""
    store = ObjectStore()
    cache = WatchCache(store, SCHEME)
    delivered = [0]

    def make_handler():
        def handler(ev):
            ev.payload.bytes_for("wire")
            ev.payload.bytes_for("json")
            delivered[0] += 1
        return handler

    for _ in range(n_watchers):
        cache.watch(make_handler())

    base = {codec: m.apiserver_wire_encode.value((codec, "false"))
            for codec in ("wire", "json")}
    template = to_manifest(realistic_pod(), SCHEME)
    t0 = time.perf_counter()
    for i in range(n_events):
        doc = json.loads(json.dumps(template))
        doc["metadata"]["name"] = f"soak-{i}"
        doc["metadata"]["uid"] = f"soak-uid-{i}"
        store.create("Pod", SCHEME.decode(doc))
    elapsed = time.perf_counter() - t0
    out = {
        "n_watchers": n_watchers,
        "n_events": n_events,
        "deliveries": delivered[0],
        "elapsed_s": round(elapsed, 3),
        "encodes_per_event": {
            codec: round((m.apiserver_wire_encode.value((codec, "false"))
                          - base[codec]) / n_events, 3)
            for codec in ("wire", "json")},
    }
    cache.close()
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=5)
    ap.add_argument("--reps", type=int, default=2000)
    ap.add_argument("--watchers", type=int, default=1000)
    ap.add_argument("--events", type=int, default=50)
    ap.add_argument("--out", default="BENCH_r19_WIRE.json")
    args = ap.parse_args()

    native = wire._native() is not None
    pod = bench_codec(realistic_pod(), args.passes, args.reps)
    node = bench_codec(realistic_node(), args.passes, args.reps)
    soak = fanout_soak(args.watchers, args.events)

    fanout_ok = all(v <= 1.5 for v in soak["encodes_per_event"].values())
    ok = (pod["median_ratio"] >= 10.0 and node["median_ratio"] >= 10.0
          and native and fanout_ok)
    artifact = {
        "environment": {
            "cpus": os.cpu_count(),
            "native_codec": native,
            "note": "median of all passes committed; min..max band rides "
                    "along (ratio = json_us / wire_us, encode+decode "
                    "per event)",
        },
        "pod": pod,
        "node": node,
        "fanout": soak,
        "acceptance": {
            "pod_ratio_ge_10x": pod["median_ratio"] >= 10.0,
            "node_ratio_ge_10x": node["median_ratio"] >= 10.0,
            "encode_once": fanout_ok,
        },
        "wire_bench": "PASS" if ok else "FAIL",
    }
    out_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        args.out) if not os.path.isabs(args.out) else args.out
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(json.dumps({"pod_ratio": pod["median_ratio"],
                      "node_ratio": node["median_ratio"],
                      "encodes_per_event": soak["encodes_per_event"],
                      "wire_bench": artifact["wire_bench"]}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
