"""Same-process A/B of deep-chaining for preemptor batches: run the
PreemptionBasic measured phase twice (chain allowed vs blocked) with warm
programs and identical chip weather.

Usage: python tools/preempt_ab.py [N INIT MEAS BATCH]
"""
import sys
import time

sys.path.insert(0, ".")

import numpy as np

import kubernetes_tpu.scheduler as sched_mod
from kubernetes_tpu.perf.workloads import (
    node_default, pod_high_priority, pod_low_priority,
)
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.utils.compilemon import enable_persistent_cache, monitor

enable_persistent_cache()
monitor.install()

N = int(sys.argv[1]) if len(sys.argv) > 1 else 5000
INIT = int(sys.argv[2]) if len(sys.argv) > 2 else 20000
MEAS = int(sys.argv[3]) if len(sys.argv) > 3 else 5000
BATCH = int(sys.argv[4]) if len(sys.argv) > 4 else 512

orig_block = sched_mod._pods_block_deep
orig_infos_block = TPUScheduler._infos_block_deep


def _block_without_preempt_clause(pods):
    """_pods_block_deep minus the preemption-capability clause — the
    'allow preemptor chaining' arm of the A/B (measured WORSE: 231/87
    pods/s vs 266/265 blocked; staleness-driven claim collisions)."""
    for p in pods:
        if sched_mod._pod_blocks_static(p):
            return True
    return False


def _infos_block_without_preempt_clause(self, infos):
    """B-arm gate for the path schedule_cycle ACTUALLY takes: deep-chain
    gating flows through TPUScheduler._infos_block_deep (the module-level
    _pods_block_deep only serves the interacts-is-None fallback), so the
    method must be patched too or both arms measure identical blocking
    (ADVICE round 5)."""
    return _block_without_preempt_clause([qi.pod for qi in infos])


def run(block_chain: bool) -> float:
    sched_mod._pods_block_deep = (
        orig_block if block_chain else _block_without_preempt_clause
    )
    TPUScheduler._infos_block_deep = (
        orig_infos_block if block_chain else _infos_block_without_preempt_clause
    )
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=BATCH, pipeline=True)
    sched.presize(N, INIT + MEAS + BATCH)
    for i in range(N):
        store.create("Node", node_default(i))
    for i in range(INIT):
        store.create("Pod", pod_low_priority(i))
    sched.run_until_idle(max_cycles=10 * (INIT // BATCH + 1))
    for i in range(MEAS):
        store.create("Pod", pod_high_priority(i))
    t0 = time.perf_counter()
    c0 = monitor.snapshot()[0]
    idle = 0.0
    while True:
        s = sched.schedule_cycle()
        if s.attempted == 0 and s.in_flight == 0:
            a, b, u = sched.queue.pending_count()
            if a == b == u == 0 or idle > 15:
                break
            time.sleep(0.02)
            idle += 0.02
        else:
            idle = 0.0
    wall = time.perf_counter() - t0
    pods, _ = store.list("Pod")
    bound = sum(1 for p in pods
                if p.spec.node_name and p.metadata.name.startswith("high"))
    thr = bound / wall
    print(f"block_chain={block_chain}: {bound}/{MEAS} in {wall:.1f}s = "
          f"{thr:.1f} pods/s (compiles {monitor.snapshot()[0]-c0})")
    return thr


# interleave to cancel weather drift: off, on, off, on
for rep in range(2):
    run(True)
    run(False)
sched_mod._pods_block_deep = orig_block
TPUScheduler._infos_block_deep = orig_infos_block
