"""Phase-level profiler for a perf suite: times host_prepare / batch compile /
snapshot sync / device dispatch / complete / bind per cycle.

Usage: python tools/profile_suite.py SUITE SIZE [scale]
"""
import sys
import time

sys.path.insert(0, ".")

import numpy as np

from kubernetes_tpu.perf.workloads import build_workload
from kubernetes_tpu.perf import harness
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.framework.runtime import BatchedFramework

PHASES = {}


def timed(obj, name, label=None):
    label = label or name
    orig = getattr(obj, name)

    def wrap(*a, **k):
        t0 = time.perf_counter()
        out = orig(*a, **k)
        PHASES.setdefault(label, []).append(time.perf_counter() - t0)
        return out

    setattr(obj, name, wrap)


def main():
    import os

    suite, size = sys.argv[1], sys.argv[2]
    scale = float(sys.argv[3]) if len(sys.argv) > 3 else 1.0
    w = build_workload(suite, size, scale)
    if os.environ.get("BATCH"):
        w.batch_size = int(os.environ["BATCH"])

    # instrument TPUScheduler methods at class level
    for meth in ["_dispatch_batch", "_complete", "_bind_phase", "_run_assignment"]:
        timed(TPUScheduler, meth)

    # count host bytes shipped to the fused program per cycle
    import jax

    orig_run = TPUScheduler._run_assignment

    def run_with_bytes(self, jt, batch, dsnap, upd, nom_rows, nom_req,
                       host_auxes, **kw):
        tot = 0
        for leaf in jax.tree_util.tree_leaves((batch, upd, nom_rows, nom_req, host_auxes)):
            if isinstance(leaf, np.ndarray):
                tot += leaf.nbytes
        PHASES.setdefault("upload_MB", []).append(tot / 1e6 / 1e3)  # store as "s"→MB/1000
        return orig_run(self, jt, batch, dsnap, upd, nom_rows, nom_req,
                        host_auxes, **kw)

    TPUScheduler._run_assignment = run_with_bytes
    timed(BatchedFramework, "host_prepare")
    from kubernetes_tpu.framework.podbatch import PodBatchCompiler
    timed(PodBatchCompiler, "compile", "podbatch.compile")
    from kubernetes_tpu.state.encoding import ClusterEncoder
    timed(ClusterEncoder, "sync", "encoder.sync")
    timed(ClusterEncoder, "to_device_deferred")
    from kubernetes_tpu.state.cache import Cache
    timed(Cache, "update_snapshot")

    t0 = time.perf_counter()
    items = harness.run_workload(w)
    wall = time.perf_counter() - t0
    for it in items:
        if it.labels.get("Metric") in ("SchedulingThroughput",
                                       "scheduler_scheduling_attempt_duration_seconds"):
            print(it.labels["Metric"], {k: round(v, 3) for k, v in it.data.items()})
    print(f"wall={wall:.1f}s")
    print(f"{'phase':28s} {'n':>5s} {'total_s':>9s} {'mean_ms':>9s} {'max_ms':>9s}  last8_ms")
    for k, v in sorted(PHASES.items(), key=lambda kv: -sum(kv[1])):
        a = np.array(v)
        tail = " ".join(f"{1e3*x:.0f}" for x in v[-8:])
        print(f"{k:28s} {len(v):5d} {a.sum():9.2f} {1e3*a.mean():9.1f} {1e3*a.max():9.1f}  [{tail}]")


if __name__ == "__main__":
    main()
