"""while_loop vs scan vs fori_loop iteration cost on this backend."""
import sys, time
sys.path.insert(0, ".")
import numpy as np
import jax
import jax.numpy as jnp

K = int(sys.argv[1]) if len(sys.argv) > 1 else 128
N = 8192

x0 = jnp.zeros((N,), jnp.float32)


@jax.jit
def w_while(x, k):
    def cond(s):
        i, _ = s
        return i < k

    def body(s):
        i, x = s
        return i + 1, x + jnp.sum(x) * 1e-9 + 1.0

    return jax.lax.while_loop(cond, body, (jnp.int32(0), x))[1]


@jax.jit
def w_scan(x, k):
    def step(x, _):
        return x + jnp.sum(x) * 1e-9 + 1.0, None

    return jax.lax.scan(step, x, None, length=K)[0]


@jax.jit
def w_fori(x, k):
    def body(i, x):
        return x + jnp.sum(x) * 1e-9 + 1.0

    return jax.lax.fori_loop(0, k, body, x)


def t(label, fn, *args):
    fn(*args).block_until_ready()
    # chained: output feeds next input (defeats any result caching)
    x = x0
    t0 = time.perf_counter()
    for _ in range(5):
        x = fn(x, *args[1:])
    x.block_until_ready()
    dt = (time.perf_counter() - t0) / 5
    print(f"{label:12s} K={K}: {1e3*dt:8.1f} ms  ({1e6*dt/K:6.1f} us/iter)")


t("while_loop", w_while, x0, jnp.int32(K))
t("scan", w_scan, x0, jnp.int32(K))
t("fori_loop", w_fori, x0, jnp.int32(K))
