"""Per-cycle trace of the PreemptionBasic suite: batch composition,
compiles, preempt timings — finds where the 75 pods/s goes.

Usage: python tools/preempt_trace.py [N] [INIT] [MEASURE] [BATCH]
"""
import sys
import time

sys.path.insert(0, ".")

import numpy as np

from kubernetes_tpu.perf.workloads import (
    node_default, pod_high_priority, pod_low_priority,
)
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.utils.compilemon import monitor

monitor.install()

N = int(sys.argv[1]) if len(sys.argv) > 1 else 5000
INIT = int(sys.argv[2]) if len(sys.argv) > 2 else 20000
MEAS = int(sys.argv[3]) if len(sys.argv) > 3 else 5000
BATCH = int(sys.argv[4]) if len(sys.argv) > 4 else 256

store = ObjectStore()
sched = TPUScheduler(store, batch_size=BATCH, pipeline=True)
sched.presize(N, INIT + MEAS + BATCH)
for i in range(N):
    store.create("Node", node_default(i))
for i in range(INIT):
    store.create("Pod", pod_low_priority(i))

t0 = time.perf_counter()
sched.run_until_idle(max_cycles=10 * (INIT // BATCH + 1))
print(f"init scheduled in {time.perf_counter()-t0:.1f}s; compiles so far: "
      f"{monitor.snapshot()}")

# preempt timing instrumentation
from kubernetes_tpu.preemption import Evaluator

for meth in ("preempt_plain", "plain_tables"):
    orig = getattr(Evaluator, meth)

    def make(orig=orig, meth=meth):
        acc = {"n": 0, "s": 0.0}

        def wrap(self, *a, **kw):
            t = time.perf_counter()
            out = orig(self, *a, **kw)
            acc["n"] += 1
            acc["s"] += time.perf_counter() - t
            return out

        wrap._acc = acc
        return wrap

    setattr(Evaluator, meth, make())

for i in range(MEAS):
    store.create("Pod", pod_high_priority(i))

print("cycle  att sched unsch inflight  dur_ms  compiles  active/backoff/unsch")
t0 = time.perf_counter()
c0, s0 = monitor.snapshot()
cyc = 0
idle_wait = 0.0
while True:
    tc = time.perf_counter()
    pre_c = monitor.snapshot()[0]
    s = sched.schedule_cycle()
    dur = time.perf_counter() - tc
    dc = monitor.snapshot()[0] - pre_c
    a, b, u = sched.queue.pending_count()
    if s.attempted or dc or cyc % 10 == 0:
        print(f"{cyc:5d} {s.attempted:4d} {s.scheduled:5d} {s.unschedulable:5d}"
              f" {s.in_flight:8d} {1e3*dur:7.0f} {dc:9d}  {a}/{b}/{u}")
    cyc += 1
    if s.attempted == 0 and s.in_flight == 0:
        if a == b == u == 0 or idle_wait > 20:
            break
        time.sleep(0.02)
        idle_wait += 0.02
    else:
        idle_wait = 0.0
wall = time.perf_counter() - t0
c1, s1 = monitor.snapshot()
pods, _ = store.list("Pod")
bound = sum(1 for p in pods if p.spec.node_name and p.metadata.name.startswith("high"))
print(f"\nbound {bound}/{MEAS} in {wall:.1f}s = {bound/wall:.1f} pods/s; "
      f"in-window compiles {c1-c0} ({s1-s0:.1f}s)")
for meth in ("preempt_plain", "plain_tables"):
    acc = getattr(Evaluator, meth)._acc
    print(f"{meth}: n={acc['n']} total={acc['s']:.2f}s")
