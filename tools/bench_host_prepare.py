"""Microbench: per-cycle InterPodAffinity.host_prepare wall vs scheduled
anti-affinity pod count — the round-6 tentpole's core host-path claim (the
old per-cycle rebuild walk is O(all scheduled affinity pods); the
incremental AffinityIndex is O(batch delta)).  Run in both a pre-round-6
worktree and the current tree to produce the `host_prepare_scaling_ms`
section of BENCH_r06_AB.json (tools/build_r6_ab.py AB_HOSTPREP env):

    JAX_PLATFORMS=cpu python tools/bench_host_prepare.py

Prints one JSON object {scheduled_pod_count: ms_per_call} (20-rep mean,
64-pod anti-affinity batch, hostname topology)."""

import json, sys, time
import numpy as np
from kubernetes_tpu.state.cache import Cache, Snapshot
from kubernetes_tpu.state.encoding import ClusterEncoder
from kubernetes_tpu.framework.podbatch import PodBatchCompiler
from kubernetes_tpu.framework.runtime import BatchedFramework
from kubernetes_tpu.scheduler import default_plugins
from kubernetes_tpu.testutil import make_node, make_pod

out = {}
for K in (500, 2000, 8000):
    N = max(1000, K)
    cache = Cache()
    for i in range(N):
        cache.add_node(make_node().name(f"node-{i:06d}")
                       .capacity({"cpu": "64", "memory": "256Gi", "pods": "400"})
                       .label("kubernetes.io/hostname", f"node-{i:06d}").obj())
    def apod(i, ns):
        return (make_pod().name(f"anti-{ns}-{i:06d}").uid(f"anti-{ns}-{i:06d}")
                .namespace(ns).req({"cpu": "100m"}).label("color", "green")
                .pod_affinity("kubernetes.io/hostname", {"color": "green"},
                              anti=True, namespaces=["sched-0", "sched-1"]).obj())
    for i in range(K):
        p = apod(i, "sched-0"); p.spec.node_name = f"node-{i % N:06d}"; cache.add_pod(p)
    snap = Snapshot(); cache.update_snapshot(snap)
    enc = ClusterEncoder()
    comp = PodBatchCompiler(enc)
    batch = comp.compile([apod(10_000_000 + i, "sched-1") for i in range(64)], pad_to=64)
    enc.full_sync(snap)
    fw = BatchedFramework(default_plugins(enc.domain_cap, None))
    fw.host_prepare(batch, snap, enc)  # warm caches
    t0 = time.perf_counter()
    reps = 20
    for _ in range(reps):
        fw.host_prepare(batch, snap, enc)
    out[K] = round((time.perf_counter() - t0) / reps * 1e3, 3)
print(json.dumps(out))
