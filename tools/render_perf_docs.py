"""Render the perf tables in COMPONENTS.md / BASELINE.md FROM the committed
artifacts (VERDICT r4 weak #1 / next #2: "generate, don't transcribe" — the
round-4 docs cited bench_final.json for numbers the file didn't contain).

Reads bench_final.json, suites_5k.out, density.json and rewrites everything
between the GENERATED:PERF sentinels in both docs.  Run as the LAST step of
any artifact refresh (tools/run_suites.sh does).  Exits non-zero if a doc
cites an artifact that is missing or unparsable, or if sentinels are absent.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BEGIN = "<!-- GENERATED:PERF:BEGIN (tools/render_perf_docs.py — edit the artifacts, not this block) -->"
END = "<!-- GENERATED:PERF:END -->"


def load_bench(path):
    with open(os.path.join(REPO, path)) as f:
        return json.load(f)


def load_suites(path="suites_5k.out"):
    out = {}
    with open(os.path.join(REPO, path)) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            if "error" in d:
                out[d["error"]] = d
                continue
            out[d["detail"]["workload"]] = d
    return out


def _ms(x):
    return f"{x:.0f}"


def suite_row(d):
    dd = d["detail"]
    att = dd["attempt_ms"]
    env = dd.get("go_envelope", {})
    ratio = env.get("vs_go_envelope_throughput")
    env_thr = (env.get("sampled") or {}).get("throughput_pods_per_s")
    comp = dd["xla_compiles_in_window"]
    steady = dd.get("steady_state_ms", {})
    env_cell = f"{env_thr:.0f}" if env_thr is not None else "—"
    ratio_cell = f"{ratio:.2f}" if ratio is not None else "—"
    return (
        f"| {dd['workload']} | {dd['throughput_pods_per_s']:.1f} | "
        f"{_ms(att['p50'])} / {_ms(att['p99'])} | "
        f"{int(comp['count'])} | "
        f"{int(steady.get('attempts', 0))}/{int(steady.get('of_total', 0))} | "
        f"{env_cell} | {ratio_cell} |"
    )


def render_components(suites, bench, density):
    dd = bench["detail"]
    att = dd["attempt_ms"]
    env = dd["go_envelope"]
    lines = [
        BEGIN,
        "",
        "North star (`bench.py`, NorthStar 5000 nodes / 2000 scheduled / "
        "10000 pending, full default plugin set — every number below is "
        "read from the committed `bench_final.json`):",
        "",
        "| Metric | Value |",
        "|---|---|",
        f"| Throughput | **{dd['throughput_pods_per_s']:.1f} pods/s** |",
        f"| attempt p50 / p90 / p99 | {_ms(att['p50'])} / {_ms(att['p90'])} "
        f"/ {_ms(att['p99'])} ms |",
        f"| in-window XLA compiles | {int(dd['xla_compiles_in_window']['count'])} |",
        f"| sampled Go envelope (same run) | "
        f"{env['sampled']['throughput_pods_per_s']:.1f} pods/s |",
        f"| vs_go_envelope_throughput | **{env['vs_go_envelope_throughput']:.3f}** |",
        f"| vs_go_envelope_dense_throughput | "
        f"{env['vs_go_envelope_dense_throughput']:.2f} |",
        "",
        "All suites, one artifact pass (`suites_5k.out`; the tunnel-attached "
        "chip's weather moves numbers ±2× between passes — the envelope "
        "column is measured in the SAME run, with each suite's own "
        "default-plugin work model, so the ratio is weather-paired):",
        "",
        "| Suite | pods/s | p50 / p99 (ms) | compiles | steady/total "
        "attempts | suite envelope (sampled) | ratio |",
        "|---|---|---|---|---|---|---|",
    ]
    for name, d in suites.items():
        if "error" in d:
            lines.append(f"| {name} | FAILED | — | — | — | — | — |")
            continue
        lines.append(suite_row(d))
    if density:
        ddd = density["detail"]
        datt = ddd["attempt_ms"]
        lines += [
            "",
            "Density (reference historic target, 30k pods / 1000 nodes, "
            "`density.json`): "
            f"**{ddd['throughput_pods_per_s']:.1f} pods/s**, attempt p50 "
            f"{_ms(datt['p50'])} ms / p99 {_ms(datt['p99'])} ms / max "
            f"{_ms(datt['max'])} ms, "
            f"{int(ddd['xla_compiles_in_window']['count'])} in-window "
            "compiles.",
        ]
    lines += ["", END]
    return "\n".join(lines)


def render_baseline(bench):
    dd = bench["detail"]
    att = dd["attempt_ms"]
    env = dd["go_envelope"]
    ratio = env["vs_go_envelope_throughput"]
    verdict = "MET" if ratio >= 1.0 else "NOT met in this pass's weather"
    lines = [
        BEGIN,
        "",
        "| Clause | Status | Evidence (all from `bench_final.json`) |",
        "|---|---|---|",
        "| ≥50× p99 `schedule_attempt_duration` reduction | **NOT met "
        "under the per-attempt definition — by design trade** | attempt "
        f"p99 {_ms(att['p99'])} ms: an attempt spans its whole batch "
        "window plus the tunnel's fixed turnaround, so per-attempt latency "
        "cannot beat a per-pod loop whose idealized envelope answers in "
        f"{env['sampled']['attempt_ms']['p99']:.2f} ms.  What the batch "
        "design buys is throughput at full optimality (next row); "
        "`BATCH_SWEEP.json` publishes the latency/throughput frontier. |",
        "| Throughput vs the sampled Go envelope | "
        f"**{verdict}: ratio {ratio:.3f}** | "
        f"{dd['throughput_pods_per_s']:.1f} pods/s scoring ALL 5000 nodes "
        f"per pod vs the envelope's "
        f"{env['sampled']['throughput_pods_per_s']:.1f} pods/s scoring 10% "
        f"(same-run measurement); dense-work ratio "
        f"{env['vs_go_envelope_dense_throughput']:.2f} |",
        "| Binding parity vs default scheduler | **Met** | oracle-parity "
        "suites (`tests/test_parity.py`, `test_fast_scan.py`, "
        "`test_batch_assign.py`, `test_volumes.py`), deterministic replay, "
        "deep-pipeline (depths 2 AND 3) == synchronous bindings "
        "(`tests/test_deep_pipeline.py`) |",
        "| Single pod scores 100k-node clusters in one shot | **Met — "
        "executed, WITH assignment** | `SCALE_100K_EXEC.json`: sharded "
        "filter+score AND both assignment engines over a concrete "
        "100,352-node snapshot; bindings asserted feasible "
        "(mask-consistent, no node oversubscribed) |",
        "",
        END,
    ]
    return "\n".join(lines)


AB_BEGIN = ("<!-- GENERATED:PERF:R6AB:BEGIN (tools/render_perf_docs.py — "
            "edit BENCH_r06_AB.json, not this block) -->")
AB_END = "<!-- GENERATED:PERF:R6AB:END -->"


def render_r6_ab(ab):
    """Round-6 same-hardware A/B table (BENCH_r06_AB.json): pre-change HEAD
    vs the incremental-affinity + hybrid-assignment build, both arms run in
    THIS repo's container.  Rendered, not transcribed, like every other
    perf block."""
    env = ab["environment"]
    lines = [
        AB_BEGIN,
        "",
        f"Environment: `{env['backend']}` backend, {env['cpus']} CPU core(s)"
        f" — {env['note']}",
        "",
        ab["scale_note"],
        "",
        "| Suite (scale) | baseline pods/s (passes) | round 6 pods/s "
        "(passes) | speedup | r6 p99 ms | r6 compiles | "
        "host_prepare+partition wall (s) |",
        "|---|---|---|---|---|---|---|",
    ]

    def band(vals):
        return "/".join(f"{v:.0f}" for v in vals)

    for r in ab["rows"]:
        b, n = r["baseline"], r["round6"]
        pw = n.get("phase_wall_s", {})
        hp = pw.get("host_prepare", 0.0) + pw.get("partition", 0.0)
        lines.append(
            f"| {r['suite']} (×{r['scale']}) | "
            f"{b['throughput_pods_per_s']:.1f} "
            f"({band(r['baseline_passes_pods_per_s'])}) | "
            f"{n['throughput_pods_per_s']:.1f} "
            f"({band(r['round6_passes_pods_per_s'])}) | "
            f"**{r['speedup']:.2f}×** | "
            f"{n['attempt_ms']['p99']:.0f} | "
            f"{int(n['xla_compiles_in_window']['count'])} | "
            f"{hp:.3f} |"
        )
    hp = ab.get("host_prepare_scaling_ms")
    if hp:
        b, n = hp["baseline"], hp["round6"]
        ks = sorted(b, key=int)
        lines += [
            "",
            "Per-cycle `InterPodAffinity.host_prepare` wall vs scheduled "
            "anti-affinity pod count (the tentpole's core claim — the old "
            "per-cycle rebuild walk is O(all scheduled affinity pods), the "
            "incremental index is O(batch delta); same-hardware microbench, "
            f"{hp['note']}):",
            "",
            "| scheduled affinity pods | " + " | ".join(ks) + " |",
            "|---|" + "---|" * len(ks),
            "| baseline (ms/cycle) | "
            + " | ".join(f"{b[k]:.2f}" for k in ks) + " |",
            "| round 6 (ms/cycle) | "
            + " | ".join(f"{n[k]:.2f}" for k in ks) + " |",
            "| speedup | "
            + " | ".join(f"**{b[k] / n[k]:.0f}×**" for k in ks) + " |",
        ]
    lines += ["", AB_END]
    return "\n".join(lines)


R12_BEGIN = ("<!-- GENERATED:PERF:R12AB:BEGIN (tools/render_perf_docs.py — "
             "edit BENCH_r12_AB.json, not this block) -->")
R12_END = "<!-- GENERATED:PERF:R12AB:END -->"


def render_r12_ab(ab):
    """Round-12 same-hardware A/B table (BENCH_r12_AB.json): pre-round-12
    worktree vs the coupled-pipeline build, both arms in THIS container."""
    env = ab["environment"]
    lines = [
        R12_BEGIN,
        "",
        f"Environment: `{env['backend']}` backend, {env['cpus']} CPU core(s)"
        f" — {env['note']}",
        "",
        ab["scale_note"],
        "",
        "| Suite (scale) | baseline pods/s (passes) | round 12 pods/s "
        "(passes) | speedup | r12 p99 ms | r12 compiles | "
        "extender_wait wall (s) |",
        "|---|---|---|---|---|---|---|",
    ]

    def band(vals):
        return "/".join(f"{v:.0f}" for v in vals)

    for r in ab["rows"]:
        b, n = r["baseline"], r["round12"]
        ew = n.get("phase_wall_s", {}).get("extender_wait", 0.0)
        lines.append(
            f"| {r['suite']} (×{r['scale']}) | "
            f"{b['throughput_pods_per_s']:.1f} "
            f"({band(r['baseline_passes_pods_per_s'])}) | "
            f"{n['throughput_pods_per_s']:.1f} "
            f"({band(r['round12_passes_pods_per_s'])}) | "
            f"**{r['speedup']:.2f}×** | "
            f"{n['attempt_ms']['p99']:.0f} | "
            f"{int(n['xla_compiles_in_window']['count'])} | "
            f"{ew:.3f} |"
        )
    ext = ab.get("extender_callout_bench")
    if ext:
        ks = list(ext)
        lines += [
            "",
            "Extender callout microbench ("
            + ab.get("extender_callout_note", "tools/bench_extender.py")
            + "):",
            "",
            "| config | pods/s | extender_wait s | walk ms/pod |",
            "|---|---|---|---|",
        ] + [
            f"| {k} | {ext[k]['pods_per_s']} | "
            f"{ext[k]['extender_wait_s']} | {ext[k]['walk_ms_per_pod']} |"
            for k in ks
        ]
    lines += ["", R12_END]
    return "\n".join(lines)


def render_phase_table(apl, indent=""):
    """Markdown per-phase p50/p90/p99 table from an `attempt_phase_latency`
    block — rendered for ANY bench artifact that carries one (the span-
    reconstructed observatory, round 14), so docs can cite the phase split
    without transcribing it."""
    if not apl or not apl.get("phases_ms"):
        return []
    lines = [
        f"{indent}| phase | p50 (ms) | p90 (ms) | p99 (ms) |",
        f"{indent}|---|---|---|---|",
    ]
    for ph, q in apl["phases_ms"].items():
        lines.append(
            f"{indent}| {ph} | {q.get('p50', 0):.1f} | "
            f"{q.get('p90', 0):.1f} | {q.get('p99', 0):.1f} |")
    lines.append(
        f"{indent}| *attempt (tiling sum p50 / measured p50 / coverage)* | "
        f"{apl.get('sum_p50_ms', 0):.1f} | {apl.get('attempt_p50_ms', 0):.1f}"
        f" | {apl.get('coverage', 0):.4f} |")
    return lines


def _r15_e2e_line(base, new):
    """Honest framing next to the attempt headline: attempt latency is the
    reference's per-attempt metric (pop → decision+bind), while a pod's
    VISIBLE wait additionally includes queue time — which the micro-bucket
    split deliberately grows (tail pods ride put-backs instead of sitting
    inside a giant in-flight batch).  Render both so the attempt win is
    never mistaken for an equal-size end-to-end win."""

    def e2e(d):
        apl = d.get("attempt_phase_latency") or {}
        qw = (apl.get("phases_ms") or {}).get("queue_wait") or {}
        return d["attempt_ms"]["p50"] + qw.get("p50", 0.0)

    return (
        f"Pod-visible e2e p50 (queue_wait + attempt): baseline "
        f"{e2e(base):.0f} ms → round 15 {e2e(new):.0f} ms — the split "
        "moves tail pods' wait from inside a giant in-flight batch into "
        "the queue, so the per-attempt win shows up end-to-end as the "
        "throughput gain (the backlog drains "
        f"{new['throughput_pods_per_s'] / max(base['throughput_pods_per_s'], 1e-9):.2f}× "
        "faster) and as decision latency once queues are shallow, not as "
        "an equal-size cut in deep-backlog per-pod wait.")


R15_BEGIN = ("<!-- GENERATED:PERF:R15LAT:BEGIN (tools/render_perf_docs.py — "
             "edit BENCH_r15_LATENCY.json, not this block) -->")
R15_END = "<!-- GENERATED:PERF:R15LAT:END -->"


def render_r15_latency(ab):
    """Round-15 attempt-latency A/B (BENCH_r15_LATENCY.json): full-batch
    baseline vs micro-bucket + overlapped-sync arm, same container,
    interleaved passes — plus each arm's span-reconstructed per-phase
    latency table and the CI budgets gate_attempt_p99 enforces."""
    env = ab["environment"]
    base, new = ab["baseline"], ab["round15"]

    def band(vals):
        return "/".join(f"{v:.0f}" for v in vals)

    lines = [
        R15_BEGIN,
        "",
        f"Environment: `{env['backend']}` backend, {env['cpus']} CPU "
        f"core(s) — {env['note']}",
        "",
        f"| arm ({ab['suite']}) | pods/s (passes) | attempt p50 / p99 ms "
        "(p99 passes) | in-window compiles | phase coverage |",
        "|---|---|---|---|---|",
        f"| baseline (full 512 batches) | "
        f"{base['throughput_pods_per_s']:.1f} "
        f"({band(ab['baseline_passes_pods_per_s'])}) | "
        f"{base['attempt_ms']['p50']:.0f} / {base['attempt_ms']['p99']:.0f} "
        f"({band(ab['baseline_passes_p99_ms'])}) | "
        f"{int(base['xla_compiles_in_window']['count'])} | "
        f"{base['attempt_phase_latency'].get('coverage', 0):.4f} |",
        f"| round 15 (micro-bucket + overlapped sync) | "
        f"{new['throughput_pods_per_s']:.1f} "
        f"({band(ab['round15_passes_pods_per_s'])}) | "
        f"{new['attempt_ms']['p50']:.0f} / {new['attempt_ms']['p99']:.0f} "
        f"({band(ab['round15_passes_p99_ms'])}) | "
        f"{int(new['xla_compiles_in_window']['count'])} | "
        f"{new['attempt_phase_latency'].get('coverage', 0):.4f} |",
        "",
        f"Attempt p99 reduced **{ab['p99_reduction_x']:.1f}×** at "
        f"**{ab['throughput_vs_baseline']:.2f}×** baseline throughput.",
        "",
        _r15_e2e_line(base, new),
        "",
        "Per-phase attempt latency, round-15 arm (span-reconstructed):",
        "",
        *render_phase_table(new.get("attempt_phase_latency")),
        "",
        "Per-phase attempt latency, baseline arm:",
        "",
        *render_phase_table(base.get("attempt_phase_latency")),
        "",
        "CI p99 budgets (`tools/run_suites.sh gate_attempt_p99`):",
        "",
        "| suite | budget (ms) | provenance |",
        "|---|---|---|",
    ]
    for suite, g in ab.get("gates", {}).items():
        lines.append(
            f"| {suite} | {g['budget_ms']:.0f} | {g['provenance']} |")
    lines += ["", R15_END]
    return "\n".join(lines)


R16_BEGIN = ("<!-- GENERATED:PERF:R16REPLICA:BEGIN (tools/render_perf_docs.py"
             " — edit BENCH_r16_REPLICA.json, not this block) -->")
R16_END = "<!-- GENERATED:PERF:R16REPLICA:END -->"


def render_r16_replica(r16):
    """Round-16 replication bench (BENCH_r16_REPLICA.json): promotion
    time over a shipped N-record log (the failover write-unavailability
    window) and follower paged-read throughput at the watermark, median +
    per-pass band, plus the riding soak's convergence line."""
    env = r16["environment"]
    promo = r16["promotion_ms"]
    reads = r16["follower_read_pages_per_s"]
    soak = r16["soak"]

    def band(vals):
        return "/".join(f"{v:.0f}" for v in vals)

    lines = [
        R16_BEGIN,
        "",
        f"Environment: `{env['backend']}` backend, {env['cpus']} CPU "
        f"core(s) — {env['note']}",
        "",
        "| metric | median | passes |",
        "|---|---|---|",
        f"| follower promotion over a {r16['records']}-record shipped log "
        f"(fsync + tail verify + WAL reattach) | "
        f"{promo['median']:.1f} ms | {band(promo['passes'])} |",
        f"| follower read throughput (rv-pinned "
        f"{r16['page_limit']}-object LIST pages at the watermark) | "
        f"{reads['median']:.0f} pages/s | {band(reads['passes'])} |",
        "",
        f"Soak (unshipped-boundary kill, seed 11): "
        f"{'converged' if soak['converged'] else 'FAILED'} — promoted "
        f"{soak['promoted']} in {soak['promotion_ticks']} ticks "
        f"({soak['fenced_losers']} fenced loser), "
        f"{soak['discarded_records']} unshipped records discarded "
        f"exactly-once, {soak['events_lost']} lost / "
        f"{soak['events_duplicated']} duplicated events, "
        f"{soak['bookmark_overclaims']} overclaimed bookmarks, injected "
        f"{soak['injected']}.",
        "",
        R16_END,
    ]
    return "\n".join(lines)


R9_BEGIN = ("<!-- GENERATED:PERF:R9100K:BEGIN (tools/render_perf_docs.py — "
            "edit BENCH_r09_100K.json, not this block) -->")
R9_END = "<!-- GENERATED:PERF:R9100K:END -->"


def render_r9_100k(ab):
    """Round-9 live-100k vs one-shot A/B table (BENCH_r09_100K.json).

    A --skip-baseline artifact (no baseline_one_shot, null ratio) still
    renders: the live row alone, no ratio sentence."""
    live = ab["live_suite"]["detail"]
    base = ab.get("baseline_one_shot")
    ratio = ab.get("throughput_ratio")
    lines = [
        R9_BEGIN,
        "",
        "| arm | pods/s | note |",
        "|---|---|---|",
    ]
    if base is not None:
        lines.append(
            f"| one-shot baseline | {base['warm_assign_pods_per_s']} | "
            f"{base.get('config', 'one-shot')}: warm "
            f"{base.get('pending_batch', '?')}-pod greedy assign step, "
            "virtual 8-device mesh |")
    lines += [
        (f"| live NorthStar/100kNodes | "
         f"{live['throughput_pods_per_s']} | end to end "
         f"(store → sync → dedup cycle → bind) at {live['nodes']} nodes, "
         f"backend {live.get('backend', '?')} |"),
        "",
    ]
    if ratio is not None:
        lines.append(
            f"Live end-to-end throughput is **{ratio}×** the "
            "one-shot warm ASSIGNMENT rate re-measured on the same hardware"
            + (f" ({ab['vs_committed_SCALE_100K_EXEC']}× vs the committed "
               "SCALE_100K_EXEC rate)"
               if "vs_committed_SCALE_100K_EXEC" in ab else "")
            + " — and the live number additionally pays snapshot sync, "
              "queue, binding and store writes the one-shot never did.")
    lines += [
        (f"Attempt p50/p99 {live['attempt_ms']['p50']:.1f}/"
         f"{live['attempt_ms']['p99']:.1f} ms; in-window compiles: "
         f"{live['xla_compiles_in_window']['count']}."),
        "",
        R9_END,
    ]
    return "\n".join(lines)


R18_BEGIN = ("<!-- GENERATED:PERF:R18DRA:BEGIN (tools/render_perf_docs.py — "
             "edit BENCH_r18_DRA.json, not this block) -->")
R18_END = "<!-- GENERATED:PERF:R18DRA:END -->"


def render_r18_dra(r18):
    """DeviceClaimGang artifact block (BENCH_r18_DRA.json, built by
    tools/build_r18_dra.py): gangs/s, claims/s, time-to-full-slice and the
    zero-in-window-compile line for the named-device-claim gang suite."""
    env = r18["environment"]
    dd = r18["run"]["detail"]
    att = dd["attempt_ms"]
    gang = dd.get("gang") or {}
    claims = dd.get("dra_claims") or {}
    tfs = gang.get("time_to_full_slice_s") or {}

    def band(vals):
        return "/".join(f"{v:.0f}" for v in vals)

    lines = [
        R18_BEGIN,
        "",
        f"Environment: `{env['backend']}` backend, {env['cpus']} CPU "
        f"core(s) — {env['note']}",
        "",
        f"| metric ({r18['suite']}/{r18['size']}"
        + (f" ×{r18['scale']}" if r18.get("scale", 1.0) != 1.0 else "")
        + ") | value |",
        "|---|---|",
        f"| member pods/s (passes) | {dd['throughput_pods_per_s']:.1f} "
        f"({band(r18['passes_pods_per_s'])}) |",
        f"| gangs seated / gangs/s | {gang.get('gangs', 0)} / "
        f"{gang.get('gangs_per_s', 0.0):.2f} |",
        f"| claims allocated / claims/s | {claims.get('allocated', 0)} / "
        f"{claims.get('claims_per_s', 0.0):.1f} |",
        f"| time-to-full-slice p50 / p90 / max | {tfs.get('p50', 0):.3f} / "
        f"{tfs.get('p90', 0):.3f} / {tfs.get('max', 0):.3f} s |",
        f"| attempt p50 / p99 | {att['p50']:.0f} / {att['p99']:.0f} ms |",
        f"| in-window XLA compiles | "
        f"{int(dd['xla_compiles_in_window']['count'])} |",
        "",
        R18_END,
    ]
    return "\n".join(lines)


R19_BEGIN = ("<!-- GENERATED:PERF:R19WIRE:BEGIN (tools/render_perf_docs.py — "
             "edit BENCH_r19_WIRE.json, not this block) -->")
R19_END = "<!-- GENERATED:PERF:R19WIRE:END -->"


def render_r19_wire(r19):
    """Binary wire plane artifact block (BENCH_r19_WIRE.json, built by
    tools/bench_wire.py): per-event codec ratios with min..max bands, byte
    sizes, and the thousand-watcher encode-once fanout line."""
    env = r19["environment"]
    fan = r19["fanout"]
    epe = fan["encodes_per_event"]

    def row(name, d):
        lo, hi = d["band_ratio"]
        return (f"| {name} encode+decode | {d['median_json_us']:.1f} µs | "
                f"{d['median_wire_us']:.1f} µs | "
                f"**{d['median_ratio']:.1f}×** ({lo:.1f}–{hi:.1f}) | "
                f"{d['json_bytes']} → {d['wire_bytes']} B |")

    lines = [
        R19_BEGIN,
        "",
        f"Environment: {env['cpus']} CPU core(s), native codec "
        f"{'ON' if env['native_codec'] else 'OFF'} — {env['note']}",
        "",
        "| per event | JSON | wire | ratio (band) | payload |",
        "|---|---|---|---|---|",
        row("pod", r19["pod"]),
        row("node", r19["node"]),
        "",
        f"Fan-out soak: {fan['n_watchers']} watchers × {fan['n_events']} "
        f"events = {fan['deliveries']} deliveries; uncached encodes per "
        f"event: wire {epe['wire']:.2f}, json {epe['json']:.2f} "
        f"(encode-once holds — the cost is ~1 encode per codec, not "
        f"~{fan['n_watchers']}).",
        "",
        R19_END,
    ]
    return "\n".join(lines)


R20_BEGIN = ("<!-- GENERATED:PERF:R20CRD:BEGIN (tools/render_perf_docs.py — "
             "edit BENCH_r20_CRD.json, not this block) -->")
R20_END = "<!-- GENERATED:PERF:R20CRD:END -->"


def render_r20_crd(r20):
    """TrainingJobFlow artifact block (BENCH_r20_CRD.json, built by
    tools/build_r20_crd.py): median+band member-pod and job throughput for
    the CRD-defined custom workload riding the gang + device-claim path,
    plus the zero-in-window-compile line."""
    env = r20["environment"]
    dd = r20["run"]["detail"]
    att = dd["attempt_ms"]
    gang = dd.get("gang") or {}
    claims = dd.get("dra_claims") or {}
    jobs = dd.get("trainingjobs") or {}
    pods = r20["pods_per_s"]
    jps = r20["jobs_per_s"]

    def band(d, fmt="{:.0f}"):
        lo, hi = d["band"]
        return f"{fmt.format(lo)}–{fmt.format(hi)}"

    lines = [
        R20_BEGIN,
        "",
        f"Environment: `{env['backend']}` backend, {env['cpus']} CPU "
        f"core(s) — {env['note']}",
        "",
        f"| metric ({r20['suite']}/{r20['size']}"
        + (f" ×{r20['scale']}" if r20.get("scale", 1.0) != 1.0 else "")
        + ") | median | band |",
        "|---|---|---|",
        f"| member pods/s | {pods['median']:.1f} | {band(pods)} |",
        f"| TrainingJobs completed / jobs/s | {jobs.get('jobs', 0)} / "
        f"{jps['median']:.1f} | {band(jps, '{:.1f}')} |",
        f"| gangs seated / gangs/s | {gang.get('gangs', 0)} / "
        f"{gang.get('gangs_per_s', 0.0):.2f} | — |",
        f"| member claims allocated / claims/s | "
        f"{claims.get('allocated', 0)} / "
        f"{claims.get('claims_per_s', 0.0):.1f} | — |",
        f"| attempt p50 / p99 | {att['p50']:.0f} / {att['p99']:.0f} ms | "
        "— |",
        f"| in-window XLA compiles | "
        f"{int(dd['xla_compiles_in_window']['count'])} | — |",
        "",
        R20_END,
    ]
    return "\n".join(lines)


def splice(path, block, begin=BEGIN, end=END):
    p = os.path.join(REPO, path)
    text = open(p).read()
    if begin not in text or end not in text:
        print(f"ERROR: {path} lacks the {begin.split(' ')[0]} sentinels",
              file=sys.stderr)
        return False
    head, rest = text.split(begin, 1)
    _, tail = rest.split(end, 1)
    open(p, "w").write(head + block + tail)
    print(f"rendered {path}")
    return True


def main() -> int:
    try:
        bench = load_bench("bench_final.json")
        suites = load_suites()
    except (OSError, json.JSONDecodeError) as e:
        print(f"ERROR: cannot load artifacts: {e}", file=sys.stderr)
        return 1
    try:
        density = load_bench("density.json")
    except (OSError, json.JSONDecodeError):
        density = None
    ok = splice("COMPONENTS.md", render_components(suites, bench, density))
    ok &= splice("BASELINE.md", render_baseline(bench))
    try:
        ab = load_bench("BENCH_r06_AB.json")
    except (OSError, json.JSONDecodeError):
        ab = None  # pre-round-6 trees have no A/B artifact
    if ab is not None:
        ok &= splice("COMPONENTS.md", render_r6_ab(ab), AB_BEGIN, AB_END)
    try:
        r9 = load_bench("BENCH_r09_100K.json")
    except (OSError, json.JSONDecodeError):
        r9 = None  # pre-round-9 trees have no live-100k artifact
    if r9 is not None:
        ok &= splice("COMPONENTS.md", render_r9_100k(r9), R9_BEGIN, R9_END)
    try:
        r12 = load_bench("BENCH_r12_AB.json")
    except (OSError, json.JSONDecodeError):
        r12 = None  # pre-round-12 trees have no coupled-pipeline artifact
    if r12 is not None:
        ok &= splice("COMPONENTS.md", render_r12_ab(r12), R12_BEGIN, R12_END)
    try:
        r15 = load_bench("BENCH_r15_LATENCY.json")
    except (OSError, json.JSONDecodeError):
        r15 = None  # pre-round-15 trees have no latency A/B artifact
    if r15 is not None:
        ok &= splice("COMPONENTS.md", render_r15_latency(r15),
                     R15_BEGIN, R15_END)
    try:
        r16 = load_bench("BENCH_r16_REPLICA.json")
    except (OSError, json.JSONDecodeError):
        r16 = None  # pre-round-16 trees have no replication artifact
    if r16 is not None:
        ok &= splice("COMPONENTS.md", render_r16_replica(r16),
                     R16_BEGIN, R16_END)
    try:
        r18 = load_bench("BENCH_r18_DRA.json")
    except (OSError, json.JSONDecodeError):
        r18 = None  # pre-round-18 trees have no DRA artifact
    if r18 is not None:
        ok &= splice("COMPONENTS.md", render_r18_dra(r18),
                     R18_BEGIN, R18_END)
    try:
        r19 = load_bench("BENCH_r19_WIRE.json")
    except (OSError, json.JSONDecodeError):
        r19 = None  # pre-round-19 trees have no wire-codec artifact
    if r19 is not None:
        ok &= splice("COMPONENTS.md", render_r19_wire(r19),
                     R19_BEGIN, R19_END)
    try:
        r20 = load_bench("BENCH_r20_CRD.json")
    except (OSError, json.JSONDecodeError):
        r20 = None  # pre-round-20 trees have no CRD artifact
    if r20 is not None:
        ok &= splice("COMPONENTS.md", render_r20_crd(r20),
                     R20_BEGIN, R20_END)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
