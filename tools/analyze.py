#!/usr/bin/env python
"""Static invariant analyzer CLI — kubernetes_tpu/analysis front end.

  python tools/analyze.py                 human report of all findings
  python tools/analyze.py --json          JSON report (machine consumers)
  python tools/analyze.py --check         gate mode: exit 1 on findings NOT
                                          grandfathered in
                                          analysis_baseline.json, or on
                                          stale baseline entries (the
                                          ratchet only shrinks)
  python tools/analyze.py --write-baseline  rewrite the baseline from the
                                          current findings (do this after
                                          FIXING sites, never to absorb
                                          new violations)
  --checks a,b  run a subset; --paths P ...  scan other roots (fixtures)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubernetes_tpu.analysis import baseline as baseline_mod  # noqa: E402
from kubernetes_tpu.analysis.core import (  # noqa: E402
    DEFAULT_SCAN_PATHS,
    load_project,
    run_checks,
)
from kubernetes_tpu.analysis.registry import default_checks  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def human_report(findings, checks) -> str:
    lines = []
    by_check = Counter(f.check for f in findings)
    for check in checks:
        n = by_check.get(check.name, 0)
        lines.append(f"== {check.name}: {n} finding(s) — {check.description}")
        for f in findings:
            if f.check == check.name:
                lines.append(f"  {f.location()} [{f.rule}]")
                lines.append(f"      {f.message}")
                if f.snippet:
                    lines.append(f"      > {f.snippet}")
    lines.append(f"total: {len(findings)} finding(s) across "
                 f"{len(checks)} check(s)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true", help="JSON report")
    ap.add_argument("--check", action="store_true",
                    help="gate against the committed baseline")
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--baseline",
                    default=os.path.join(REPO_ROOT,
                                         baseline_mod.BASELINE_FILENAME))
    ap.add_argument("--checks", default="",
                    help="comma-separated subset of registered checks")
    ap.add_argument("--paths", nargs="*", default=None,
                    help="roots to scan (default: %s)"
                         % (DEFAULT_SCAN_PATHS,))
    args = ap.parse_args(argv)

    checks = default_checks(
        [c for c in args.checks.split(",") if c] if args.checks else ())
    project = load_project(REPO_ROOT, args.paths or DEFAULT_SCAN_PATHS)
    findings = run_checks(project, checks)

    if args.write_baseline:
        if args.checks or args.paths:
            print("refusing --write-baseline with --checks/--paths: a "
                  "subset run would clobber every other check's "
                  "grandfathered entries; rerun without subset flags.",
                  file=sys.stderr)
            return 2
        baseline_mod.write(findings, args.baseline)
        print(f"wrote {len(findings)} finding(s) "
              f"({len(baseline_mod.baseline_counts(findings))} keys) to "
              f"{args.baseline}")
        return 0

    if args.json:
        print(json.dumps({
            "findings": [f.__dict__ for f in findings],
            "by_check": dict(Counter(f.check for f in findings)),
        }, indent=1))
    else:
        print(human_report(findings, checks))

    if not args.check:
        return 0

    base = baseline_mod.load(args.baseline)
    # a subset run must not misread the rest of the baseline as stale:
    # restrict the comparison to the checks actually run, and skip stale
    # enforcement entirely on a partial --paths scan (live counts for
    # unscanned files are legitimately zero)
    run_names = {c.name for c in checks}
    base = {k: v for k, v in base.items()
            if k.split("::", 1)[0] in run_names}
    new, stale = baseline_mod.diff(findings, base)
    if args.paths:
        stale = []
    if new:
        print(f"\nFAIL: {len(new)} NEW violation(s) beyond the baseline:",
              file=sys.stderr)
        for f in new:
            print(f"  {f.location()} [{f.check}/{f.rule}] {f.message}",
                  file=sys.stderr)
        print("fix them (preferred), or consciously re-baseline with "
              "--write-baseline and justify it in the PR.", file=sys.stderr)
        return 1
    if stale:
        print(f"\nFAIL: {len(stale)} STALE baseline entr(ies) — the "
              f"violations were fixed; shrink the baseline so they stay "
              f"fixed (tools/analyze.py --write-baseline):", file=sys.stderr)
        for k in stale:
            print(f"  {k}", file=sys.stderr)
        return 1
    print(f"\nOK: all {len(findings)} finding(s) grandfathered; "
          f"baseline is tight.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
