#!/usr/bin/env python
"""Static invariant analyzer CLI — kubernetes_tpu/analysis front end.

  python tools/analyze.py                 human report of all findings
  python tools/analyze.py --json          JSON report (machine consumers)
  python tools/analyze.py --check all     gate mode: exit 1 on findings NOT
                                          grandfathered in
                                          analysis_baseline.json (which is
                                          ZERO findings — the ratchet was
                                          burned empty), or on stale
                                          baseline entries (the ratchet
                                          only shrinks).  ``--check`` alone
                                          means ``--check all``; a comma
                                          list gates that subset only.
  python tools/analyze.py --diff REF      analyze the FULL tree (the
                                          interprocedural checks need
                                          whole-project context) but gate/
                                          report only findings in files
                                          changed vs merge-base(HEAD, REF)
                                          — the fast pre-commit signal
  python tools/analyze.py --write-baseline  rewrite the baseline from the
                                          current findings (do this after
                                          FIXING sites, never to absorb
                                          new violations — keep it EMPTY)
  python tools/analyze.py --report-ownership  dump the thread-ownership
                                          engine's per-field role map
                                          (class → field → roles/
                                          classification) and exit
  --checks a,b  run a subset; --paths P ...  scan other roots (fixtures)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubernetes_tpu.analysis import baseline as baseline_mod  # noqa: E402
from kubernetes_tpu.analysis.core import (  # noqa: E402
    DEFAULT_SCAN_PATHS,
    load_project,
    run_checks,
)
from kubernetes_tpu.analysis.registry import default_checks  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def human_report(findings, checks) -> str:
    lines = []
    by_check = Counter(f.check for f in findings)
    names = [c.name for c in checks]
    # engine-level findings (the suppression lint) have no Check object —
    # give them their own section instead of hiding them in the total
    extra = sorted(set(by_check) - set(names))
    descr = {c.name: c.description for c in checks}
    descr.setdefault("suppression",
                     "ktpu-analysis ignore-comment lint (justification "
                     "required; no unknown checks; no stale ignores)")
    for name in names + extra:
        n = by_check.get(name, 0)
        lines.append(f"== {name}: {n} finding(s) — "
                     f"{descr.get(name, '(engine)')}")
        for f in findings:
            if f.check == name:
                lines.append(f"  {f.location()} [{f.rule}]")
                lines.append(f"      {f.message}")
                if f.snippet:
                    lines.append(f"      > {f.snippet}")
    lines.append(f"total: {len(findings)} finding(s) across "
                 f"{len(checks)} check(s)")
    return "\n".join(lines)


def changed_files(ref: str):
    """Repo-relative .py paths changed vs merge-base(HEAD, ref), plus
    untracked ones; None when git can't answer (caller falls back to the
    full-tree gate — fail CLOSED, not open)."""
    def git(*args):
        return subprocess.run(
            ["git", *args], capture_output=True, text=True, cwd=REPO_ROOT)

    mb = git("merge-base", "HEAD", ref)
    base = mb.stdout.strip() if mb.returncode == 0 else None
    if base is None:
        # the ref may still be a valid commit without a merge-base query
        # (shallow clone): try it directly
        if git("rev-parse", "--verify", ref).returncode != 0:
            return None
        base = ref
    diff = git("diff", "--name-only", base, "--")
    if diff.returncode != 0:
        return None
    out = {ln.strip() for ln in diff.stdout.splitlines() if ln.strip()}
    untracked = git("ls-files", "--others", "--exclude-standard")
    if untracked.returncode == 0:
        out |= {ln.strip() for ln in untracked.stdout.splitlines()
                if ln.strip()}
    return {p for p in out if p.endswith(".py")}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true", help="JSON report")
    ap.add_argument("--check", nargs="?", const="all", default=None,
                    metavar="all|c1,c2",
                    help="gate against the committed baseline; 'all' "
                         "(default) gates every registered check, a comma "
                         "list gates that subset")
    ap.add_argument("--diff", metavar="REF",
                    help="report/gate only findings in files changed vs "
                         "merge-base(HEAD, REF); analysis still runs over "
                         "the full tree for interprocedural context")
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--baseline",
                    default=os.path.join(REPO_ROOT,
                                         baseline_mod.BASELINE_FILENAME))
    ap.add_argument("--checks", default="",
                    help="comma-separated subset of registered checks")
    ap.add_argument("--paths", nargs="*", default=None,
                    help="roots to scan (default: %s)"
                         % (DEFAULT_SCAN_PATHS,))
    ap.add_argument("--report-ownership", action="store_true",
                    help="dump the thread-ownership role map (class → "
                         "field → write/read roles + classification) as "
                         "JSON and exit; the same map the runtime access "
                         "sanitizer verifies against")
    args = ap.parse_args(argv)

    if args.report_ownership:
        from kubernetes_tpu.analysis.threads import thread_analysis_for
        project = load_project(REPO_ROOT, args.paths or DEFAULT_SCAN_PATHS)
        print(json.dumps(thread_analysis_for(project).ownership_report(),
                         indent=1, sort_keys=True))
        return 0

    subset = [c for c in args.checks.split(",") if c]
    if args.check not in (None, "all"):
        if subset:
            print("--check <subset> and --checks are mutually exclusive; "
                  "pick one spelling.", file=sys.stderr)
            return 2
        subset = [c for c in args.check.split(",") if c]
    checks = default_checks(subset)
    project = load_project(REPO_ROOT, args.paths or DEFAULT_SCAN_PATHS)
    findings = run_checks(project, checks)

    scoped = findings
    diff_scope = None
    if args.diff:
        diff_scope = changed_files(args.diff)
        if diff_scope is None:
            print(f"--diff {args.diff}: git could not resolve a merge "
                  f"base; falling back to the FULL-tree gate.",
                  file=sys.stderr)
        else:
            scoped = [f for f in findings if f.path in diff_scope]

    if args.write_baseline:
        if subset or args.paths or args.diff:
            print("refusing --write-baseline with --checks/--paths/--diff: "
                  "a partial run would clobber every other check's "
                  "grandfathered entries; rerun without subset flags.",
                  file=sys.stderr)
            return 2
        baseline_mod.write(findings, args.baseline)
        print(f"wrote {len(findings)} finding(s) "
              f"({len(baseline_mod.baseline_counts(findings))} keys) to "
              f"{args.baseline}")
        return 0

    if args.json:
        print(json.dumps({
            "findings": [f.__dict__ for f in scoped],
            "by_check": dict(Counter(f.check for f in scoped)),
            "diff_scope": sorted(diff_scope) if diff_scope is not None
            else None,
        }, indent=1))
    else:
        print(human_report(scoped, checks))

    if args.check is None:
        return 0

    base = baseline_mod.load(args.baseline)
    # a subset run must not misread the rest of the baseline as stale:
    # restrict the comparison to the checks actually run (plus the
    # engine-level suppression lint, which always runs), and skip stale
    # enforcement entirely on partial --paths/--diff scans (live counts
    # for unscanned/unchanged files are legitimately zero)
    run_names = {c.name for c in checks} | {"suppression"}
    base = {k: v for k, v in base.items()
            if k.split("::", 1)[0] in run_names}
    if diff_scope is not None:
        base = {k: v for k, v in base.items()
                if k.split("::")[1] in diff_scope}
    new, stale = baseline_mod.diff(scoped, base)
    if args.paths or diff_scope is not None:
        stale = []
    if new:
        print(f"\nFAIL: {len(new)} NEW violation(s) beyond the baseline:",
              file=sys.stderr)
        for f in new:
            print(f"  {f.location()} [{f.check}/{f.rule}] {f.message}",
                  file=sys.stderr)
        print("fix them (preferred), or add a `ktpu-analysis: "
              "ignore[check] -- justification` suppression and defend it "
              "in the PR; the baseline stays EMPTY.", file=sys.stderr)
        return 1
    if stale:
        print(f"\nFAIL: {len(stale)} STALE baseline entr(ies) — the "
              f"violations were fixed; shrink the baseline so they stay "
              f"fixed (tools/analyze.py --write-baseline):", file=sys.stderr)
        for k in stale:
            print(f"  {k}", file=sys.stderr)
        return 1
    scope_note = (f" in {len(diff_scope)} changed file(s)"
                  if diff_scope is not None else "")
    print(f"\nOK: {len(scoped)} finding(s){scope_note}; baseline is tight.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
