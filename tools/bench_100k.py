"""BENCH_r09_100K.json: the live 100k suite vs the one-shot artifact, A/B.

Two arms, each its own subprocess (they need different device topologies —
the baseline replays SCALE_100K_EXEC's virtual 8-device mesh, the live
suite runs the scheduler's default backend):

  baseline — the SCALE_100K_EXEC configuration re-MEASURED on this
    hardware: the sharded filter+score+greedy-assign one-shot at 100,352
    nodes, warm step timed.  Greedy arm only (the auction arm costs ~20
    CI-host minutes and is not the committed 101.8s baseline number).
  live — bench.py over NorthStar/100kNodes (perf/workloads.py): the full
    control plane scheduling 2000 pods end to end at the same node count.

The committed ratio compares warm ASSIGNMENT throughput: the baseline's
256-pod warm step (pods / warm_assign_step_seconds) against the live
suite's end-to-end SchedulingThroughput — the live number additionally
carries snapshot sync, queue, binding and store writes, so the ratio
UNDERSTATES the assignment-path win.

Usage: python tools/bench_100k.py [--skip-baseline]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_BASELINE_SNIPPET = r"""
import json, time
import numpy as np
from __graft_entry__ import _build_problem, _provision_devices, \
    _memory_analysis_dict

devices = _provision_devices(8)
import jax, jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from kubernetes_tpu.parallel import node_sharded_mesh
from kubernetes_tpu.parallel.mesh import NODE_AXIS, replicate
from kubernetes_tpu.state.encoding import _NODE_ARRAYS

mesh = node_sharded_mesh(devices)
fw, batch, dsnap, dyn, host_auxes = _build_problem(
    n_nodes=64 * 8, n_sched=8 * 8, n_pending=256)
n_small = dsnap.num_nodes
reps = 12_544 * 8 // n_small
n_big = n_small * reps

def tile(x, axis):
    arr = np.asarray(x)
    return np.concatenate([arr] * reps, axis=axis)

node_fields = set(_NODE_ARRAYS)
snap_vals, snap_shard = {}, {}
for name in dsnap.__dataclass_fields__:
    arr = getattr(dsnap, name)
    if name in node_fields:
        snap_vals[name] = tile(arr, 0)
        snap_shard[name] = NamedSharding(
            mesh, P(NODE_AXIS, *([None] * (np.asarray(arr).ndim - 1))))
    else:
        snap_vals[name] = np.asarray(arr)
        snap_shard[name] = replicate(mesh)
big_snap = type(dsnap)(**{
    k: jax.device_put(v, snap_shard[k]) for k, v in snap_vals.items()})
big_dyn = jax.tree_util.tree_map(
    lambda x: jax.device_put(
        tile(x, 0),
        NamedSharding(mesh, P(NODE_AXIS, *([None] * (x.ndim - 1))))),
    dyn)

def grow_aux(x):
    if hasattr(x, "shape") and np.asarray(x).ndim >= 1 \
            and np.asarray(x).shape[-1] == n_small:
        arr = tile(x, -1)
        return jax.device_put(arr, NamedSharding(
            mesh, P(*([None] * (arr.ndim - 1) + [NODE_AXIS]))))
    return jax.device_put(np.asarray(x), replicate(mesh)) \
        if hasattr(x, "shape") else x

big_aux = jax.tree_util.tree_map(grow_aux, host_auxes)
big_batch = jax.tree_util.tree_map(
    lambda x: jax.device_put(np.asarray(x), replicate(mesh))
    if hasattr(x, "shape") else x, batch)
order = jnp.arange(batch.size)

def greedy_step(batch, dsnap, dyn, host_auxes, order):
    auxes = fw.prepare(batch, dsnap, dyn, host_auxes)
    return fw.greedy_assign(batch, dsnap, dyn, auxes, order)

with mesh:
    args = (big_batch, big_snap, big_dyn, big_aux, order)
    t0 = time.perf_counter()
    compiled = jax.jit(greedy_step).lower(*args).compile()
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    res = compiled(*args)
    jax.block_until_ready(res.node_row)
    first_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    res = compiled(*args)
    jax.block_until_ready(res.node_row)
    warm_s = time.perf_counter() - t0
rows = np.asarray(res.node_row)
print(json.dumps({
    "config": "SCALE_100K_EXEC greedy arm, re-measured",
    "platform": devices[0].platform,
    "n_devices": 8,
    "nodes": int(n_big),
    "pending_batch": int(batch.size),
    "warm_assign_step_seconds": round(warm_s, 3),
    "first_assign_step_seconds": round(first_s, 3),
    "compile_seconds": round(compile_s, 1),
    "assigned": int((rows >= 0).sum()),
    "warm_assign_pods_per_s": round(int(batch.size) / warm_s, 2),
}))
"""


def run_baseline() -> dict:
    out = subprocess.run(
        [sys.executable, "-c", _BASELINE_SNIPPET], cwd=REPO,
        capture_output=True, text=True, timeout=3600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    if out.returncode != 0:
        raise RuntimeError(f"baseline arm failed:\n{out.stderr[-4000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def run_live() -> dict:
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "BENCH_SUITE": "NorthStar", "BENCH_SIZE": "100kNodes",
           "BENCH_ORACLE_SAMPLE": "4"}
    out = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, capture_output=True,
        text=True, timeout=7200, env=env,
    )
    if out.returncode != 0:
        raise RuntimeError(f"live arm failed:\n{out.stderr[-4000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> int:
    skip_baseline = "--skip-baseline" in sys.argv
    t0 = time.time()
    committed = None
    try:
        with open(os.path.join(REPO, "SCALE_100K_EXEC.json")) as f:
            committed = json.load(f)
        # probe the schema now: a mismatch must disable the optional
        # comparison here, not KeyError after the measurement arms ran
        committed["assign"]["greedy"]["warm_assign_step_seconds"]
        committed["pending_batch"]
    except (OSError, ValueError, KeyError, TypeError) as e:
        committed = None
        # the committed-artifact comparison is optional garnish; the
        # measured A/B below is the result
        print(f"note: no committed SCALE_100K_EXEC baseline ({e})",
              file=sys.stderr)
    result = {"metric": "live_100k_vs_one_shot"}
    if not skip_baseline:
        result["baseline_one_shot"] = run_baseline()
    elif committed is not None:
        result["baseline_one_shot"] = {
            "config": "SCALE_100K_EXEC committed artifact (not re-run)",
            "warm_assign_step_seconds":
                committed["assign"]["greedy"]["warm_assign_step_seconds"],
            "pending_batch": committed["pending_batch"],
            "warm_assign_pods_per_s": round(
                committed["pending_batch"]
                / committed["assign"]["greedy"]["warm_assign_step_seconds"],
                2),
        }
    result["live_suite"] = run_live()
    base = result.get("baseline_one_shot", {}).get("warm_assign_pods_per_s")
    live = result["live_suite"]["detail"]["throughput_pods_per_s"]
    result["live_end_to_end_pods_per_s"] = live
    result["baseline_warm_assign_pods_per_s"] = base
    result["throughput_ratio"] = round(live / base, 1) if base else None
    if committed is not None:
        committed_rate = (
            committed["pending_batch"]
            / committed["assign"]["greedy"]["warm_assign_step_seconds"])
        result["vs_committed_SCALE_100K_EXEC"] = round(
            live / committed_rate, 1)
    result["wall_s"] = round(time.time() - t0, 1)
    path = os.path.join(REPO, "BENCH_r09_100K.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
