"""Full convergence-under-failure soak (the PR's acceptance workload).

Runs the 500-pod HollowCluster workload twice under the seeded
FaultSchedule (≥10% watch drops, 5% write 429s + 500s, CAS-conflict storm,
one ignorable extender hard down) and checks:
  - every pod bound exactly once, zero scheduler crashes;
  - bounded retries (each injected write fault costs exactly one resend);
  - determinism: both runs inject the same faults and pay the same retries.

The tier-1 suite runs a 48-pod variant of the same harness
(tests/test_chaos.py); the 500-pod version is marked `slow` there and runs
here instead:

    python tools/chaos_soak.py [PODS NODES SEED BATCH]
"""

import sys

sys.path.insert(0, ".")

from kubernetes_tpu.chaos.soak import run_soak  # noqa: E402

PODS = int(sys.argv[1]) if len(sys.argv) > 1 else 500
NODES = int(sys.argv[2]) if len(sys.argv) > 2 else 50
SEED = int(sys.argv[3]) if len(sys.argv) > 3 else 7
BATCH = int(sys.argv[4]) if len(sys.argv) > 4 else 64


def report(tag, r):
    status = "CONVERGED" if r.converged else "FAILED"
    print(f"[{tag}] {status}: {r.bound}/{r.pods} bound, "
          f"{r.duplicate_binds} duplicate binds, "
          f"{r.store_retries} retries, {r.informer_relists} relists, "
          f"circuit={r.circuit_state}, {r.wall_seconds:.1f}s")
    print(f"[{tag}] injected: {dict(sorted(r.injected.items()))}")
    return r.converged


r1 = run_soak(PODS, NODES, seed=SEED, batch_size=BATCH)
ok1 = report("run1", r1)
r2 = run_soak(PODS, NODES, seed=SEED, batch_size=BATCH)
ok2 = report("run2", r2)

deterministic = r1.determinism_signature() == r2.determinism_signature()
print(f"deterministic replay: {deterministic}")
if not deterministic:
    print(f"  run1: {r1.determinism_signature()}")
    print(f"  run2: {r2.determinism_signature()}")
sys.exit(0 if (ok1 and ok2 and deterministic) else 1)
