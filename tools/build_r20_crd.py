"""TrainingJobFlow bench artifact → BENCH_r20_CRD.json.

Runs the TrainingJobFlow suite (a tenant-defined TrainingJob custom
resource served through the dynamic-kind plane, expanded by the
controller into PodGroup + member pods + named-device ResourceClaims and
gang-scheduled through the identical warm path as DeviceClaimGang) in
fresh subprocesses and writes the artifact tools/render_perf_docs.py
renders into COMPONENTS.md.

Unlike the older best-pass artifacts, this one keeps the MEDIAN pass and
publishes the full per-pass band (the tunnel-attached chip's weather
moves passes ±2×; a best-pass headline overstates the typical run).

Acceptance (ISSUE 20): TrainingJobs expanded and gang-scheduled end to
end with jobs/s reported, member claims allocated, and zero in-window
compiles (the run_suites.sh gate holds the 5k row to the same bar).

Usage: python tools/build_r20_crd.py [--size SIZE] [--scale F]
       [--passes N] [--out FILE]
"""

import argparse
import json
import os
import statistics
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SUITE = "TrainingJobFlow"


def run_pass(size: str, scale: float) -> dict:
    env = dict(os.environ)
    env.update(BENCH_SUITE=SUITE, BENCH_SIZE=size, BENCH_ORACLE_SAMPLE="2",
               BENCH_SCALE=str(scale))
    out = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=3000, check=True,
    )
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="500Nodes")
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--passes", type=int, default=3,
                    help="passes; the MEDIAN-throughput pass is kept and "
                         "the full band rides along (pass 1 also warms "
                         "the persistent compile cache)")
    ap.add_argument("--out", default="BENCH_r20_CRD.json")
    args = ap.parse_args()

    passes = []
    for i in range(args.passes):
        passes.append(run_pass(args.size, args.scale))
        d = passes[-1]["detail"]
        print(f"pass {i + 1}: {d['throughput_pods_per_s']:.0f} pods/s, "
              f"{d.get('trainingjobs', {}).get('jobs_per_s', 0):.1f} "
              f"jobs/s, {d['xla_compiles_in_window']['count']} compiles",
              file=sys.stderr)

    def thr(p):
        return p["detail"]["throughput_pods_per_s"]

    median = sorted(passes, key=thr)[len(passes) // 2]
    dd = median["detail"]
    gang = dd.get("gang") or {}
    claims = dd.get("dra_claims") or {}
    jobs = dd.get("trainingjobs") or {}
    assert jobs.get("jobs", 0) > 0, "no TrainingJobs completed — bad run"
    assert gang.get("gangs", 0) > 0, "no gangs seated — bad run"
    assert claims.get("allocated", 0) > 0, "no claims allocated — bad run"

    pods_band = sorted(thr(p) for p in passes)
    jobs_band = sorted(
        p["detail"].get("trainingjobs", {}).get("jobs_per_s", 0.0)
        for p in passes)

    import jax

    artifact = {
        "environment": {
            "backend": jax.default_backend(),
            "cpus": os.cpu_count(),
            "note": "all passes in THIS container, fresh subprocess each; "
                    "MEDIAN-throughput pass kept, full band published "
                    "(weather moves passes ±2×)",
        },
        "suite": SUITE,
        "size": args.size,
        "scale": args.scale,
        "pods_per_s": {
            "median": statistics.median(pods_band),
            "band": [pods_band[0], pods_band[-1]],
            "passes": pods_band,
        },
        "jobs_per_s": {
            "median": statistics.median(jobs_band),
            "band": [jobs_band[0], jobs_band[-1]],
            "passes": jobs_band,
        },
        "run": median,
    }
    with open(os.path.join(REPO, args.out), "w") as f:
        json.dump(artifact, f, indent=2)
    print(f"wrote {args.out}: median {dd['throughput_pods_per_s']:.0f} "
          f"pods/s, {jobs.get('jobs', 0)} jobs "
          f"({jobs.get('jobs_per_s', 0):.1f}/s), "
          f"{claims.get('allocated', 0)} claims allocated, "
          f"{dd['xla_compiles_in_window']['count']} in-window compiles",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
