#!/usr/bin/env python
"""WAL crash-survival gate: REAL kill -9, then replay, then the
exactly-once + bit-identical asserts (ISSUE 11 acceptance; run_suites.sh
runs this fail-fast before any perf suite, tests/test_wal.py runs it in
tier-1).

Two child deaths are exercised, each in a fresh subprocess (no simulated
exception — the child dies by SIGKILL at a deterministic point):

  - ``clean``: the child binds K pods through a fsync-every-append WAL and
    SIGKILLs itself immediately after bind K returns — the
    ``crash.mid_bind`` state (store bind landed, every byte fsynced,
    process memory gone);
  - ``torn``: the child arms a torn write on bind K's append, so the WAL
    tail is a half-written record made durable by the dying process —
    replay must checksum-truncate it and surface binds 1..K-1 only.

The parent replays each WAL and asserts:
  1. replay == a never-crashed replica that ran the same surviving ops,
     compared bit-for-bit at the wire-manifest level;
  2. every pod bound EXACTLY once in the replayed history (the store-log
     transition probe);
  3. the truncated log reopens for appends and the remaining binds
     complete — the successor continues where the victim died.

No jax anywhere: the child imports only the store/WAL layers, so the gate
runs in ~2s.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_NODES = 4
N_PODS = 12
N_BIND = 7  # the child dies after (or tearing) this bind


def _mk_world(store):
    from kubernetes_tpu.testutil import make_node, make_pod

    # creation timestamps pinned: the child and the parent's never-crashed
    # oracle are different processes, and the bit-identical compare must
    # fail only on REAL divergence, not on wall-clock defaults
    for i in range(N_NODES):
        node = make_node().name(f"n{i}") \
            .capacity({"cpu": "8", "pods": "32"}).obj()
        node.metadata.creation_timestamp = float(i + 1)  # 0.0 is wire-omitted
        node.metadata.uid = f"n{i}"  # the default rides a process counter
        store.create("Node", node)
    for i in range(N_PODS):
        store.create("Pod", make_pod().name(f"p{i}").uid(f"p{i}")
                     .namespace("default").req({"cpu": "1"})
                     .creation_timestamp(100.0 + i).obj())


def child(wal_dir: str, torn: bool) -> None:
    from kubernetes_tpu.chaos import FaultSchedule, install_crash_schedule
    from kubernetes_tpu.sim.store import ObjectStore
    from kubernetes_tpu.sim.wal import WriteAheadLog

    wal = WriteAheadLog(os.path.join(wal_dir, "store.wal"), fsync_every=1)
    store = ObjectStore(wal=wal)
    _mk_world(store)
    if torn:
        fs = FaultSchedule()
        fs.arm_torn_write(at_append=N_BIND)  # appends past the world setup
        install_crash_schedule(fs)
        # count only bind appends toward the arming: consume the setup
        # appends' positions by arming RELATIVE (arm_torn_write already
        # armed relative to appends seen so far — world setup happened
        # before, so bind N_BIND is the N_BIND-th future append)
    try:
        for i in range(N_BIND):
            store.bind_pod("default", f"p{i}", f"n{i % N_NODES}")
    # ktpu-analysis: ignore[exception-hygiene] -- the handler's whole body is os.kill(SIGKILL): the torn-write ProcessCrash is converted into REAL process death, which is the point of this gate — nothing is swallowed, the process ceases
    except BaseException:
        # the torn append "killed" us — make it a REAL death so the parent
        # sees the same SIGKILL exit either way
        os.kill(os.getpid(), signal.SIGKILL)
    # clean variant: store bind landed + fsynced, bookkeeping dies here
    os.kill(os.getpid(), signal.SIGKILL)


def _manifests(store, scheme):
    from kubernetes_tpu.api.serialize import to_manifest

    return {k: to_manifest(o, scheme) for k, o in store._objects.items()}


def _bind_counts(store):
    """(pod name) → unbound→bound transitions in the replayed history."""
    node_of, counts = {}, {}
    for ev in store._log:
        if ev.kind != "Pod":
            continue
        name = ev.obj.metadata.name
        nn = ev.obj.spec.node_name or None
        if nn is not None and node_of.get(name) is None:
            counts[name] = counts.get(name, 0) + 1
        node_of[name] = nn
    return counts


def run_variant(torn: bool) -> dict:
    from kubernetes_tpu.api.scheme import default_scheme
    from kubernetes_tpu.sim.store import ObjectStore
    from kubernetes_tpu.sim.wal import WriteAheadLog, replay_on_boot

    scheme = default_scheme()
    wal_dir = tempfile.mkdtemp(prefix="walgate-")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", wal_dir]
        + (["--torn"] if torn else []),
        timeout=120, capture_output=True)
    assert proc.returncode == -signal.SIGKILL, (
        f"child exited {proc.returncode}, wanted SIGKILL; "
        f"stderr: {proc.stderr.decode()[-2000:]}")
    path = os.path.join(wal_dir, "store.wal")
    replay = replay_on_boot(path, scheme=scheme)
    survived = N_BIND - 1 if torn else N_BIND
    assert replay.truncated_tail == torn, replay
    # never-crashed replica running the same surviving ops
    oracle = ObjectStore()
    _mk_world(oracle)
    for i in range(survived):
        oracle.bind_pod("default", f"p{i}", f"n{i % N_NODES}")
    assert _manifests(replay.store, scheme) == _manifests(oracle, scheme), \
        "replayed store != never-crashed replica"
    counts = _bind_counts(replay.store)
    assert counts == {f"p{i}": 1 for i in range(survived)}, counts
    # the successor continues on the SAME (truncated) log file
    replay.store.wal = WriteAheadLog(path, fsync_every=1)
    for i in range(survived, N_PODS):
        assert replay.store.bind_pod("default", f"p{i}", f"n{i % N_NODES}")
    final = replay_on_boot(path, scheme=scheme)
    done = ObjectStore()
    _mk_world(done)
    for i in range(N_PODS):
        done.bind_pod("default", f"p{i}", f"n{i % N_NODES}")
    assert _manifests(final.store, scheme) == _manifests(done, scheme), \
        "post-recovery store != never-crashed full run"
    assert _bind_counts(final.store) == {f"p{i}": 1 for i in range(N_PODS)}
    return {"variant": "torn" if torn else "clean",
            "records_replayed": replay.records_applied,
            "truncated_tail": replay.truncated_tail,
            "binds_survived": survived}


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child(sys.argv[2], torn="--torn" in sys.argv[3:])
        return 1  # unreachable: the child SIGKILLs itself
    out = [run_variant(torn=False), run_variant(torn=True)]
    print(json.dumps({"wal_crash_gate": "PASS", "variants": out}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
