#!/usr/bin/env python
"""Tracer-overhead A/B microbench (ISSUE-14 gate: disabled-tracer overhead
< 1% on SchedulingBasic).

Three measurements, one JSON line (committed as BENCH_r14_TRACE_OVERHEAD.json
by the PR that ships the tracer; run_suites.sh re-runs and re-gates it):

  1. guard microcost — the disabled tracer's ENTIRE hot-path footprint is
     ``tracer.enabled`` attribute reads (constant False) plus the rare
     unguarded ``tracer.span()``/NOOP_SPAN calls; measure both per-call and
     extrapolate: sites-per-pod × cost-per-site / measured-per-pod-wall.
     This is the "disabled overhead" the gate asserts — it is measurable
     even though the instrumentation cannot be compiled out of the build.
  2. workload A/A (disabled) — a SchedulingBasic-shaped window run twice
     with the default NOOP tracer: the run-to-run noise band, printed so
     the extrapolated number has a scale reference.
  3. workload A/B (enabled) — the same window with a live tracer +
     in-memory exporter: the ENABLED cost, informational (the perf harness
     runs enabled; suites absorb it knowingly).

Scale via BENCH_TRACE_NODES/PODS (defaults small enough for the 1-core
container; the per-pod denominators normalize the extrapolation).
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# every span-emission site a pod's attempt crosses with the tracer DISABLED
# (counted from scheduler.py's guards): per-batch guards amortize over the
# batch; per-pod guards are the bind-span build + the noop-trace checks.
# Conservative over-count: 24 per pod.
GUARD_SITES_PER_POD = 24


def guard_cost_ns() -> float:
    """Per-call cost of the disabled-tracer guard: an `enabled` attribute
    read plus (worst case) a NOOP_TRACER.span() returning the shared noop
    span."""
    from kubernetes_tpu.component_base.trace import NOOP_TRACER

    n = 1_000_000
    t0 = time.perf_counter()
    for _ in range(n):
        if NOOP_TRACER.enabled:  # the hot-path guard form
            NOOP_TRACER.span("dispatch")
    t_guard = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n):
        NOOP_TRACER.span("dispatch")  # unguarded worst case
    t_span = time.perf_counter() - t0
    # charge the dearer of the two forms per site
    return max(t_guard, t_span) / n * 1e9


def run_window(n_nodes: int, n_pods: int, tracer=None) -> float:
    """One SchedulingBasic-shaped window (default templates, pipeline on);
    returns wall seconds for the measured pods."""
    from kubernetes_tpu.perf.harness import default_node, default_pod
    from kubernetes_tpu.scheduler import TPUScheduler
    from kubernetes_tpu.sim.store import ObjectStore

    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=64, pipeline=True, tracer=tracer)
    sched.presize(n_nodes, n_pods)
    for i in range(n_nodes):
        store.create("Node", default_node(i))
    # warm: compile the program variants outside the measured window
    for i in range(2):
        store.create("Pod", default_pod(900000 + i))
    sched.run_until_idle(max_cycles=8)
    t0 = time.perf_counter()
    for i in range(n_pods):
        store.create("Pod", default_pod(i))
    sched.run_until_idle(max_cycles=4 * (n_pods // 64 + 2))
    wall = time.perf_counter() - t0
    sched.close()
    return wall


def main() -> int:
    from kubernetes_tpu.component_base.trace import InMemoryExporter, Tracer

    n_nodes = int(os.environ.get("BENCH_TRACE_NODES", "200"))
    n_pods = int(os.environ.get("BENCH_TRACE_PODS", "1024"))
    g_ns = guard_cost_ns()

    # interleave passes so drift (thermal, cache) spreads across arms
    walls = {"disabled_a": 0.0, "enabled": 0.0, "disabled_b": 0.0}
    walls["disabled_a"] = run_window(n_nodes, n_pods)
    walls["enabled"] = run_window(
        n_nodes, n_pods, tracer=Tracer(exporters=[InMemoryExporter()]))
    walls["disabled_b"] = run_window(n_nodes, n_pods)

    dis = min(walls["disabled_a"], walls["disabled_b"])
    per_pod_us = dis / n_pods * 1e6
    # the gate: disabled-tracer footprint as a fraction of per-pod cost
    disabled_overhead = (GUARD_SITES_PER_POD * g_ns) / (per_pod_us * 1e3)
    enabled_overhead = walls["enabled"] / dis - 1.0
    noise = abs(walls["disabled_a"] - walls["disabled_b"]) / dis

    out = {
        "metric": "disabled_tracer_overhead_fraction",
        "value": round(disabled_overhead, 6),
        "unit": "fraction",
        "detail": {
            "guard_cost_ns": round(g_ns, 2),
            "guard_sites_per_pod": GUARD_SITES_PER_POD,
            "per_pod_us_disabled": round(per_pod_us, 2),
            "walls_s": {k: round(v, 3) for k, v in walls.items()},
            "enabled_overhead_fraction": round(enabled_overhead, 4),
            "disabled_aa_noise_fraction": round(noise, 4),
            "nodes": n_nodes,
            "pods": n_pods,
            "note": (
                "disabled overhead is extrapolated (guard sites × guard "
                "cost / per-pod wall) because the guards cannot be "
                "compiled out of a Python build; the A/A band shows why a "
                "direct disabled-vs-baseline diff would measure noise"),
        },
    }
    print(json.dumps(out))
    if disabled_overhead >= 0.01:
        print(f"FAIL: disabled-tracer overhead "
              f"{disabled_overhead:.4%} >= 1%", file=sys.stderr)
        return 1
    print(f"OK: disabled-tracer overhead {disabled_overhead:.4%} < 1% "
          f"(enabled: {enabled_overhead:+.2%}, A/A noise {noise:.2%})",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
