"""Does returning the big TSAux outputs from the fused program cost tunnel
time?  Chained timing of full-output vs node_row-only programs for a TSC
batch at 5k nodes.

NOTE: outputs that stay device-resident cost nothing until fetched —
variants must np.asarray every compared leaf (done below via device_get),
not just block on computation, or the bench measures dispatch only."""
import sys, time
sys.path.insert(0, ".")
import numpy as np
import jax

from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.perf.workloads import node_zoned, pod_topology_spread, pod_default, ZONES3
from kubernetes_tpu.framework.runtime import coupling_flags
from kubernetes_tpu.state.encoding import apply_scatter
from kubernetes_tpu.framework.runtime import initial_dynamic_state

N, B, S = 5000, 256, 5000
store = ObjectStore()
sched = TPUScheduler(store, batch_size=B)
sched.presize(N, S + 4 * B)
for i in range(N):
    store.create("Node", node_zoned(ZONES3)(i))
for i in range(S):
    p = pod_default(100000 + i)
    p.spec.node_name = f"node-{i % N:06d}"
    store.create("Pod", p)
for i in range(B):
    store.create("Pod", pod_topology_spread(i))

infos = sched.queue.pop_batch(B)
changed = sched.cache.update_snapshot(sched.snapshot)
sched.encoder.sync(sched.snapshot, changed)
batch = sched.compiler.compile([qi.pod for qi in infos], pad_to=B)
fw = sched._framework("default-scheduler")
host_auxes = fw.host_prepare(batch, sched.snapshot, sched.encoder,
                             namespace_labels=sched.namespace_labels)
dsnap, upd = sched.encoder.to_device_deferred()
nom_rows, nom_req = sched._nominated_arrays(set())
prev = sched._noop_delta(batch)
order = np.arange(batch.size, dtype=np.int32)


def make(variant):
    def prog(batch, dsnap, upd, nom_rows, nom_req, prev, host_auxes, order):
        ds = apply_scatter(dsnap, upd)
        dyn = initial_dynamic_state(ds)
        auxes = fw.prepare(batch, ds, dyn, host_auxes)
        auxes = fw.chain_prev(batch, ds, auxes, prev)
        res = fw.greedy_assign(batch, ds, dyn, auxes, order)
        diag = fw.diagnose_bits(batch, ds, dyn, auxes)
        if variant == "full":
            return res, auxes, ds, dyn, diag
        if variant == "no-aux":
            return res.node_row, ds, diag
        return res.node_row, diag  # minimal: no dsnap chain either

    return jax.jit(prog)


from kubernetes_tpu.utils.compilemon import monitor

monitor.install()
# all three program variants jitted ONCE up front (the recompile-hazard
# check flagged the previous per-iteration `make(variant)` wrap); the
# timing loops below must hit these cached callables, never rebuild
VARIANTS = ("full", "no-aux", "minimal")
JITS = {variant: make(variant) for variant in VARIANTS}

for variant in VARIANTS:
    jt = JITS[variant]
    out = jt(batch, dsnap, upd, nom_rows, nom_req, prev, host_auxes, order)
    jax.block_until_ready(jax.tree_util.tree_leaves(out)[0])
    warm_compiles = monitor.snapshot()[0]
    ds = dsnap
    ts = []
    for _ in range(6):
        t0 = time.perf_counter()
        out = jt(batch, ds, upd, nom_rows, nom_req, prev, host_auxes, order)
        # fetch EVERY leaf: device-resident outputs cost nothing until
        # transferred, so blocking on computation alone measures dispatch
        # only, not the output-size difference this bench exists to compare
        jax.device_get(out)
        ts.append(time.perf_counter() - t0)
        if variant == "full":
            ds = out[2]
        elif variant == "no-aux":
            ds = out[1]
    # the jit hoist must not change compile behavior: after the warm call,
    # the 6-iteration window compiles NOTHING (compilemon regression guard)
    steady_compiles = monitor.snapshot()[0] - warm_compiles
    assert steady_compiles == 0, (
        f"{variant}: {steady_compiles} recompile(s) in steady state — "
        f"shape leak or uncached jit wrap")
    print(f"{variant:8s}:", " ".join(f"{1e3*x:.0f}" for x in ts), "ms")
