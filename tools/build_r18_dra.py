"""DeviceClaimGang bench artifact → BENCH_r18_DRA.json.

Runs the DeviceClaimGang suite (named TPU-device claims riding the gang
anchor-slice path: batched claim Filter/Score planes, Reserve picks named
devices, PreBind CAS-commits allocations) in fresh subprocesses — same
discipline as tools/build_r12_ab.py — and writes the artifact
tools/render_perf_docs.py renders into COMPONENTS.md.  The best-throughput
pass is kept; every pass's pods/s rides along so weather is visible.

Acceptance (ISSUE 18): every gang all-or-nothing with every member's claim
allocated to named chips in ONE slice, claims/s reported, zero in-window
compiles (the run_suites.sh gate holds the 5k row to the same bar).

Usage: python tools/build_r18_dra.py [--size SIZE] [--scale F]
       [--passes N] [--out FILE]
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SUITE = "DeviceClaimGang"


def run_pass(size: str, scale: float) -> dict:
    env = dict(os.environ)
    env.update(BENCH_SUITE=SUITE, BENCH_SIZE=size, BENCH_ORACLE_SAMPLE="2",
               BENCH_SCALE=str(scale))
    out = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=3000, check=True,
    )
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="500Nodes")
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--passes", type=int, default=2,
                    help="passes; best-throughput pass is kept (pass 1 "
                         "also warms the persistent compile cache)")
    ap.add_argument("--out", default="BENCH_r18_DRA.json")
    args = ap.parse_args()

    passes = []
    for i in range(args.passes):
        passes.append(run_pass(args.size, args.scale))
        d = passes[-1]["detail"]
        print(f"pass {i + 1}: {d['throughput_pods_per_s']:.0f} pods/s, "
              f"{d.get('dra_claims', {}).get('claims_per_s', 0):.0f} "
              f"claims/s, {d['xla_compiles_in_window']['count']} compiles",
              file=sys.stderr)

    best = max(passes, key=lambda d: d["detail"]["throughput_pods_per_s"])
    dd = best["detail"]
    gang = dd.get("gang") or {}
    claims = dd.get("dra_claims") or {}
    assert claims.get("allocated", 0) > 0, "no claims allocated — bad run"
    assert gang.get("gangs", 0) > 0, "no gangs seated — bad run"

    import jax

    artifact = {
        "environment": {
            "backend": jax.default_backend(),
            "cpus": os.cpu_count(),
            "note": "all passes in THIS container, fresh subprocess each; "
                    "best-throughput pass kept (weather moves passes ±2×)",
        },
        "suite": SUITE,
        "size": args.size,
        "scale": args.scale,
        "passes_pods_per_s": [
            p["detail"]["throughput_pods_per_s"] for p in passes],
        "run": best,
    }
    with open(os.path.join(REPO, args.out), "w") as f:
        json.dump(artifact, f, indent=2)
    print(f"wrote {args.out}: {dd['throughput_pods_per_s']:.0f} pods/s, "
          f"{gang.get('gangs', 0)} gangs, "
          f"{claims.get('allocated', 0)} claims allocated "
          f"({claims.get('claims_per_s', 0):.0f}/s), "
          f"{dd['xla_compiles_in_window']['count']} in-window compiles",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
