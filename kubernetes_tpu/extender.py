"""Scheduler extender: HTTP/JSON callouts + the TPUScore extender server.

Reference: pkg/scheduler/extender.go — HTTPExtender.Filter :277, .Prioritize
:347, .Bind :389, .send :416; config in apis/config/types.go:246-286
(urlPrefix, filterVerb/prioritizeVerb/bindVerb, weight, nodeCacheCapable,
ignorable, managedResources).

Two halves:
  - ``HTTPExtender``: the CLIENT the TPU scheduler uses to call out-of-process
    extenders at Filter/Prioritize/Bind, merging weighted extender scores into
    the device-computed totals (scheduler.go:1146-1185).
  - ``TPUScoreExtenderServer``: the SERVER that exposes THIS framework's batched
    device scorer over the same protocol, so an *unmodified* kube-scheduler can
    opt in per profile via its extenders config — the sanctioned out-of-process
    integration boundary (SURVEY §2.1 extender row, §7 step 8).
"""

from __future__ import annotations

import http.client
import json
import socket
import threading
import urllib.request
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlparse

from .api import objects as v1


@dataclass
class ExtenderConfig:
    """apis/config/types.go:246-286 subset."""

    url_prefix: str = ""
    filter_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    preempt_verb: str = ""
    weight: int = 1
    node_cache_capable: bool = False
    ignorable: bool = False
    http_timeout: float = 5.0
    # Resource names this extender manages (extender.go:444-471): when
    # non-empty, the extender is only consulted for pods that request or
    # limit at least one of them (IsInterested / hasManagedResources).
    managed_resources: List[str] = field(default_factory=list)


class ExtenderError(Exception):
    pass


class HTTPExtender:
    def __init__(self, cfg: ExtenderConfig):
        self.cfg = cfg
        # pool of idle keep-alive connections, shared across threads: the
        # scheduler's callout ThreadPoolExecutor is per-round, so
        # thread-local connections would be rebuilt (and leaked) each round
        self._pool: List[http.client.HTTPConnection] = []
        self._pool_lock = threading.Lock()

    def close(self) -> None:
        with self._pool_lock:
            conns, self._pool = self._pool, []
        for c in conns:
            c.close()

    @property
    def is_ignorable(self) -> bool:
        return self.cfg.ignorable

    @property
    def supports_preemption(self) -> bool:
        """ProcessPreemption is only attempted when preemptVerb is set
        (extender.go SupportsPreemption)."""
        return bool(self.cfg.preempt_verb)

    def is_interested(self, pod: v1.Pod) -> bool:
        """IsInterested (extender.go:444-471): no managed resources → all
        pods; otherwise any container (incl. init) requesting/limiting one."""
        if not self.cfg.managed_resources:
            return True
        managed = set(self.cfg.managed_resources)
        for c in list(pod.spec.containers) + list(pod.spec.init_containers):
            res = c.resources
            for table in (res.requests, res.limits):
                if table and managed & set(table):
                    return True
        return False

    def process_preemption(
        self, pod: v1.Pod, node_name_to_victims: Dict[str, dict]
    ) -> Dict[str, dict]:
        """ProcessPreemption (extender.go:164-207): ships the candidate
        victim map, receives the subset of nodes the extender accepts
        (possibly with different victims).

        Form follows nodeCacheCapable exactly as the reference client does
        (extender.go convertToNodeNameToMetaVictims): capable extenders get
        metaVictims (uids only), others get full pod objects under
        nodeNameToVictims; both reply forms are parsed.  An error from an
        ignorable extender keeps the original candidates.

        Each ``node_name_to_victims`` entry: {"pods": [v1.Pod],
        "numPDBViolations": int}."""
        if not self.supports_preemption:
            return node_name_to_victims
        if self.cfg.node_cache_capable:
            victims_key = "nodeNameToMetaVictims"
            victims = {
                node: {
                    "pods": [{"uid": p.uid} for p in entry["pods"]],
                    "numPDBViolations": entry["numPDBViolations"],
                }
                for node, entry in node_name_to_victims.items()
            }
        else:
            victims_key = "nodeNameToVictims"
            victims = {
                node: {
                    "pods": [_pod_to_dict(p) for p in entry["pods"]],
                    "numPDBViolations": entry["numPDBViolations"],
                }
                for node, entry in node_name_to_victims.items()
            }
        args = {"pod": _pod_to_dict(pod), victims_key: victims}
        try:
            result = self._send(self.cfg.preempt_verb, args)
        except Exception as e:
            if self.cfg.ignorable:
                return node_name_to_victims
            raise ExtenderError(str(e)) from e
        reply = result.get("nodeNameToMetaVictims") or result.get("nodeNameToVictims") or {}
        out = {}
        for node, meta in reply.items():
            if node not in node_name_to_victims:
                continue
            uids = set()
            for pd in (meta or {}).get("pods", []):
                uid = pd.get("uid") or ((pd.get("metadata") or {}).get("uid"))
                if uid:
                    uids.add(uid)
            by_uid = {p.uid: p for p in node_name_to_victims[node]["pods"]}
            out[node] = {
                "pods": [by_uid[u] for u in uids if u in by_uid],
                "numPDBViolations": (meta or {}).get("numPDBViolations", 0),
            }
        return out

    def _fresh_conn(self) -> http.client.HTTPConnection:
        u = urlparse(self.cfg.url_prefix)
        cls = (http.client.HTTPSConnection if u.scheme == "https"
               else http.client.HTTPConnection)
        c = cls(u.hostname, u.port, timeout=self.cfg.http_timeout)
        c.connect()
        # TCP_NODELAY: the request goes out in multiple small sends; Nagle
        # holding the tail segment for the peer's delayed ACK cost a flat
        # ~40ms per callout (profiled)
        c.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return c

    def _send(self, verb: str, payload: dict) -> dict:
        """POST over a POOLED persistent connection (http.client with
        HTTP/1.1 keep-alive).  urllib opens + tears down a TCP connection
        per request; at scheduler callout rates that connection churn was
        the dominant extender-path cost (profiled ~45ms/callout for a
        trivial in-process extender).  The reference's extender client
        shares one http.Client with keep-alive (extender.go NewHTTPExtender
        → utilnet.SetTransportDefaults) — this is the same discipline."""
        base_path = urlparse(self.cfg.url_prefix).path.rstrip("/")
        path = f"{base_path}/{verb}"
        body = json.dumps(payload).encode()
        headers = {"Content-Type": "application/json",
                   "Connection": "keep-alive"}
        with self._pool_lock:
            conn = self._pool.pop() if self._pool else None
        fresh = conn is None
        if fresh:
            conn = self._fresh_conn()
        for attempt in (0, 1):
            try:
                conn.request("POST", path, body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                if not 200 <= resp.status < 300:
                    conn.close()
                    raise ExtenderError(
                        f"extender {verb}: HTTP {resp.status} "
                        f"{data[:200]!r}")
                with self._pool_lock:
                    if len(self._pool) < 16:
                        self._pool.append(conn)
                        conn = None
                if conn is not None:
                    conn.close()
                return json.loads(data.decode())
            except (http.client.RemoteDisconnected, http.client.BadStatusLine,
                    ConnectionResetError, BrokenPipeError) as e:
                # a pooled keep-alive socket the server idled out — the
                # request never reached a handler, so ONE resend is safe
                # even for side-effecting verbs.  Timeouts and other OS
                # errors are NOT retried (the extender may be mid-request).
                conn.close()
                if attempt or fresh:
                    raise ExtenderError(str(e)) from e
                conn = self._fresh_conn()
            except (OSError, http.client.HTTPException):
                conn.close()
                raise

    def filter(
        self, pod: v1.Pod, node_names: List[str]
    ) -> Tuple[List[str], Dict[str, str]]:
        """→ (feasible node names, failed node → reason). ExtenderArgs uses
        nodenames when nodeCacheCapable (extender.go:277-345)."""
        if not self.cfg.filter_verb:
            return node_names, {}
        args = {"pod": _pod_to_dict(pod), "nodenames": node_names}
        try:
            result = self._send(self.cfg.filter_verb, args)
        except Exception as e:
            if self.cfg.ignorable:
                return node_names, {}
            raise ExtenderError(str(e)) from e
        if result.get("error"):
            raise ExtenderError(result["error"])
        return list(result.get("nodenames") or []), dict(result.get("failedNodes") or {})

    def prioritize(
        self, pod: v1.Pod, node_names: List[str]
    ) -> Dict[str, float]:
        """→ node → weighted score contribution (HostPriorityList × weight,
        scheduler.go:1146-1185)."""
        if not self.cfg.prioritize_verb:
            return {}
        args = {"pod": _pod_to_dict(pod), "nodenames": node_names}
        try:
            result = self._send(self.cfg.prioritize_verb, args)
        except Exception as e:
            if self.cfg.ignorable:
                return {}
            raise ExtenderError(str(e)) from e
        return {
            hp["host"]: hp["score"] * self.cfg.weight
            for hp in result or []
        }

    def bind(self, pod: v1.Pod, node_name: str) -> bool:
        if not self.cfg.bind_verb:
            return False
        result = self._send(self.cfg.bind_verb, {
            "podNamespace": pod.namespace, "podName": pod.metadata.name,
            "podUID": pod.uid, "node": node_name,
        })
        if result.get("error"):
            raise ExtenderError(result["error"])
        return True


def _pod_to_dict(pod: v1.Pod) -> dict:
    """Serialized form cached per pod object: one scheduling round calls
    filter AND prioritize for the same pod (2 serializations), and a pod
    deferred across rounds repeats both.  The cache key is
    (resourceVersion, nodeName): the sim store bumps resourceVersion on
    every update, so in-place mutations that went through the store
    invalidate; nodeName covers the bind subresource path."""
    key = (pod.metadata.resource_version, pod.spec.node_name)
    cached = getattr(pod, "_extender_dict", None)
    if cached is not None and cached[0] == key:
        return cached[1]
    d = _pod_to_dict_uncached(pod)
    try:
        pod._extender_dict = (key, d)
    except Exception:
        pass
    return d


def _pod_to_dict_uncached(pod: v1.Pod) -> dict:
    return {
        "metadata": {
            "name": pod.metadata.name,
            "namespace": pod.namespace,
            "uid": pod.uid,
            "labels": dict(pod.metadata.labels),
        },
        "spec": {
            "schedulerName": pod.spec.scheduler_name,
            "priority": pod.spec.priority,
            "nodeName": pod.spec.node_name,
            "containers": [
                {"name": c.name, "image": c.image,
                 "resources": {"requests": dict(c.resources.requests or {})}}
                for c in pod.spec.containers
            ],
            "nodeSelector": dict(pod.spec.node_selector),
            "tolerations": [
                {"key": t.key, "operator": t.operator, "value": t.value,
                 "effect": t.effect}
                for t in pod.spec.tolerations
            ],
        },
    }


class TPUScoreExtenderServer:
    """Serves this framework's device scorer over the extender protocol.

    Endpoints: POST /filter and /prioritize with ExtenderArgs
    (nodeCacheCapable: node names only).  Backed by a callable
    ``score_fn(pod_dict, node_names) -> (feasible names, {name: score})`` —
    typically TPUScheduler-owned state compiled per request batch.
    """

    def __init__(self, score_fn, host: str = "127.0.0.1", port: int = 0):
        self.score_fn = score_fn
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1: keep-alive lets the scheduler's persistent client
            # connections survive across callouts (Content-Length is always
            # set in _reply, so the framing is complete)
            protocol_version = "HTTP/1.1"
            # handler-level attr (socketserver.StreamRequestHandler.setup
            # reads it): headers and body go out as separate sends, and
            # Nagle holding the body for the client's delayed ACK cost a
            # flat ~44ms per callout (profiled: handler finished in 0.3ms,
            # client saw the reply 44ms later)
            disable_nagle_algorithm = True

            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                args = json.loads(self.rfile.read(length) or b"{}")
                pod = args.get("pod") or {}
                names = list(args.get("nodenames") or [])
                try:
                    feasible, scores = outer.score_fn(pod, names)
                except Exception as e:  # extender protocol error field
                    body = {"error": str(e)}
                    self._reply(body)
                    return
                if self.path.rstrip("/").endswith("filter"):
                    failed = {n: "TPUScore: infeasible" for n in names if n not in feasible}
                    self._reply({"nodenames": list(feasible), "failedNodes": failed})
                else:  # prioritize
                    self._reply([
                        {"host": n, "score": int(scores.get(n, 0))} for n in names
                    ])

            def _reply(self, body):
                data = json.dumps(body).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self):
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
