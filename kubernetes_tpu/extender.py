"""Scheduler extender: HTTP/JSON callouts + the TPUScore extender server.

Reference: pkg/scheduler/extender.go — HTTPExtender.Filter :277, .Prioritize
:347, .Bind :389, .send :416; config in apis/config/types.go:246-286
(urlPrefix, filterVerb/prioritizeVerb/bindVerb, weight, nodeCacheCapable,
ignorable, managedResources).

Two halves:
  - ``HTTPExtender``: the CLIENT the TPU scheduler uses to call out-of-process
    extenders at Filter/Prioritize/Bind, merging weighted extender scores into
    the device-computed totals (scheduler.go:1146-1185).
  - ``TPUScoreExtenderServer``: the SERVER that exposes THIS framework's batched
    device scorer over the same protocol, so an *unmodified* kube-scheduler can
    opt in per profile via its extenders config — the sanctioned out-of-process
    integration boundary (SURVEY §2.1 extender row, §7 step 8).
"""

from __future__ import annotations

import json
import select
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlparse

from .api import objects as v1
from .api import wire
from .metrics import scheduler_metrics as m


@dataclass
class ExtenderConfig:
    """apis/config/types.go:246-286 subset."""

    url_prefix: str = ""
    filter_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    preempt_verb: str = ""
    weight: int = 1
    node_cache_capable: bool = False
    ignorable: bool = False
    http_timeout: float = 5.0
    # Resource names this extender manages (extender.go:444-471): when
    # non-empty, the extender is only consulted for pods that request or
    # limit at least one of them (IsInterested / hasManagedResources).
    managed_resources: List[str] = field(default_factory=list)
    # Circuit breaker (degradation policy, not in the reference config —
    # the reference relies on ignorable alone, which still pays the full
    # http_timeout on EVERY callout during an outage): after
    # ``failure_threshold`` consecutive transport failures the circuit
    # opens and callouts are skipped outright; after
    # ``circuit_reset_seconds`` one half-open probe is let through —
    # success closes the circuit, failure re-opens it.
    failure_threshold: int = 3
    circuit_reset_seconds: float = 30.0


class ExtenderError(Exception):
    pass


# circuit states — also the extender_circuit_state gauge values
CIRCUIT_CLOSED = 0
CIRCUIT_OPEN = 1
CIRCUIT_HALF_OPEN = 2


class CircuitBreaker:
    """Consecutive-failure circuit breaker with a timed half-open probe.

    Thread-safe: the scheduler fans extender callouts across a 16-worker
    pool, and N workers hitting a dead extender must resolve to ONE open
    circuit (and later exactly one half-open probe), not N racing states.
    """

    def __init__(self, failure_threshold: int = 3,
                 reset_seconds: float = 30.0, clock=time.monotonic):
        self.failure_threshold = max(1, failure_threshold)
        self.reset_seconds = reset_seconds
        self.clock = clock
        self._state = CIRCUIT_CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._lock = threading.Lock()

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a call go out now?  OPEN past the reset window transitions
        to HALF_OPEN and admits exactly one probe; further calls are
        refused until that probe resolves via success()/failure()."""
        with self._lock:
            if self._state == CIRCUIT_CLOSED:
                return True
            if self._state == CIRCUIT_OPEN and \
                    self.clock() - self._opened_at >= self.reset_seconds:
                self._state = CIRCUIT_HALF_OPEN
                return True
            return False

    def success(self) -> None:
        with self._lock:
            self._state = CIRCUIT_CLOSED
            self._failures = 0

    def failure(self) -> None:
        with self._lock:
            self._failures += 1
            if (self._state == CIRCUIT_HALF_OPEN
                    or self._failures >= self.failure_threshold):
                self._state = CIRCUIT_OPEN
                self._opened_at = self.clock()


# --- minimal HTTP/1.1 fast path ---------------------------------------------
#
# The stdlib stack costs ~1.9ms per callout on loopback (profiled: BaseHTTP
# RequestHandler re-parses headers through email.parser per request, http.
# client's getresponse builds an HTTPMessage the same way), and the extender
# protocol is 2 callouts × 2 messages per pod — at 1000 pods that tax alone
# was ~4s of GIL time, the dominant SchedulingExtender suite cost after
# round 4's keep-alive/Nagle fixes.  The wire format stays exactly HTTP/1.1
# + JSON (a real kube-scheduler or any external extender interoperates);
# only the endpoint implementations are hand-rolled.  Responses the client
# can't fast-parse (chunked encoding etc.) surface as ExtenderError — the
# ignorable policy then applies, as for any malformed extender reply.


def _read_headers(rfile) -> Optional[Dict[bytes, bytes]]:
    """Read header lines until the blank line; lowercase-keyed dict.
    None on EOF before any header (peer closed a keep-alive socket)."""
    headers: Dict[bytes, bytes] = {}
    while True:
        line = rfile.readline(65536)
        if not line:
            return None
        if line in (b"\r\n", b"\n"):
            return headers
        k, _, v = line.partition(b":")
        headers[k.strip().lower()] = v.strip()


def _read_body(rfile, headers: Dict[bytes, bytes]) -> Optional[bytes]:
    """Content-Length- or chunked-framed body; None when the framing is
    neither — the client surfaces that as ExtenderError (ignorable policy
    applies) and the server drops the connection.

    Chunked matters for interop: a real Go extender writing large JSON
    replies through json.NewEncoder(w) emits Transfer-Encoding: chunked
    (net/http buffers only small handler writes), so rejecting it failed
    every callout against exactly the external extenders this module
    exists for (ADVICE round 5)."""
    te = headers.get(b"transfer-encoding")
    if te is not None:
        if te.strip().lower() != b"chunked":
            return None
        return _read_chunked(rfile)
    cl = headers.get(b"content-length")
    if cl is None:
        return None
    n = int(cl)
    chunks = []
    while n > 0:
        chunk = rfile.read(n)
        if not chunk:
            raise ConnectionResetError("peer closed mid-body")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _read_chunked(rfile) -> Optional[bytes]:
    """RFC 7230 §4.1 chunked decoding: size line (hex, extensions after
    ';' ignored) → chunk data → CRLF, until the 0-size chunk, then trailer
    lines until the blank line.  None on a malformed size line (stream
    desynced — caller treats as unsupported framing and drops the
    connection); ConnectionResetError when the peer closes mid-body."""
    chunks = []
    while True:
        size_line = rfile.readline(65536)
        if not size_line:
            raise ConnectionResetError("peer closed mid-chunk-size")
        try:
            n = int(size_line.split(b";", 1)[0].strip(), 16)
        except ValueError:
            return None
        if n == 0:
            while True:  # trailer section
                t = rfile.readline(65536)
                if not t:
                    raise ConnectionResetError("peer closed in trailers")
                if t in (b"\r\n", b"\n"):
                    return b"".join(chunks)
        remaining = n
        while remaining > 0:
            chunk = rfile.read(remaining)
            if not chunk:
                raise ConnectionResetError("peer closed mid-chunk")
            chunks.append(chunk)
            remaining -= len(chunk)
        crlf = rfile.readline(65536)  # the chunk-terminating CRLF
        if crlf not in (b"\r\n", b"\n"):
            return None


def _conn_stale(sock) -> bool:
    """True when an idle pooled HTTP connection is unusable: a readable
    idle socket means the peer closed it (EOF queued) or desynced the
    stream (unsolicited bytes) — either way a request on it is wasted."""
    try:
        readable, _, _ = select.select([sock], [], [], 0)
    except (OSError, ValueError):
        return True  # closed/invalid fd
    return bool(readable)


class HTTPExtender:
    def __init__(self, cfg: ExtenderConfig, clock=time.monotonic):
        self.cfg = cfg
        # pool of idle keep-alive connections, shared across threads: the
        # scheduler's callout ThreadPoolExecutor is per-round, so
        # thread-local connections would be rebuilt (and leaked) each round
        self._pool: List[tuple] = []  # (socket, buffered reader)
        self._pool_lock = threading.Lock()
        # per-extender circuit breaker (see ExtenderConfig): transport
        # failures trip it; an open circuit skips callouts so an ignorable
        # extender's outage stops costing http_timeout per pod, and a
        # non-ignorable one fails fast into the unschedulable/backoff path
        self.breaker = CircuitBreaker(cfg.failure_threshold,
                                      cfg.circuit_reset_seconds, clock=clock)
        self._publish_circuit()

    def _publish_circuit(self) -> None:
        m.extender_circuit_state.set(self.breaker.state,
                                     (self.cfg.url_prefix,))

    def _circuit_allow(self) -> bool:
        ok = self.breaker.allow()
        self._publish_circuit()
        return ok

    def _circuit_result(self, ok: bool) -> None:
        (self.breaker.success if ok else self.breaker.failure)()
        self._publish_circuit()

    def close(self) -> None:
        with self._pool_lock:
            conns, self._pool = self._pool, []
        for sock, rfile in conns:
            rfile.close()
            sock.close()

    @property
    def is_ignorable(self) -> bool:
        return self.cfg.ignorable

    @property
    def supports_preemption(self) -> bool:
        """ProcessPreemption is only attempted when preemptVerb is set
        (extender.go SupportsPreemption)."""
        return bool(self.cfg.preempt_verb)

    def is_interested(self, pod: v1.Pod) -> bool:
        """IsInterested (extender.go:444-471): no managed resources → all
        pods; otherwise any container (incl. init) requesting/limiting one."""
        if not self.cfg.managed_resources:
            return True
        managed = set(self.cfg.managed_resources)
        for c in list(pod.spec.containers) + list(pod.spec.init_containers):
            res = c.resources
            for table in (res.requests, res.limits):
                if table and managed & set(table):
                    return True
        return False

    def process_preemption(
        self, pod: v1.Pod, node_name_to_victims: Dict[str, dict]
    ) -> Dict[str, dict]:
        """ProcessPreemption (extender.go:164-207): ships the candidate
        victim map, receives the subset of nodes the extender accepts
        (possibly with different victims).

        Form follows nodeCacheCapable exactly as the reference client does
        (extender.go convertToNodeNameToMetaVictims): capable extenders get
        metaVictims (uids only), others get full pod objects under
        nodeNameToVictims; both reply forms are parsed.  An error from an
        ignorable extender keeps the original candidates.

        Each ``node_name_to_victims`` entry: {"pods": [v1.Pod],
        "numPDBViolations": int}."""
        if not self.supports_preemption:
            return node_name_to_victims
        if not self._circuit_allow():
            # checked BEFORE building the victims payload: an open circuit
            # must not pay the per-victim pod serialization it would discard
            if self.cfg.ignorable:
                return node_name_to_victims
            raise ExtenderError(
                f"extender {self.cfg.url_prefix}: circuit open")
        if self.cfg.node_cache_capable:
            victims_key = "nodeNameToMetaVictims"
            victims = {
                node: {
                    "pods": [{"uid": p.uid} for p in entry["pods"]],
                    "numPDBViolations": entry["numPDBViolations"],
                }
                for node, entry in node_name_to_victims.items()
            }
        else:
            victims_key = "nodeNameToVictims"
            victims = {
                node: {
                    "pods": [_pod_to_dict(p) for p in entry["pods"]],
                    "numPDBViolations": entry["numPDBViolations"],
                }
                for node, entry in node_name_to_victims.items()
            }
        args = {"pod": _pod_to_dict(pod), victims_key: victims}
        try:
            result = self._send(self.cfg.preempt_verb, args)
        except Exception as e:
            self._circuit_result(False)
            if self.cfg.ignorable:
                return node_name_to_victims
            raise ExtenderError(str(e)) from e
        self._circuit_result(True)
        reply = result.get("nodeNameToMetaVictims") or result.get("nodeNameToVictims") or {}
        out = {}
        for node, meta in reply.items():
            if node not in node_name_to_victims:
                continue
            uids = set()
            for pd in (meta or {}).get("pods", []):
                uid = pd.get("uid") or ((pd.get("metadata") or {}).get("uid"))
                if uid:
                    uids.add(uid)
            by_uid = {p.uid: p for p in node_name_to_victims[node]["pods"]}
            out[node] = {
                "pods": [by_uid[u] for u in uids if u in by_uid],
                "numPDBViolations": (meta or {}).get("numPDBViolations", 0),
            }
        return out

    def _fresh_conn(self):
        """(socket, buffered reader) with TCP_NODELAY: the request goes out
        in one sendall, but Nagle holding small segments for the peer's
        delayed ACK cost a flat ~40ms per callout (profiled)."""
        u = urlparse(self.cfg.url_prefix)
        sock = socket.create_connection(
            (u.hostname, u.port or (443 if u.scheme == "https" else 80)),
            timeout=self.cfg.http_timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if u.scheme == "https":
            import ssl

            sock = ssl.create_default_context().wrap_socket(
                sock, server_hostname=u.hostname)
        return (sock, sock.makefile("rb"))

    def _send(self, verb: str, payload,
              idempotent: bool = False) -> dict:
        """POST over a POOLED persistent connection — hand-rolled HTTP/1.1
        (see the fast-path note above; the stdlib stack's per-message
        parsing was ~1.9ms of GIL per callout).  Keep-alive with one safe
        resend when a pooled socket was idled out by the server; timeouts
        and mid-request errors are NOT retried (the extender may have
        acted).  The reference's client shares one keep-alive http.Client
        (extender.go NewHTTPExtender -> utilnet.SetTransportDefaults) --
        same discipline, leaner stack.

        ``idempotent`` marks pure-query verbs (filter/prioritize): those may
        be resent even after a PARTIAL response (server reset mid-reply) —
        one transient reset otherwise turns the pod unschedulable and costs
        the suite a 30s backoff window; side-effecting verbs (bind,
        preempt) never resend once any byte arrived (double-bind hazard)."""
        u = urlparse(self.cfg.url_prefix)
        path = f"{u.path.rstrip('/')}/{verb}"
        # pre-encoded bodies (bytes) skip json.dumps: the round walk builds
        # callout bodies from cached pod/name-list fragments, and at ~40KB
        # of JSON per pod-round the encode was a measured slice of the
        # single-core extender suite's wall
        body = payload if isinstance(payload, bytes) \
            else json.dumps(payload).encode()
        # resolved port, matching _fresh_conn: u.port is None for a URL
        # without an explicit port, and "Host: example.com:None" breaks
        # strict servers / vhost routing (ADVICE round 5)
        port = u.port or (443 if u.scheme == "https" else 80)
        head = (
            f"POST {path} HTTP/1.1\r\nHost: {u.hostname}:{port}\r\n"
            f"Content-Type: application/json\r\n"
            f"Content-Length: {len(body)}\r\nConnection: keep-alive\r\n\r\n"
        ).encode()
        with self._pool_lock:
            conn = self._pool.pop() if self._pool else None
        if conn is not None and _conn_stale(conn[0]):
            # the server idled this keep-alive socket out (EOF already
            # queued) or left stray bytes: a zero-timeout readability probe
            # detects it for free, saving the wasted send + the one safe
            # resend the reset path would burn
            conn[1].close()
            conn[0].close()
            conn = None
        fresh = conn is None
        if fresh:
            conn = self._fresh_conn()
        for attempt in (0, 1):
            sock, rfile = conn
            got_bytes = False  # any response byte => handler may have acted
            try:
                sock.sendall(head + body)
                status_line = rfile.readline(65536)
                if not status_line:
                    # ZERO response bytes on a pooled socket the server
                    # idled out: the request never reached a handler, so
                    # ONE resend is safe even for side-effecting verbs.
                    # Any later truncation (reset in headers/body) is NOT
                    # retried — the handler may already have acted (the
                    # double-bind hazard).
                    raise ConnectionResetError("peer closed keep-alive socket")
                got_bytes = True
                parts = status_line.split(None, 2)
                status = int(parts[1])
                headers = _read_headers(rfile)
                if headers is None:
                    raise ExtenderError(
                        f"extender {verb}: peer closed mid-headers")
                data = _read_body(rfile, headers)
                if data is None:
                    # exotic framing (chunked ...): only Content-Length
                    # replies fast-parse; socket state is now unknown
                    raise ExtenderError(
                        f"extender {verb}: unsupported response framing")
                if not 200 <= status < 300:
                    raise ExtenderError(
                        f"extender {verb}: HTTP {status} {data[:200]!r}")
                keep = headers.get(b"connection", b"keep-alive").lower() != b"close"
                if keep:
                    with self._pool_lock:
                        if len(self._pool) < 16:
                            self._pool.append(conn)
                            conn = None
                if conn is not None:
                    rfile.close()
                    sock.close()
                return json.loads(data)
            except (ConnectionResetError, BrokenPipeError) as e:
                rfile.close()
                sock.close()
                if attempt or (got_bytes and not idempotent) \
                        or (fresh and not idempotent):
                    raise ExtenderError(str(e)) from e
                conn = self._fresh_conn()
            except (ValueError, json.JSONDecodeError) as e:
                # malformed status line / Content-Length / JSON — the
                # stream is desynced; close, never resend
                rfile.close()
                sock.close()
                raise ExtenderError(
                    f"extender {verb}: malformed response ({e})") from e
            except (OSError, ExtenderError):
                rfile.close()
                sock.close()
                raise

    def _args_body(self, pod: v1.Pod, node_names: List[str],
                   names_json: Optional[bytes],
                   node_manifests=None) -> bytes:
        """ExtenderArgs wire bytes, assembled from cached fragments.

        nodeCacheCapable extenders get the NAME-LIST form (``nodenames``,
        extender.go:277 convertToNodeNames) — the fast path the suites
        measure; non-capable extenders get full node manifests under
        ``nodes.items`` exactly as the reference client does
        (extender.go:416 ``send`` with ExtenderArgs.Nodes), built through
        the caller-provided ``node_manifests(names) -> bytes`` hook (the
        scheduler caches the encoded manifest list per feasible-set)."""
        pod_json = _pod_to_json(pod)
        if self.cfg.node_cache_capable or node_manifests is None:
            names = names_json if names_json is not None \
                else json.dumps(node_names).encode()
            return b'{"pod":' + pod_json + b',"nodenames":' + names + b"}"
        return (b'{"pod":' + pod_json + b',"nodes":{"items":'
                + node_manifests(node_names) + b"}}")

    def filter(
        self, pod: v1.Pod, node_names: List[str],
        names_json: Optional[bytes] = None, node_manifests=None,
    ) -> Tuple[List[str], Dict[str, str]]:
        """→ (feasible node names, failed node → reason). ExtenderArgs uses
        nodenames when nodeCacheCapable, full manifests otherwise
        (extender.go:277-345); ``names_json``/``node_manifests`` are
        optional pre-encoded fragments (see _args_body)."""
        if not self.cfg.filter_verb:
            return node_names, {}
        if not self._circuit_allow():
            # open circuit: an ignorable extender is SKIPPED (all nodes
            # pass, the cycle proceeds without it — graceful degradation);
            # a non-ignorable one fails fast, sparing the timeout, and the
            # scheduler's callout handler turns that into
            # unschedulable+backoff, never a crashed cycle
            if self.cfg.ignorable:
                return node_names, {}
            raise ExtenderError(
                f"extender {self.cfg.url_prefix}: circuit open")
        body = self._args_body(pod, node_names, names_json, node_manifests)
        try:
            result = self._send(self.cfg.filter_verb, body, idempotent=True)
        except Exception as e:
            self._circuit_result(False)
            if self.cfg.ignorable:
                return node_names, {}
            raise ExtenderError(str(e)) from e
        self._circuit_result(True)
        if result.get("error"):
            # protocol-level error from a HEALTHY extender (it answered):
            # not a transport failure — the circuit stays closed
            raise ExtenderError(result["error"])
        if result.get("nodenames") is not None:
            names = list(result.get("nodenames") or [])
        else:
            # non-capable reply form: full node objects (FilterResult.Nodes)
            names = [
                ((item.get("metadata") or {}).get("name"))
                for item in ((result.get("nodes") or {}).get("items") or [])
            ]
            names = [n for n in names if n]
        return names, dict(result.get("failedNodes") or {})

    def prioritize(
        self, pod: v1.Pod, node_names: List[str],
        names_json: Optional[bytes] = None, node_manifests=None,
    ) -> Dict[str, float]:
        """→ node → weighted score contribution (HostPriorityList × weight,
        scheduler.go:1146-1185).  The reference's ``send`` builds ONE
        ExtenderArgs form per extender for BOTH verbs, so a
        non-nodeCacheCapable extender receives full manifests here too."""
        if not self.cfg.prioritize_verb:
            return {}
        if not self._circuit_allow():
            if self.cfg.ignorable:
                return {}
            raise ExtenderError(
                f"extender {self.cfg.url_prefix}: circuit open")
        body = self._args_body(pod, node_names, names_json, node_manifests)
        try:
            result = self._send(self.cfg.prioritize_verb, body,
                                idempotent=True)
        except Exception as e:
            self._circuit_result(False)
            if self.cfg.ignorable:
                return {}
            raise ExtenderError(str(e)) from e
        self._circuit_result(True)
        return {
            hp["host"]: hp["score"] * self.cfg.weight
            for hp in result or []
        }

    def bind(self, pod: v1.Pod, node_name: str) -> bool:
        if not self.cfg.bind_verb:
            return False
        if not self._circuit_allow():
            raise ExtenderError(
                f"extender {self.cfg.url_prefix}: circuit open")
        try:
            result = self._send(self.cfg.bind_verb, {
                "podNamespace": pod.namespace, "podName": pod.metadata.name,
                "podUID": pod.uid, "node": node_name,
            })
        except Exception:
            self._circuit_result(False)
            raise
        self._circuit_result(True)
        if result.get("error"):
            raise ExtenderError(result["error"])
        return True


def _pod_to_dict(pod: v1.Pod) -> dict:
    """Serialized form memoized per pod object via the shared encode memo
    (api.wire.memo_encode — the one mechanism the watch cache, WAL, and
    HTTP planes use): one scheduling round calls filter AND prioritize for
    the same pod (2 serializations), and a pod deferred across rounds
    repeats both.  The key is (resourceVersion, nodeName): the sim store
    bumps resourceVersion on every update, so in-place mutations that went
    through the store invalidate; nodeName covers the bind subresource
    path."""
    key = (pod.metadata.resource_version, pod.spec.node_name)
    return wire.memo_encode(pod, "_extender_dict", key,
                            lambda: _pod_to_dict_uncached(pod))


def _node_to_dict(node) -> dict:
    """Minimal node manifest for the non-nodeCacheCapable ExtenderArgs
    form (extender.go:416 ships the full node list when the extender
    can't resolve names against its own cache)."""
    return {
        "metadata": {
            "name": node.metadata.name,
            "labels": dict(node.metadata.labels),
        },
        "status": {
            "allocatable": dict(node.status.allocatable or {}),
            "capacity": dict(node.status.capacity or {}),
        },
    }


def _pod_to_json(pod: v1.Pod) -> bytes:
    """json.dumps(_pod_to_dict(pod)) memoized per (resourceVersion,
    nodeName) through the shared encode memo — one round calls filter AND
    prioritize for the same pod, and a pod deferred across rounds repeats
    both; at ~1KB of JSON per encode the re-serialization was a measured
    slice of the single-core extender suite's wall."""
    key = (pod.metadata.resource_version, pod.spec.node_name)
    return wire.memo_encode(pod, "_extender_json", key,
                            lambda: json.dumps(_pod_to_dict(pod)).encode())


def _pod_to_dict_uncached(pod: v1.Pod) -> dict:
    return {
        "metadata": {
            "name": pod.metadata.name,
            "namespace": pod.namespace,
            "uid": pod.uid,
            "labels": dict(pod.metadata.labels),
        },
        "spec": {
            "schedulerName": pod.spec.scheduler_name,
            "priority": pod.spec.priority,
            "nodeName": pod.spec.node_name,
            "containers": [
                {"name": c.name, "image": c.image,
                 "resources": {"requests": dict(c.resources.requests or {})}}
                for c in pod.spec.containers
            ],
            "nodeSelector": dict(pod.spec.node_selector),
            "tolerations": [
                {"key": t.key, "operator": t.operator, "value": t.value,
                 "effect": t.effect}
                for t in pod.spec.tolerations
            ],
        },
    }


class TPUScoreExtenderServer:
    """Serves this framework's device scorer over the extender protocol.

    Endpoints: POST /filter and /prioritize with ExtenderArgs
    (nodeCacheCapable: node names only).  Backed by a callable
    ``score_fn(pod_dict, node_names) -> (feasible names, {name: score})`` —
    typically TPUScheduler-owned state compiled per request batch.
    """

    def __init__(self, score_fn, host: str = "127.0.0.1", port: int = 0):
        import socketserver

        self.score_fn = score_fn
        self._thread: Optional[threading.Thread] = None
        # name → its JSON encoding (quoted/escaped), cached across requests:
        # the same few hundred node names ride every callout, and re-encoding
        # them per response was a measured slice of the single-core extender
        # suite (the server shares the machine with the scheduler there)
        self._name_json: Dict[str, str] = {}
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            # hand-rolled HTTP/1.1 persistent-connection loop (see the
            # fast-path note above BaseHTTPRequestHandler's email.parser
            # header parsing alone was ~0.25ms per request); the whole
            # reply goes out in ONE sendall, which also sidesteps the
            # Nagle/delayed-ACK stall that cost a flat ~44ms per callout
            # before round 4's disable_nagle fix
            disable_nagle_algorithm = True

            def handle(self):
                while True:
                    req_line = self.rfile.readline(65536)
                    if not req_line or not req_line.strip():
                        return  # client closed the keep-alive socket
                    parts = req_line.split(None, 2)
                    if len(parts) < 2:
                        return
                    path = parts[1].decode("latin-1")
                    headers = _read_headers(self.rfile)
                    if headers is None:
                        return
                    data = _read_body(self.rfile, headers)
                    if data is None:
                        return  # unsupported framing: drop the connection
                    try:
                        body = outer._dispatch(path, data)
                        status = b"200 OK"
                    # ktpu-analysis: ignore[exception-hygiene] -- the error is surfaced to the CLIENT as an HTTP 500 with the message in the JSON body (and the connection closes); server-side logging of handler bugs belongs to the caller's circuit breaker
                    except Exception as e:  # handler bug → 500 + close
                        body = json.dumps({"error": str(e)}).encode()
                        status = b"500 Internal Server Error"
                    self.wfile.write(
                        b"HTTP/1.1 " + status
                        + b"\r\nContent-Type: application/json\r\n"
                        + b"Content-Length: " + str(len(body)).encode()
                        + b"\r\nConnection: keep-alive\r\n\r\n" + body
                    )
                    if status[:3] != b"200":
                        return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _dispatch(self, path: str, data: bytes) -> bytes:
        args = json.loads(data or b"{}")
        pod = args.get("pod") or {}
        names = list(args.get("nodenames") or [])
        if not names:
            # non-nodeCacheCapable callers ship full manifests
            # (ExtenderArgs.Nodes) — serve them off the metadata names
            names = [
                ((item.get("metadata") or {}).get("name"))
                for item in ((args.get("nodes") or {}).get("items") or [])
            ]
            names = [n for n in names if n]
        try:
            feasible, scores = self.score_fn(pod, names)
        # ktpu-analysis: ignore[exception-hygiene] -- surfaced via the extender protocol's error field (extenderv1 FilterResult.Error); the scheduler side decides whether that is ignorable
        except Exception as e:  # extender protocol error field
            return json.dumps({"error": str(e)}).encode()
        jname = self._name_json
        if len(jname) > 65536:
            # bound the per-name memo: a server outliving heavy node churn
            # (autoscaling creates uniquely-named nodes forever) must not
            # leak an entry per retired name
            jname.clear()

        def enc(n: str) -> str:
            v = jname.get(n)
            if v is None:
                v = jname[n] = json.dumps(n)
            return v

        if path.rstrip("/").endswith("filter"):
            feas = set(feasible)  # a list membership scan was O(N²)/request
            failed = {n: "TPUScore: infeasible" for n in names
                      if n not in feas}
            return ('{"nodenames":[' + ",".join(enc(n) for n in feasible)
                    + '],"failedNodes":' + json.dumps(failed)
                    + "}").encode()
        return ("[" + ",".join(
            '{"host":%s,"score":%d}' % (enc(n), int(scores.get(n, 0)))
            for n in names
        ) + "]").encode()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self):
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        thread, self._thread = self._thread, None
        if thread is not None:
            # shutdown() returns once serve_forever exits its loop; the
            # bounded join keeps a request already in a handler from
            # leaking the serving thread past stop()
            thread.join(timeout=2.0)


def run_subprocess_score_server(score_fn, port_pipe):
    """Subprocess entry for benchmarks/integration: serve ``score_fn`` over
    the extender protocol and report the bound port.  Lives here (stdlib-
    only imports) so a spawn-context child does NOT re-import the jax stack
    through the perf modules."""
    srv = TPUScoreExtenderServer(score_fn)
    srv.start()
    port_pipe.send(srv.port)
    port_pipe.close()
    import time as _t

    while True:  # until the parent terminates us
        _t.sleep(3600)


def uniform_score_fn(pod_dict, names):
    """Trivial extender body (module-level so subprocess targets can import
    it by name): every node feasible, uniform score."""
    return names, {name: 1 for name in names}
