"""Span tracing + legacy step tracing.

Reference: k8s.io/utils/trace as used in the scheduling hot path (schedulePod
creates a utiltrace and logs if >100ms, scheduler.go:775-816) layered under
component-base/tracing (the OTel TracerProvider wiring, apiserver and
scheduler --tracing-config) — this module is both layers' analog:

  - ``Trace`` keeps the utiltrace step-trace semantics (named steps,
    log_if_long) the scheduler hot path has always used;
  - ``Tracer``/``Span`` is the OTel-shaped span layer: parent links,
    attributes, timestamped events, an injected clock (deterministic in
    tests), and pluggable exporters — an in-memory ring
    (``InMemoryExporter``: tests + ``ktpu trace``), Chrome trace-event
    JSONL (``ChromeTraceExporter``: one artifact per perf-suite run,
    loadable in Perfetto/chrome://tracing), and the log_if_long behavior
    generalized (``ThresholdLogExporter``).

Overhead policy (the hard constraint the scheduler instrumentation relies
on): the module-level ``NOOP_TRACER`` has ``enabled = False`` and its
``span()`` returns one shared ``_NoopSpan`` whose methods do nothing — hot
paths guard every span build behind ``if tracer.enabled:`` so a disabled
tracer costs one attribute read per guard (measured in
tools/bench_trace_overhead.py; gated < 1% of per-pod cost).  Spans are
emitted ONLY off the jitted paths: they bracket dispatch/fetch boundaries,
never traced code — emitting from inside a jit would either fail tracing or
record trace-time, not run-time.

Cross-thread context: a ``SpanContext`` is an explicit value handed through
the pipeline seams (``_InFlight.span_ctx`` → bg-fetch thread → async
extender walk → ``_complete`` → bind phase) — never a thread-local, so the
deep-pipelined scheduler's spans keep their parent links across threads.

SPAN_CATALOG is the closed set of span names this codebase may emit; the
``span-catalog`` static check (analysis/checks/span_catalog.py) fails
tools/analyze.py on any ``tracer.span("name")`` literal outside it and on
any catalog entry no code emits.  The same list is documented in
COMPONENTS.md §Observability (kept in sync by tests/test_trace.py).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

log = logging.getLogger("kubernetes_tpu.trace")

# The closed span-name catalog (see module docstring).  Grouped by layer:
# scheduler attempt tree, control-plane (store/WAL), apiserver request.
SPAN_CATALOG = frozenset({
    # per dispatched batch: the attempt tree root + its phases
    "attempt",          # root: one scheduling attempt (one dispatched batch)
    "queue_wait",       # earliest queue entry -> dispatch pop (per batch)
    "dispatch",         # host dispatch work (t0 -> device program enqueued)
    "snapshot",         # cache.update_snapshot + encoder.sync
    "compile",          # PodBatchCompiler.compile (batch staging, not XLA)
    "host_prepare",     # framework host_prepare (PreFilter/PreScore analog)
    "device_enqueue",   # fused-program dispatch (enqueue only, no fetch)
    "device_wait",      # program enqueue -> decisions host-side (bg fetch)
    "sync_overlap",     # background snapshot/sync + scatter-build (the
                        # off-critical-path prep for the NEXT dispatch,
                        # overlapping the just-dispatched batch's window)
    "extender_rounds",  # the extender round walk (callouts + ledger)
    "complete",         # fetch join + cache assumes (_complete)
    "bind_phase",       # the batch's binding cycle (reserve/permit/bind)
    "bind",             # one pod's reserve->bind segment
    "permit_wait",      # a gang member's Permit hold (held binding cycle)
    # control plane
    "wal_append",       # one WAL record append (durable-before-visible)
    "wal_fsync",        # WAL fsync (cadence or explicit)
    "apiserver_request",  # one HTTP resource request, routing -> response
    "apf_wait",         # flow-control queue wait before a seat was granted
})


class SpanContext:
    """The explicit cross-thread handoff value: identifies a span without
    holding it (the child end of a parent link)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: int, span_id: int):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return f"SpanContext({self.trace_id}, {self.span_id})"


class Span:
    """One timed operation.  Created by ``Tracer.span``; ``finish()`` (or
    context-manager exit) stamps the end and hands it to the exporters."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start", "end",
                 "attrs", "events", "thread", "_tracer")

    def __init__(self, tracer: "Tracer", name: str, trace_id: int,
                 span_id: int, parent_id: Optional[int], start: float,
                 attrs: Optional[dict]):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start
        self.end: Optional[float] = None
        self.attrs: Dict[str, object] = attrs or {}
        self.events: List = []  # (name, at, attrs)
        self.thread = threading.get_ident()

    @property
    def enabled(self) -> bool:
        return True

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def event(self, name: str, **attrs) -> None:
        self.events.append((name, self._tracer.clock(), attrs))

    def duration(self) -> float:
        return (self.end if self.end is not None
                else self._tracer.clock()) - self.start

    def finish(self, end: Optional[float] = None) -> None:
        if self.end is not None:
            return  # idempotent — a finally and an explicit finish may race
        self.end = self._tracer.clock() if end is None else end
        self._tracer._export(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()


class _NoopSpan:
    """The shared disabled span: every method is a no-op, so instrumented
    code may call through unconditionally on paths that are cheap anyway;
    hot paths should guard on ``tracer.enabled`` instead and skip even the
    call."""

    __slots__ = ()
    enabled = False
    name = ""
    attrs: Dict[str, object] = {}

    def context(self) -> Optional[SpanContext]:
        return None

    def set(self, **attrs) -> "_NoopSpan":
        return self

    def event(self, name: str, **attrs) -> None:
        pass

    def duration(self) -> float:
        return 0.0

    def finish(self, end: Optional[float] = None) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Span factory + exporter fan-out.  ``clock`` is injected (tests pass
    a fake; the scheduler passes its own clock so spans and metrics share a
    timeline).  ``enabled`` is the constant hot-path guard — a Tracer built
    with ``enabled=False`` (or ``NOOP_TRACER``) never allocates a Span."""

    def __init__(self, clock=time.perf_counter, exporters=(),
                 enabled: bool = True):
        self.clock = clock
        self.exporters: List = list(exporters)
        self.enabled = enabled
        self._ids = itertools.count(1)

    def span(self, name: str, parent=None, start: Optional[float] = None,
             **attrs):
        """Open a span.  ``parent`` is a Span, a SpanContext (the explicit
        cross-thread handoff), or None (a new root/trace); ``start`` backdates
        the span to an already-taken clock stamp (retroactive spans around
        existing stamps cost nothing on the timed path itself)."""
        if not self.enabled:
            return NOOP_SPAN
        span_id = next(self._ids)
        if parent is None:
            trace_id, parent_id = span_id, None
        else:
            ctx = parent.context() if isinstance(parent, Span) else parent
            if ctx is None:  # noop parent: still record, as a root
                trace_id, parent_id = span_id, None
            else:
                trace_id, parent_id = ctx.trace_id, ctx.span_id
        return Span(self, name, trace_id, span_id, parent_id,
                    self.clock() if start is None else start, attrs or None)

    def _export(self, span: Span) -> None:
        for ex in self.exporters:
            try:
                ex.export(span)
            except Exception as e:  # an exporter fault must never kill the
                # scheduling path it observes — drop the span, say so once
                log.warning("span exporter %s failed: %s: %s",
                            type(ex).__name__, type(e).__name__, e)


class _NoopTracer(Tracer):
    """``NOOP_TRACER``: the production default.  ``enabled`` is False and
    ``span()`` short-circuits to the shared noop span even if a caller
    skipped the guard."""

    def __init__(self):
        super().__init__(enabled=False)

    def span(self, name: str, parent=None, start=None, **attrs):
        return NOOP_SPAN


NOOP_TRACER = _NoopTracer()


# --- exporters ----------------------------------------------------------------


class InMemoryExporter:
    """Bounded ring of finished spans (newest kept), with span-tree
    reconstruction — the backing for tests and ``ktpu trace``."""

    def __init__(self, max_spans: int = 65536):
        self._spans: deque = deque(maxlen=max_spans)
        self._lock = threading.Lock()

    def export(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def trees(self, last: Optional[int] = None,
              root_name: Optional[str] = None):
        """The last N root spans (finish order) as (root, children_of) where
        ``children_of`` maps span_id -> [child spans sorted by start].  A
        root whose children were evicted from the ring still renders (with
        the surviving subset)."""
        spans = self.spans()
        children: Dict[int, List[Span]] = {}
        by_trace: Dict[int, List[Span]] = {}
        for s in spans:
            by_trace.setdefault(s.trace_id, []).append(s)
            if s.parent_id is not None:
                children.setdefault(s.parent_id, []).append(s)
        for kids in children.values():
            kids.sort(key=lambda s: s.start)
        roots = [s for s in spans if s.parent_id is None
                 and (root_name is None or s.name == root_name)]
        if last is not None:
            roots = roots[-last:]
        return [(r, children) for r in roots]

    def attempt_records(self) -> List[dict]:
        """Per-pod phase records off the scheduler's ``attempt`` roots (the
        ``pod_phases`` attribute) — what the perf harness aggregates."""
        out: List[dict] = []
        for s in self.spans():
            if s.name == "attempt" and s.parent_id is None:
                out.extend(s.attrs.get("pod_phases") or ())
        return out


class ChromeTraceExporter:
    """Chrome trace-event JSONL: one complete ("ph": "X") event per span,
    one line each, inside a JSON array that is valid even if the process
    dies mid-write (the trace-event spec explicitly allows an unterminated
    array; Perfetto and chrome://tracing both load it).  Timestamps are the
    tracer clock in µs; ``tid`` is the emitting thread, so cross-thread
    pipeline spans land on their real timelines."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f = open(path, "w")
        self._f.write("[\n")
        self._tids: Dict[int, int] = {}  # thread ident -> small stable tid

    def export(self, span: Span) -> None:
        with self._lock:
            if self._f.closed:
                return
            tid = self._tids.setdefault(span.thread, len(self._tids))
            ev = {
                "name": span.name,
                "cat": "ktpu",
                "ph": "X",
                "ts": round(span.start * 1e6, 3),
                "dur": round(max((span.end or span.start) - span.start, 0.0)
                             * 1e6, 3),
                "pid": 1,
                "tid": tid,
                "args": _jsonable(span.attrs),
            }
            self._f.write(json.dumps(ev) + ",\n")

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                # terminator metadata event closes the array cleanly
                self._f.write(json.dumps(
                    {"name": "trace_end", "ph": "i", "ts": 0, "pid": 1,
                     "tid": 0, "s": "g"}) + "\n]\n")
                self._f.close()


def _jsonable(attrs: dict) -> dict:
    out = {}
    for k, v in (attrs or {}).items():
        if k == "pod_phases":
            # the per-pod record list is a harness aggregation channel, not
            # a display attribute (the tree renderer skips it too): ~10KB
            # of stringified dicts per attempt event would bloat every
            # committed suite artifact
            continue
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[k] = v
        elif isinstance(v, (list, tuple)) and len(v) <= 64:
            out[k] = [x if isinstance(x, (str, int, float, bool)) else str(x)
                      for x in v]
        else:
            out[k] = f"<{type(v).__name__}>"
    return out


class ThresholdLogExporter:
    """``log_if_long`` generalized: buffers a trace's spans until its ROOT
    finishes, then logs the whole tree when the root exceeded
    ``threshold`` seconds — the utiltrace contract at span granularity."""

    def __init__(self, threshold: float = 0.1, max_traces: int = 256):
        self.threshold = threshold
        self._lock = threading.Lock()
        self._by_trace: Dict[int, List[Span]] = {}
        self._order: deque = deque()
        self.max_traces = max_traces
        # traces whose root already flushed: a LATE child (e.g. a gang
        # permit_wait span resolved cycles after its attempt root) must
        # not open a fresh buffer entry no root will ever pop — those dead
        # entries would churn live traces out of the bounded buffer
        self._flushed: deque = deque(maxlen=4 * max_traces)
        self._flushed_set: set = set()

    def export(self, span: Span) -> None:
        with self._lock:
            if span.parent_id is not None and \
                    span.trace_id in self._flushed_set:
                return  # late child of an already-logged trace: drop
            if span.trace_id not in self._by_trace:
                self._by_trace[span.trace_id] = []
                self._order.append(span.trace_id)
                while len(self._order) > self.max_traces:
                    self._by_trace.pop(self._order.popleft(), None)
            self._by_trace[span.trace_id].append(span)
            if span.parent_id is not None:
                return
            spans = self._by_trace.pop(span.trace_id, [])
            if len(self._flushed) == self._flushed.maxlen:
                self._flushed_set.discard(self._flushed[0])
            self._flushed.append(span.trace_id)
            self._flushed_set.add(span.trace_id)
        if span.duration() < self.threshold:
            return
        log.info("%s", render_tree(span, spans))


def render_tree(root: Span, spans: Optional[List[Span]] = None,
                children: Optional[Dict[int, List[Span]]] = None) -> str:
    """Indented tree rendering shared by ThresholdLogExporter and
    ``ktpu trace``: per-span +offset-from-root and duration in ms.  Pass
    either the flat span list (the index is derived) or a pre-built
    ``children`` map (InMemoryExporter.trees already computed one — don't
    rebuild it per root over a 65k-span ring)."""
    if children is None:
        children = {}
        for s in spans or ():
            if s.parent_id is not None:
                children.setdefault(s.parent_id, []).append(s)
        for kids in children.values():
            kids.sort(key=lambda s: s.start)
    lines = [f'span "{root.name}" total={root.duration() * 1e3:.1f}ms '
             f'{_render_attrs(root.attrs)}'.rstrip()]

    def walk(sid: int, depth: int):
        for c in children.get(sid, ()):
            lines.append(
                f"{'  ' * depth}- {c.name} "
                f"+{(c.start - root.start) * 1e3:.1f}ms "
                f"{c.duration() * 1e3:.1f}ms {_render_attrs(c.attrs)}"
                .rstrip())
            walk(c.span_id, depth + 1)

    walk(root.span_id, 1)
    return "\n".join(lines)


def _render_attrs(attrs: dict) -> str:
    shown = {k: v for k, v in (attrs or {}).items() if k != "pod_phases"}
    return " ".join(f"{k}={v}" for k, v in shown.items())


# --- legacy step trace (k8s.io/utils/trace) -----------------------------------


@dataclass
class Step:
    name: str
    at: float


class Trace:
    def __init__(self, name: str, clock=time.perf_counter, **fields):
        self.name = name
        self.fields = fields
        self.clock = clock
        self.start = clock()
        self.steps: List[Step] = []

    def step(self, name: str) -> None:
        self.steps.append(Step(name, self.clock()))

    def total_seconds(self) -> float:
        return self.clock() - self.start

    def log_if_long(self, threshold: float = 0.1) -> Optional[str]:
        """utiltrace semantics: dump all steps when total exceeds threshold."""
        total = self.total_seconds()
        if total < threshold:
            return None
        parts = [f'trace "{self.name}" {self.fields} total={total * 1000:.1f}ms']
        prev = self.start
        for s in self.steps:
            parts.append(f"  step {s.name}: +{(s.at - prev) * 1000:.1f}ms")
            prev = s.at
        msg = "\n".join(parts)
        log.info(msg)
        return msg


@contextlib.contextmanager
def device_profile(path: str):
    """JAX profiler session (the device-side complement to the host spans)."""
    import jax

    jax.profiler.start_trace(path)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
