"""Step tracing (reference: k8s.io/utils/trace as used in the scheduling hot
path — schedulePod creates a trace and logs if >100ms, scheduler.go:775-816;
plus a hook into the JAX profiler as the OTel analog)."""

from __future__ import annotations

import contextlib
import logging
import time
from dataclasses import dataclass, field
from typing import List, Optional

log = logging.getLogger("kubernetes_tpu.trace")


@dataclass
class Step:
    name: str
    at: float


class Trace:
    def __init__(self, name: str, clock=time.perf_counter, **fields):
        self.name = name
        self.fields = fields
        self.clock = clock
        self.start = clock()
        self.steps: List[Step] = []

    def step(self, name: str) -> None:
        self.steps.append(Step(name, self.clock()))

    def total_seconds(self) -> float:
        return self.clock() - self.start

    def log_if_long(self, threshold: float = 0.1) -> Optional[str]:
        """utiltrace semantics: dump all steps when total exceeds threshold."""
        total = self.total_seconds()
        if total < threshold:
            return None
        parts = [f'trace "{self.name}" {self.fields} total={total * 1000:.1f}ms']
        prev = self.start
        for s in self.steps:
            parts.append(f"  step {s.name}: +{(s.at - prev) * 1000:.1f}ms")
            prev = s.at
        msg = "\n".join(parts)
        log.info(msg)
        return msg


@contextlib.contextmanager
def device_profile(path: str):
    """JAX profiler session (the OTel-exporter analog for device work)."""
    import jax

    jax.profiler.start_trace(path)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
