"""Structured, verbosity-gated logging — the klog v2 analog.

Reference: k8s.io/klog/v2 (klog.InfoS / klog.ErrorS / klog.V(n).InfoS used
throughout the scheduler, e.g. verbosity-gated score dumps
pkg/scheduler/scheduler.go:1127-1134).  Mirrors the structured form:
a message plus key=value pairs, gated by a global verbosity level.

Built on the stdlib logging module so output routing/formatting stays
standard; the klog-ish surface is ``InfoS``/``ErrorS``/``V(n)``.
"""

from __future__ import annotations

import logging
import os

_logger = logging.getLogger("kubernetes_tpu")
_verbosity = int(os.environ.get("TPU_SCHED_V", "0"))


def set_verbosity(v: int) -> None:
    global _verbosity
    _verbosity = v


def verbosity() -> int:
    return _verbosity


def _fmt(msg: str, kv: dict) -> str:
    if not kv:
        return msg
    parts = " ".join(f"{k}={v!r}" for k, v in kv.items())
    return f"{msg} {parts}"


def info_s(msg: str, **kv) -> None:
    """klog.InfoS: structured info line."""
    _logger.info(_fmt(msg, kv))


def error_s(err, msg: str, **kv) -> None:
    """klog.ErrorS: structured error line (err first, like the reference)."""
    if err is not None:
        kv = {"err": err, **kv}
    _logger.error(_fmt(msg, kv))


class _Verbose:
    __slots__ = ("enabled",)

    def __init__(self, enabled: bool):
        self.enabled = enabled

    def info_s(self, msg: str, **kv) -> None:
        if self.enabled:
            _logger.info(_fmt(msg, kv))

    def __bool__(self):
        return self.enabled


def V(level: int) -> _Verbose:
    """klog.V(n): returns a gate whose info_s only logs at verbosity ≥ n."""
    return _Verbose(_verbosity >= level)
