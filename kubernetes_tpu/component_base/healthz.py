"""healthz checks (reference: apiserver/pkg/server/healthz; every binary serves
/healthz with named checks)."""

from __future__ import annotations

from typing import Callable, Dict, Tuple


class Healthz:
    def __init__(self):
        self._checks: Dict[str, Callable[[], bool]] = {"ping": lambda: True}

    def add_check(self, name: str, fn: Callable[[], bool]) -> None:
        self._checks[name] = fn

    def check(self) -> Tuple[bool, Dict[str, bool]]:
        results = {}
        for name, fn in self._checks.items():
            try:
                results[name] = bool(fn())
            # ktpu-analysis: ignore[exception-hygiene] -- a raising probe IS the unhealthy signal: check() returns it as False per named check, which /healthz renders — logging here would double-report every scrape
            except Exception:
                results[name] = False
        return all(results.values()), results
