"""healthz/readyz checks (reference: apiserver/pkg/server/healthz; every
binary serves /healthz with named checks, and /readyz separately so a live
process that cannot take traffic yet — informers unsynced, state rebuilding
— is restarted by nobody but routed to by nobody either)."""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple


class Healthz:
    def __init__(self):
        self._checks: Dict[str, Callable[[], bool]] = {"ping": lambda: True}

    def add_check(self, name: str, fn: Callable[[], bool]) -> None:
        self._checks[name] = fn

    def check(self) -> Tuple[bool, Dict[str, bool]]:
        results = {}
        for name, fn in self._checks.items():
            try:
                results[name] = bool(fn())
            # ktpu-analysis: ignore[exception-hygiene] -- a raising probe IS the unhealthy signal: check() returns it as False per named check, which /healthz renders — logging here would double-report every scrape
            except Exception:
                results[name] = False
        return all(results.values()), results


class Readyz:
    """Readiness DISTINCT from liveness: a recovering replica is alive (its
    /healthz checks pass) but must report NotReady until cold-start state
    reconstruction completes, with per-component rebuild progress — the
    reference's informer-HasSynced gating on /readyz
    (apiserver/pkg/server/healthz informer-sync checks).

    Components register with ``begin(name, total)``, advance with
    ``progress``, and finish with ``complete``; the instance is ready when
    every registered component is complete.  A fresh instance with no
    components is ready (nothing is rebuilding).  Single-writer (the
    recovering thread) with GIL-atomic dict reads — scrapers (HTTP handler,
    CLI) only snapshot.
    """

    def __init__(self):
        # name -> (done, total); complete iff done >= total
        self._progress: Dict[str, Tuple[int, int]] = {}

    def begin(self, name: str, total: int = 1) -> None:
        self._progress[name] = (0, max(int(total), 0))

    def begin_all(self, names, total: int = 1) -> None:
        """Enter a rebuild atomically: every component lands NotReady in ONE
        dict assignment, so a concurrent scrape can never observe the empty
        (= ready) window between a reset and the first begin()."""
        self._progress = {name: (0, max(int(total), 0)) for name in names}

    def progress(self, name: str, done: int,
                 total: Optional[int] = None) -> None:
        cur = self._progress.get(name, (0, 1))
        self._progress[name] = (int(done),
                                cur[1] if total is None else int(total))

    def complete(self, name: str) -> None:
        _, total = self._progress.get(name, (0, 1))
        self._progress[name] = (total, total)

    def reset(self) -> None:
        """Back to no-components — which is READY (nothing is rebuilding).
        A replica entering a fresh reconstruction must use ``begin_all``
        (one atomic assignment), never reset-then-begin: the in-between
        empty dict would serve a ready /readyz mid-rebuild."""
        self._progress = {}

    @property
    def ready(self) -> bool:
        return self.check()[0]

    def check(self) -> Tuple[bool, Dict[str, Tuple[int, int]]]:
        # snapshot FIRST (one reference read, atomic under the GIL), then
        # iterate the snapshot — iterating the live dict races concurrent
        # begin()/progress() writers from the recovering thread
        snap = dict(self._progress)
        return (all(d >= t for d, t in snap.values()), snap)

    def render(self) -> str:
        """Text form for /readyz and the CLI: ``ok`` when ready, else one
        line per incomplete component with its rebuild progress."""
        ok, comps = self.check()
        if ok:
            return "ok"
        lines = ["NotReady"]
        for name in sorted(comps):
            done, total = comps[name]
            if done < total:
                lines.append(f"  {name}: {done}/{total}")
        return "\n".join(lines)
