"""Observability/config substrate (reference LX: staging/.../component-base)."""

from .featuregate import FeatureGate, default_feature_gate  # noqa: F401
from .healthz import Healthz, Readyz  # noqa: F401
from .configz import Configz  # noqa: F401
from .trace import (  # noqa: F401
    NOOP_TRACER,
    ChromeTraceExporter,
    InMemoryExporter,
    Span,
    SpanContext,
    ThresholdLogExporter,
    Trace,
    Tracer,
)
