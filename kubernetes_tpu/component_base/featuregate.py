"""Feature gates (reference: component-base/featuregate + the 114 gates of
pkg/features/kube_features.go).

Gates relevant to the scheduling capability surface are pre-registered with
their ~v1.24 default states; unknown gates can be registered at runtime.
``--feature-gates``-style strings parse via set_from_string.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

ALPHA, BETA, GA, DEPRECATED = "ALPHA", "BETA", "GA", "DEPRECATED"


@dataclass
class FeatureSpec:
    default: bool
    stage: str = ALPHA
    lock_to_default: bool = False


class FeatureGate:
    def __init__(self):
        self._specs: Dict[str, FeatureSpec] = {}
        self._enabled: Dict[str, bool] = {}

    def register(self, name: str, spec: FeatureSpec) -> None:
        self._specs[name] = spec

    def enabled(self, name: str) -> bool:
        if name in self._enabled:
            return self._enabled[name]
        spec = self._specs.get(name)
        return spec.default if spec else False

    def set(self, name: str, value: bool) -> None:
        spec = self._specs.get(name)
        if spec is not None and spec.lock_to_default and value != spec.default:
            raise ValueError(f"feature {name} is locked to {spec.default}")
        self._enabled[name] = value

    def set_from_string(self, s: str) -> None:
        """'Foo=true,Bar=false' (the --feature-gates flag format)."""
        for part in filter(None, (p.strip() for p in s.split(","))):
            name, _, val = part.partition("=")
            self.set(name, val.strip().lower() in ("true", "1", "t"))

    def known(self) -> Dict[str, FeatureSpec]:
        return dict(self._specs)


default_feature_gate = FeatureGate()

# scheduling-relevant gates @ ~v1.24 defaults (pkg/features/kube_features.go)
for _name, _spec in {
    "DefaultPodTopologySpread": FeatureSpec(True, GA),
    "MinDomainsInPodTopologySpread": FeatureSpec(False, ALPHA),
    "NodeAffinityLabelSelector": FeatureSpec(True, GA),
    "PodAffinityNamespaceSelector": FeatureSpec(True, BETA),
    "PodOverhead": FeatureSpec(True, BETA),
    "PodDisruptionBudget": FeatureSpec(True, GA, lock_to_default=True),
    "PreferNominatedNode": FeatureSpec(True, GA),
    "VolumeCapacityPriority": FeatureSpec(False, ALPHA),
    "CSIStorageCapacity": FeatureSpec(True, BETA),
    "LocalStorageCapacityIsolation": FeatureSpec(True, BETA),
    "NonPreemptingPriority": FeatureSpec(True, GA),
    "TaintBasedEvictions": FeatureSpec(True, GA),
}.items():
    default_feature_gate.register(_name, _spec)
