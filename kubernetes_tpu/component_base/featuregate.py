"""Feature gates (reference: component-base/featuregate + the gates of
pkg/features/kube_features.go).

The FULL ~v1.24 registry (113 gates) is pre-registered with the
reference's default/stage/lock values — the surface --feature-gates accepts;
the scheduling-relevant subset actually changes behavior here, and unknown
gates can still be registered at runtime.  ``--feature-gates``-style strings
parse via set_from_string.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

ALPHA, BETA, GA, DEPRECATED = "ALPHA", "BETA", "GA", "DEPRECATED"


@dataclass
class FeatureSpec:
    default: bool
    stage: str = ALPHA
    lock_to_default: bool = False


class FeatureGate:
    def __init__(self):
        self._specs: Dict[str, FeatureSpec] = {}
        self._enabled: Dict[str, bool] = {}

    def register(self, name: str, spec: FeatureSpec) -> None:
        self._specs[name] = spec

    def enabled(self, name: str) -> bool:
        if name in self._enabled:
            return self._enabled[name]
        spec = self._specs.get(name)
        return spec.default if spec else False

    def set(self, name: str, value: bool) -> None:
        spec = self._specs.get(name)
        if spec is not None and spec.lock_to_default and value != spec.default:
            raise ValueError(f"feature {name} is locked to {spec.default}")
        self._enabled[name] = value

    def set_from_string(self, s: str) -> None:
        ''''Foo=true,Bar=false' (the --feature-gates flag format).'''
        for part in filter(None, (p.strip() for p in s.split(","))):
            name, _, val = part.partition("=")
            self.set(name, val.strip().lower() in ("true", "1", "t"))

    def known(self) -> Dict[str, FeatureSpec]:
        return dict(self._specs)


default_feature_gate = FeatureGate()

# the reference's full default gate map @ ~v1.24 (name, default, stage,
# lock-to-default) — data extracted from pkg/features/kube_features.go's
# defaultKubernetesFeatureGates; this is API surface (names/defaults), not
# code.  Gates the scheduler consults are the same entries they always were.
_DEFAULT_GATES = {
    "AppArmor": FeatureSpec(True, BETA),
    "DynamicKubeletConfig": FeatureSpec(False, DEPRECATED),
    "ExperimentalHostUserNamespaceDefaultingGate": FeatureSpec(False, BETA),
    "DevicePlugins": FeatureSpec(True, BETA),
    "RotateKubeletServerCertificate": FeatureSpec(True, BETA),
    "LocalStorageCapacityIsolation": FeatureSpec(True, BETA),
    "EphemeralContainers": FeatureSpec(True, BETA),
    "QOSReserved": FeatureSpec(False, ALPHA),
    "ExpandPersistentVolumes": FeatureSpec(True, BETA),
    "ExpandInUsePersistentVolumes": FeatureSpec(True, BETA),
    "ExpandCSIVolumes": FeatureSpec(True, BETA),
    "CPUManager": FeatureSpec(True, BETA),
    "MemoryManager": FeatureSpec(True, BETA),
    "CPUCFSQuotaPeriod": FeatureSpec(False, ALPHA),
    "TopologyManager": FeatureSpec(True, BETA),
    "StorageObjectInUseProtection": FeatureSpec(True, GA, lock_to_default=True),
    "CSIMigration": FeatureSpec(True, BETA),
    "CSIMigrationGCE": FeatureSpec(True, BETA),
    "InTreePluginGCEUnregister": FeatureSpec(False, ALPHA),
    "CSIMigrationAWS": FeatureSpec(True, BETA),
    "InTreePluginAWSUnregister": FeatureSpec(False, ALPHA),
    "CSIMigrationAzureDisk": FeatureSpec(True, BETA),
    "InTreePluginAzureDiskUnregister": FeatureSpec(False, ALPHA),
    "CSIMigrationAzureFile": FeatureSpec(True, BETA),
    "InTreePluginAzureFileUnregister": FeatureSpec(False, ALPHA),
    "CSIMigrationvSphere": FeatureSpec(False, BETA),
    "InTreePluginvSphereUnregister": FeatureSpec(False, ALPHA),
    "CSIMigrationOpenStack": FeatureSpec(True, GA, lock_to_default=True),
    "InTreePluginOpenStackUnregister": FeatureSpec(False, ALPHA),
    "CSIMigrationRBD": FeatureSpec(False, ALPHA),
    "InTreePluginRBDUnregister": FeatureSpec(False, ALPHA),
    "ConfigurableFSGroupPolicy": FeatureSpec(True, GA, lock_to_default=True),
    "CSIMigrationPortworx": FeatureSpec(False, ALPHA),
    "InTreePluginPortworxUnregister": FeatureSpec(False, ALPHA),
    "CSIInlineVolume": FeatureSpec(True, BETA),
    "CSIStorageCapacity": FeatureSpec(True, BETA),
    "CSIServiceAccountToken": FeatureSpec(True, GA, lock_to_default=True),
    "GenericEphemeralVolume": FeatureSpec(True, GA, lock_to_default=True),
    "CSIVolumeFSGroupPolicy": FeatureSpec(True, GA, lock_to_default=True),
    "VolumeSubpath": FeatureSpec(True, GA, lock_to_default=True),
    "NetworkPolicyEndPort": FeatureSpec(True, BETA),
    "ProcMountType": FeatureSpec(False, ALPHA),
    "TTLAfterFinished": FeatureSpec(True, GA, lock_to_default=True),
    "IndexedJob": FeatureSpec(True, BETA),
    "JobTrackingWithFinalizers": FeatureSpec(True, BETA),
    "JobReadyPods": FeatureSpec(False, ALPHA),
    "KubeletPodResources": FeatureSpec(True, BETA),
    "LocalStorageCapacityIsolationFSQuotaMonitoring": FeatureSpec(False, ALPHA),
    "NonPreemptingPriority": FeatureSpec(True, GA, lock_to_default=True),
    "PodOverhead": FeatureSpec(True, BETA),
    "IPv6DualStack": FeatureSpec(True, GA, lock_to_default=True),
    "EndpointSlice": FeatureSpec(True, GA, lock_to_default=True),
    "EndpointSliceProxying": FeatureSpec(True, GA, lock_to_default=True),
    "EndpointSliceTerminatingCondition": FeatureSpec(True, BETA),
    "ProxyTerminatingEndpoints": FeatureSpec(False, ALPHA),
    "EndpointSliceNodeName": FeatureSpec(True, GA, lock_to_default=True),
    "WindowsEndpointSliceProxying": FeatureSpec(True, GA, lock_to_default=True),
    "PodDisruptionBudget": FeatureSpec(True, GA, lock_to_default=True),
    "DaemonSetUpdateSurge": FeatureSpec(True, BETA),
    "DownwardAPIHugePages": FeatureSpec(True, BETA),
    "AnyVolumeDataSource": FeatureSpec(False, ALPHA),
    "DefaultPodTopologySpread": FeatureSpec(True, GA, lock_to_default=True),
    "WinOverlay": FeatureSpec(True, BETA),
    "WinDSR": FeatureSpec(False, ALPHA),
    "DisableAcceleratorUsageMetrics": FeatureSpec(True, BETA),
    "HPAContainerMetrics": FeatureSpec(False, ALPHA),
    "SizeMemoryBackedVolumes": FeatureSpec(True, BETA),
    "ExecProbeTimeout": FeatureSpec(True, GA),
    "KubeletCredentialProviders": FeatureSpec(False, ALPHA),
    "GracefulNodeShutdown": FeatureSpec(True, BETA),
    "GracefulNodeShutdownBasedOnPodPriority": FeatureSpec(False, ALPHA),
    "ServiceLBNodePortControl": FeatureSpec(True, GA, lock_to_default=True),
    "MixedProtocolLBService": FeatureSpec(False, ALPHA),
    "VolumeCapacityPriority": FeatureSpec(False, ALPHA),
    "PreferNominatedNode": FeatureSpec(True, GA, lock_to_default=True),
    "ProbeTerminationGracePeriod": FeatureSpec(False, BETA),
    "NodeSwap": FeatureSpec(False, ALPHA),
    "PodDeletionCost": FeatureSpec(True, BETA),
    "StatefulSetAutoDeletePVC": FeatureSpec(False, ALPHA),
    "TopologyAwareHints": FeatureSpec(False, BETA),
    "PodAffinityNamespaceSelector": FeatureSpec(True, GA, lock_to_default=True),
    "ServiceLoadBalancerClass": FeatureSpec(True, BETA),
    "IngressClassNamespacedParams": FeatureSpec(True, GA, lock_to_default=True),
    "ServiceInternalTrafficPolicy": FeatureSpec(True, BETA),
    "LogarithmicScaleDown": FeatureSpec(True, BETA),
    "SuspendJob": FeatureSpec(True, GA, lock_to_default=True),
    "KubeletPodResourcesGetAllocatable": FeatureSpec(True, BETA),
    "CSIVolumeHealth": FeatureSpec(False, ALPHA),
    "WindowsHostProcessContainers": FeatureSpec(True, BETA),
    "DisableCloudProviders": FeatureSpec(False, ALPHA),
    "DisableKubeletCloudCredentialProviders": FeatureSpec(False, ALPHA),
    "StatefulSetMinReadySeconds": FeatureSpec(True, BETA),
    "ExpandedDNSConfig": FeatureSpec(False, ALPHA),
    "SeccompDefault": FeatureSpec(False, ALPHA),
    "PodSecurity": FeatureSpec(True, BETA),
    "ReadWriteOncePod": FeatureSpec(False, ALPHA),
    "CSRDuration": FeatureSpec(True, BETA),
    "DelegateFSGroupToCSIDriver": FeatureSpec(True, BETA),
    "KubeletInUserNamespace": FeatureSpec(False, ALPHA),
    "MemoryQoS": FeatureSpec(False, ALPHA),
    "CPUManagerPolicyOptions": FeatureSpec(True, BETA),
    "ControllerManagerLeaderMigration": FeatureSpec(True, BETA),
    "CPUManagerPolicyAlphaOptions": FeatureSpec(False, ALPHA),
    "CPUManagerPolicyBetaOptions": FeatureSpec(True, BETA),
    "JobMutableNodeSchedulingDirectives": FeatureSpec(True, BETA),
    "IdentifyPodOS": FeatureSpec(False, ALPHA),
    "PodAndContainerStatsFromCRI": FeatureSpec(False, ALPHA),
    "HonorPVReclaimPolicy": FeatureSpec(False, BETA),
    "RecoverVolumeExpansionFailure": FeatureSpec(False, ALPHA),
    "GRPCContainerProbe": FeatureSpec(False, ALPHA),
    "LegacyServiceAccountTokenNoAutoGeneration": FeatureSpec(True, BETA),
    "MinDomainsInPodTopologySpread": FeatureSpec(False, ALPHA),
    "HPAScaleToZero": FeatureSpec(False, ALPHA),
}
for _name, _spec in _DEFAULT_GATES.items():
    default_feature_gate.register(_name, _spec)
