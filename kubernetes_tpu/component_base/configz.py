"""configz: live config introspection (reference: component-base/configz;
scheduler registers its effective componentconfig, server.go:146-150)."""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict


class Configz:
    def __init__(self):
        self._sections: Dict[str, Any] = {}

    def install(self, name: str, config: Any) -> None:
        self._sections[name] = config

    def dump(self) -> str:
        def default(o):
            if dataclasses.is_dataclass(o):
                return dataclasses.asdict(o)
            return str(o)

        return json.dumps(self._sections, default=default, sort_keys=True)
