"""Scheduler metric series — same names/buckets as the reference.

Reference: pkg/scheduler/metrics/metrics.go:45-180.
"""

from .registry import Counter, Gauge, Histogram, default_registry, exponential_buckets

# :62-66 — THE baseline metric: exp buckets 1ms·2^k, 15 buckets
scheduling_attempt_duration = default_registry.register(
    Histogram(
        "scheduler_scheduling_attempt_duration_seconds",
        exponential_buckets(0.001, 2, 15),
        "Scheduling attempt latency (scheduling algorithm + binding)",
    )
)
scheduling_algorithm_duration = default_registry.register(
    Histogram(
        "scheduler_scheduling_algorithm_duration_seconds",
        exponential_buckets(0.001, 2, 15),
    )
)
e2e_scheduling_duration = default_registry.register(
    Histogram(
        "scheduler_e2e_scheduling_duration_seconds",
        exponential_buckets(0.001, 2, 15),
    )
)
pod_scheduling_duration = default_registry.register(
    Histogram(
        "scheduler_pod_scheduling_duration_seconds",
        exponential_buckets(0.01, 2, 20),  # :110-116
    )
)
framework_extension_point_duration = default_registry.register(
    Histogram(
        "scheduler_framework_extension_point_duration_seconds",
        exponential_buckets(0.0001, 2, 12),  # :130
    )
)
schedule_attempts = default_registry.register(
    Counter("scheduler_schedule_attempts_total")  # labels: (result,)
)
pending_pods = default_registry.register(
    Gauge("scheduler_pending_pods")  # labels: (queue,)
)
pod_scheduling_attempts = default_registry.register(
    Histogram("scheduler_pod_scheduling_attempts", [1, 2, 4, 8, 16])
)
preemption_attempts = default_registry.register(
    Counter("scheduler_preemption_attempts_total")
)
preemption_victims = default_registry.register(
    Histogram("scheduler_preemption_victims", [1, 2, 4, 8, 16, 32, 64])
)
queue_incoming_pods = default_registry.register(
    Counter("scheduler_queue_incoming_pods_total")  # labels: (queue, event)
)
scheduler_cache_size = default_registry.register(
    Gauge("scheduler_scheduler_cache_size")  # labels: (type,)
)

# --- span-tracing observatory (component_base/trace.py + scheduler) -----------
# Per-pod attempt latency BY PHASE, observed in the bind phase from the same
# clock stamps the attempt span tree carries.  The three attempt-tiling
# phases sum EXACTLY to scheduler_scheduling_attempt_duration_seconds per
# pod: "dispatch" (host dispatch work, t0 → device program enqueued),
# "device" (enqueue → decisions host-side; the extender round walk for
# extender batches), "bind" (the pod's own reserve→bind segment).  Two
# non-tiling phases ride the same label dimension: "queue_wait" (this
# attempt's queue entry → dispatch pop — overlaps the previous attempt's
# pipeline, so it must not be summed into the attempt) and "permit_wait"
# (a gang member's Permit hold, resolved at the waiting-bind flush).
# Always-on (independent of the tracer): `ktpu slo` reads these live or via
# /metrics buckets; the cost is a handful of histogram observes per pod.
attempt_phase_duration = default_registry.register(
    Histogram("scheduler_attempt_phase_duration_seconds",
              exponential_buckets(0.0001, 2, 20),
              "Per-pod scheduling attempt latency by phase")
)

# --- robustness / degradation observability ----------------------------------
# The chaos harness (kubernetes_tpu/chaos/) asserts these series so every
# retry, relist, and circuit transition is visible, not silent.

# --- gang scheduling (kubernetes_tpu/gang/) ----------------------------------
# Emitted by GangDirectory at the real decision points: a gang release
# (last member passes Permit), a quorum rejection at PreFilter, and the
# Permit-timeout group failure.

gang_scheduling_attempts = default_registry.register(
    # labels: (result,) — "scheduled" (gang released all-or-nothing) |
    # "timeout" (Permit deadline fired, whole gang requeued) |
    # "rejected" (non-timeout group failure: a member's binding cycle
    # rolled back or a member was deleted below quorum mid-wait) |
    # "quorum_reject" (fewer than minMember members known at PreFilter)
    Counter("gang_scheduling_attempts_total",
            "Per-gang scheduling attempt outcomes")
)
gang_wait_duration = default_registry.register(
    # first member entering the Permit wait → gang released or rejected
    Histogram("gang_wait_duration_seconds", exponential_buckets(0.001, 2, 18),
              "Time a gang's first waiting member held its Permit wait")
)
gang_timeouts = default_registry.register(
    Counter("gang_timeouts_total",
            "Gangs whose Permit wait expired before all members placed")
)

# --- hybrid assignment engine (framework/conflict.py + batch_assign) ---------

assignment_rounds = default_registry.register(
    # labels: (engine,) — "batch" (conflict-partitioned auction rounds) |
    # "scan" (greedy lax.scan steps) | "extender" (host round walk).
    # Incremented per completed dispatch with the engine's actual round
    # count (fetched packed with the decisions — zero extra device rounds).
    Counter("scheduler_assignment_rounds_total",
            "Assignment-engine rounds executed, by engine")
)
coupled_component_size = default_registry.register(
    # observed at partition time for every multi-pod conflict component —
    # the auction's serialization is bounded by the largest of these
    Histogram("scheduler_coupled_component_size",
              [1, 2, 4, 8, 16, 32, 64, 128, 256, 512],
              "Sizes of multi-pod pod-interaction components per batch")
)
identity_class_count = default_registry.register(
    # observed per dedup-ADMITTED dispatch (TPUScheduler._dedup_classes):
    # how many exact-content pod classes the batch collapsed to — the [C, N]
    # plane width the fused program actually computed.  Templated suites
    # sit at 1-2; a drift upward says the dedup win is eroding.
    Histogram("scheduler_identity_class_count",
              [1, 2, 4, 8, 16, 32, 64, 128, 256],
              "Identity classes per dedup-admitted batch")
)
dedup_fallback = default_registry.register(
    # labels: (reason,) — why a batch took the FULL [B, N] path instead of
    # identity-class dedup: "rng_key" (tie-noise instance), "class_hook"
    # (a dynamic plugin carries updates but no update_batch_classes),
    # "pod_indexed_aux" (host aux without a rep-view hook), "gang_anchor"
    # (a batch pod anchors a gang), "preemption" (affinity batch with a
    # preemption-capable pod — the dedup variant materializes no pod-level
    # auxes for the candidate program), "heterogeneous" (C > B/2: rep
    # planes would be as wide as the full path's)
    Counter("scheduler_dedup_fallback_total",
            "Batches routed to the full dense path, by dedup-gate reason")
)

scheduler_retries = default_registry.register(
    # labels: (reason,) — "cycle_error" (whole-batch dispatch failure
    # requeued) | "bind_error" (per-pod binding-cycle fault requeued)
    Counter("scheduler_retries_total",
            "Pods requeued through the failure handler instead of dropped")
)

phase_wall_clamped = default_registry.register(
    # labels: (phase,) — a phase-wall accumulation came out NEGATIVE and
    # was clamped to zero.  A negative slice means two timers double-
    # attributed the same wall-clock (e.g. extender callout wait larger
    # than the whole dispatch interval it was subtracted from) — exactly
    # the attribution bug class the per-phase A/B artifacts depend on
    # never having silently; the old bare max(x, 0.0) hid it.
    Counter("scheduler_phase_wall_clamped_total",
            "Negative phase-wall attributions clamped to zero, by phase")
)

sync_overlap = default_registry.register(
    # labels: (result,) — how each dispatch consumed the overlapped
    # background snapshot/sync (see TPUScheduler._spawn_sync_ahead):
    # "reused" (prepared payload adopted verbatim — nothing changed since
    # capture), "merged" (top-up diff landed after capture; consumed rows
    # folded back and the scatter payload rebuilt from live mirrors),
    # "fallback_node_delete" (a node DELETE arrived after capture — row
    # reuse could alias the prepared payload, so it was discarded and the
    # dispatch synced synchronously)
    Counter("scheduler_sync_overlap_total",
            "Overlapped-sync consumption per dispatch, by result")
)
extender_circuit_state = default_registry.register(
    # labels: (url,) — 0 closed, 1 open, 2 half-open (extender.CircuitBreaker)
    Gauge("extender_circuit_state",
          "Per-extender circuit breaker state (0 closed, 1 open, 2 half-open)")
)
informer_relists = default_registry.register(
    # labels: (kind,) — one series per OBJECT KIND relisted, plus two
    # mechanism tags in the same dimension (ISSUE-11 contract): "paged"
    # counts relists that walked rv-pinned limit/continue pages (in
    # ADDITION to their kind series — sum kinds, not the whole dimension,
    # for a total), "bookmark" counts resyncs whose restart rv came from a
    # BOOKMARK (relists avoided, not performed)
    Counter("informer_relists_total",
            "Reflector full relists after a watch drop/error")
)
client_request_retries = default_registry.register(
    # labels: (code,) — HTTP status (or 409 for injected conflicts) that
    # triggered the resend; shared by HTTPApiClient and chaos.RetryingStore
    Counter("client_request_retries_total",
            "API requests resent after a retryable failure")
)
chaos_faults_injected = default_registry.register(
    # labels: (fault,) — write_429 | write_500 | write_503 | conflict |
    # watch_drop | slow | http_429 | http_500 | http_503
    Counter("chaos_faults_injected_total",
            "Faults the active FaultSchedule actually injected")
)
leader_election_status = default_registry.register(
    # labels: (identity,) — 1 while leading (the reference's
    # leader_election_master_status)
    Gauge("leader_election_master_status")
)

# --- durable, flood-proof control plane (sim/wal.py, sim/watchcache.py,
# apiserver/flowcontrol.py) ----------------------------------------------------
# Emitted at the real decision points: every WAL append/fsync, every watch
# cache ring apply/compaction, and every flow-control admit/reject — the
# series `ktpu controlplane status` renders.

apiserver_inflight = default_registry.register(
    # labels: (kind,) — "mutating" | "readonly": current seats held in each
    # split inflight pool (the APF max-inflight gates)
    Gauge("apiserver_inflight_requests",
          "In-flight API requests by request class")
)
apiserver_rejected = default_registry.register(
    # labels: (reason,) — "mutating_queue_full" | "mutating_timeout" |
    # "readonly_queue_full" | "readonly_timeout" (flow-control sheds,
    # answered 429 + Retry-After) | "chaos_shed" (injected APF-shaped shed)
    # | "watch_expired" (410 Gone: requested rv older than the watch
    # cache's ring)
    Counter("apiserver_rejected_requests_total",
            "API requests rejected before storage, by reason")
)
wal_records = default_registry.register(
    # labels: (op,) — create | update | delete | bind
    Counter("wal_records_total",
            "Mutations appended to the write-ahead log")
)
wal_size_bytes = default_registry.register(
    Gauge("wal_size_bytes", "Current write-ahead log file size")
)
wal_last_fsync_rv = default_registry.register(
    # the durability watermark: every rv ≤ this survives kill -9
    Gauge("wal_last_fsync_rv",
          "Highest resourceVersion known fsynced to the WAL")
)
apiserver_wire_encode = default_registry.register(
    # labels: (codec, cached) — codec "json" | "wire", cached "true" |
    # "false".  Incremented by api/wire.py EncodedPayload every time a
    # serving plane asks for an object's encoded bytes: cached="false" is
    # a real serialization, cached="true" a byte-cache hit.  The
    # encode-once contract is the ratio: at N watchers per event, total
    # increments ≈ N per codec but cached="false" stays ≈ 1.
    Counter("apiserver_wire_encode_total",
            "Encoded-payload requests by codec and cache outcome")
)
apiserver_wire_requests = default_registry.register(
    # labels: (codec,) — "json" | "wire": list/get/watch requests served
    # in each negotiated content type (Accept-header negotiation,
    # apiserver/server.py)
    Counter("apiserver_wire_requests_total",
            "API requests served, by negotiated wire codec")
)
watch_cache_ring_occupancy = default_registry.register(
    Gauge("watch_cache_ring_occupancy",
          "Events currently held in the watch cache ring")
)
watch_cache_oldest_rv = default_registry.register(
    # watch/list-at-rv requests BELOW this answer 410 Gone (ring compacted
    # past them) — the reference cacher's too-old-resourceVersion contract
    Gauge("watch_cache_oldest_rv",
          "Oldest resourceVersion the watch cache can still replay from")
)

# --- crash-restart resilience (kubernetes_tpu/recovery/) ----------------------
# Emitted at the real decision points: the event recorder's flush/eviction
# path when an event is truly lost, the drift detector on every divergent
# component it finds, and the cold-start reconstructor once per recovery.

events_dropped = default_registry.register(
    # truly lost events only: evicted from the recorder's bounded retain
    # buffer, or still failing at the shutdown flush — retained-and-later-
    # flushed events never count (client/events.py)
    Counter("events_dropped_total",
            "Events lost after the recorder's bounded retry/flush")
)
state_drift = default_registry.register(
    # labels: (component,) — "cache_pods" | "encoder_nodes" |
    # "encoder_pods" | "affinity" — one increment per divergent key found
    # by recovery/drift.py's live-vs-from-scratch-store diff (before repair)
    Counter("scheduler_state_drift_total",
            "Divergent keys between live scheduler state and a "
            "from-scratch store rebuild, by component")
)
cold_starts = default_registry.register(
    # labels: (outcome,) — "clean" (post-rebuild drift check found
    # nothing) | "repaired" (divergence found and repaired) | "degraded"
    # (divergence survived repair — the replica should stay NotReady)
    Counter("scheduler_cold_starts_total",
            "Cold-start state reconstructions, by drift outcome")
)

# --- node lifecycle & partition tolerance (controllers/nodelifecycle.py) ------
# Emitted at the real decision points: every zone-state recompute, every
# eviction verdict the lifecycle controller receives from the shared gate
# (plus the cancellations lease recovery performs), and each atomic
# gang-slice repair — the series `ktpu nodehealth` renders.

node_lifecycle_zone_state = default_registry.register(
    # labels: (zone,) — 0 Normal | 1 PartialDisruption | 2 FullDisruption
    # (controllers/nodelifecycle.ZONE_STATE_CODE); set on every sync for
    # every zone with at least one node
    Gauge("node_lifecycle_zone_state",
          "Per-zone disruption state (0 Normal, 1 Partial, 2 Full)")
)
node_lifecycle_evictions = default_registry.register(
    # labels: (mode, result) — mode is the node's ZONE state when the
    # decision fired ("Normal" | "PartialDisruption" | "FullDisruption");
    # result is the gate verdict ("evicted" | "refused" | "missing" |
    # "error") plus two lifecycle-only outcomes: "cancelled" (lease
    # recovery cancelled a pending timed eviction — the flap guard) and
    # "deferred" (a due timed eviction held back by a frozen zone)
    Counter("node_lifecycle_evictions_total",
            "Node-lifecycle eviction decisions, by zone mode and result")
)
node_lifecycle_queue_depth = default_registry.register(
    # labels: (zone,) — nodes waiting in the zone's rate-limited eviction
    # queue at the end of the last sync (what `ktpu nodehealth` shows)
    Gauge("node_lifecycle_eviction_queue_depth",
          "Nodes pending in each zone's rate-limited eviction queue")
)
gang_repairs = default_registry.register(
    # one increment per gang failed ATOMICALLY by the lifecycle controller
    # (every bound member evicted through the gate in one pass) — the
    # requeued-exactly-once probe counts these against rebinds
    Counter("gang_repairs_total",
            "Gangs atomically failed and requeued after a member's node died")
)

# --- descheduler subsystem (kubernetes_tpu/descheduler/) ---------------------
# Emitted at the real decision points: every pod-killing path's verdict at
# the shared eviction gate, each policy plan's end state in the controller
# loop, and the device what-if solve latency in the planner.

descheduler_evictions = default_registry.register(
    # labels: (policy, result) — policy names the calling path
    # ("defrag" | "spread" | "drain" | "nodelifecycle" | "preemption" |
    # "api" | ...); result is the gate verdict: "evicted" (gate passed,
    # pod deleted) | "refused" (a matching PDB had no budget) |
    # "overridden" (budget exhausted but the caller may violate —
    # preemption's last-resort contract) | "dry_run" (gate evaluated,
    # nothing deleted) | "missing" (pod already gone — the exactly-once
    # guard) | "error" (store fault mid-eviction)
    Counter("descheduler_evictions_total",
            "Eviction-gate verdicts, by calling policy")
)
descheduler_plans = default_registry.register(
    # labels: (policy, outcome) — "applied" (every victim evicted) |
    # "dry_run" (planned + scored, nothing evicted) | "abandoned" (a
    # mid-plan refusal/fault stopped the plan; remaining victims kept) |
    # "no_fit" (no candidate plan survived the counterfactual solve)
    Counter("descheduler_plans_total",
            "Descheduler plan outcomes, by policy")
)
descheduler_planner_duration = default_registry.register(
    # one observation per counterfactual batched solve (victims masked out
    # of the forked DeviceSnapshot, assignment program re-run)
    Histogram("descheduler_planner_solve_duration_seconds",
              exponential_buckets(0.001, 2, 15),
              "Device what-if planner solve latency")
)

# --- unified counterfactual engine + cluster autoscaler -----------------------
# Emitted at the real decision points: every fork the whatif engine solves
# (WhatIfEngine.evaluate — descheduler plans, autoscaler simulations), and
# each autoscaler scale decision's end state in the controller loop.

whatif_forks = default_registry.register(
    # incremented by K per evaluate() call — K candidate plans ride one
    # vmapped [K, B, N] solve, so forks/solve is the fan-out observability
    Counter("whatif_forks_evaluated_total",
            "Counterfactual forks evaluated by the whatif engine")
)
# --- WAL replication & follower reads (kubernetes_tpu/sim/replication.py) ----
# Emitted at the real decision points: the follower's ship-apply path
# (FollowerReplica.deliver), the shipper's per-pump lag refresh
# (LogShipper.pump), and role transitions (follower construction,
# promotion, APIServer startup).

replication_applied_rv = default_registry.register(
    # labels: (replica,) — highest WAL resourceVersion this follower has
    # applied from the shipped stream: its rv-gated serving watermark
    # (lists/watches at rv ≤ this serve locally; above it wait-then-504)
    Gauge("replication_applied_rv",
          "Highest shipped WAL resourceVersion applied, per follower")
)
replication_lag_rv = default_registry.register(
    # labels: (replica,) — leader_rv - applied_rv at the last ship pump or
    # batch apply (0 = caught up)
    Gauge("replication_lag_rv",
          "Replication lag in resourceVersions, per follower replica")
)
replication_ship_errors = default_registry.register(
    # labels: (reason,) — "torn" (batch cut mid-record: the verified
    # prefix applied, the remainder is resent), "gap" (batch offset ahead
    # of the follower's applied watermark: rejected, shipper resends),
    # "stale" (delivery to an already-promoted replica: ignored),
    # "regressed" (tailed file shrank below the verified prefix)
    Counter("replication_ship_errors_total",
            "Ship-stream anomalies detected by the replication layer")
)
apiserver_role = default_registry.register(
    # labels: (replica, role) — 1 for the replica's CURRENT role
    # ("leader" | "follower"), 0 once it transitions away (promotion
    # flips follower→leader); `ktpu controlplane status` renders the set
    Gauge("apiserver_role",
          "Current serving role per apiserver replica (1 = active)")
)

# --- dynamic resource allocation (kubernetes_tpu/dra/) ------------------------
# Emitted at the real decision points: PreBind's claim-commit loop (one
# increment per claim, one duration observation per pod allocation), and
# the Reserve-time conflict path.

dra_claims_allocated = default_registry.register(
    # labels: (result,) — "allocated" (claim allocation persisted to the
    # store) | "conflict" (Reserve lost the named-device race or the claim
    # was held by another pod) | "rollback" (a later claim's commit failed,
    # this pod's written claims were deallocated — the exactly-once path)
    # | "error" (terminal store fault with nothing left to roll back)
    Counter("dra_claims_allocated_total",
            "ResourceClaim allocation outcomes, by result")
)
dra_allocation_duration = default_registry.register(
    # PreBind entry → all of the pod's claims committed (or rolled back);
    # one observation per pod that carried at least one claim
    Histogram("dra_allocation_duration_seconds",
              exponential_buckets(0.0001, 2, 15),
              "Per-pod ResourceClaim allocation commit latency")
)

autoscaler_scale_decisions = default_registry.register(
    # labels: (direction, result) — direction "up" | "down"; result
    # "applied" (nodes created / node drained+deleted) | "no_fit" (no
    # simulated candidate made the demand placeable) | "at_max" (demand
    # exists but every group is at max_size) | "blocked" (scale-down
    # refused: a PDB blocks a victim or the drain was refused mid-way) |
    # "no_replacement" (scale-down refused: displaced pods don't re-place
    # in the what-if) | "error" (store fault mid-apply)
    Counter("autoscaler_scale_decisions_total",
            "Cluster-autoscaler scale decisions, by direction and outcome")
)

# --- multi-tenant API surface (apiextensions + auth) --------------------------

crd_registrations = default_registry.register(
    # labels: (op,) — "install" (kind newly served) | "update" (schema or
    # scope change re-minted the served type) | "uninstall" (CRD deleted,
    # kind removed + stored CRs cascaded) | "conflict" (CRD names a kind a
    # built-in already serves: registration refused, never a ghost kind)
    Counter("apiextensions_crd_registrations_total",
            "Dynamic-kind registrar operations, by outcome")
)
crd_kinds_served = default_registry.register(
    Gauge("apiextensions_crd_kinds_served",
          "Custom kinds currently installed in the serving scheme")
)
rbac_decisions = default_registry.register(
    # labels: (decision,) — "allow" | "deny"; one increment per authorizer
    # evaluation at the apiserver door
    Counter("rbac_authorization_decisions_total",
            "RBAC authorizer decisions, by outcome")
)
trainingjob_expansions = default_registry.register(
    # labels: (result,) — "expanded" (objects newly created this sync) |
    # "steady" (job already fully expanded — the idempotent no-op path)
    Counter("trainingjob_expansions_total",
            "TrainingJob controller reconciles, by outcome")
)
