"""Scheduler metric series — same names/buckets as the reference.

Reference: pkg/scheduler/metrics/metrics.go:45-180.
"""

from .registry import Counter, Gauge, Histogram, default_registry, exponential_buckets

# :62-66 — THE baseline metric: exp buckets 1ms·2^k, 15 buckets
scheduling_attempt_duration = default_registry.register(
    Histogram(
        "scheduler_scheduling_attempt_duration_seconds",
        exponential_buckets(0.001, 2, 15),
        "Scheduling attempt latency (scheduling algorithm + binding)",
    )
)
scheduling_algorithm_duration = default_registry.register(
    Histogram(
        "scheduler_scheduling_algorithm_duration_seconds",
        exponential_buckets(0.001, 2, 15),
    )
)
e2e_scheduling_duration = default_registry.register(
    Histogram(
        "scheduler_e2e_scheduling_duration_seconds",
        exponential_buckets(0.001, 2, 15),
    )
)
pod_scheduling_duration = default_registry.register(
    Histogram(
        "scheduler_pod_scheduling_duration_seconds",
        exponential_buckets(0.01, 2, 20),  # :110-116
    )
)
framework_extension_point_duration = default_registry.register(
    Histogram(
        "scheduler_framework_extension_point_duration_seconds",
        exponential_buckets(0.0001, 2, 12),  # :130
    )
)
schedule_attempts = default_registry.register(
    Counter("scheduler_schedule_attempts_total")  # labels: (result,)
)
pending_pods = default_registry.register(
    Gauge("scheduler_pending_pods")  # labels: (queue,)
)
pod_scheduling_attempts = default_registry.register(
    Histogram("scheduler_pod_scheduling_attempts", [1, 2, 4, 8, 16])
)
preemption_attempts = default_registry.register(
    Counter("scheduler_preemption_attempts_total")
)
preemption_victims = default_registry.register(
    Histogram("scheduler_preemption_victims", [1, 2, 4, 8, 16, 32, 64])
)
queue_incoming_pods = default_registry.register(
    Counter("scheduler_queue_incoming_pods_total")  # labels: (queue, event)
)
scheduler_cache_size = default_registry.register(
    Gauge("scheduler_scheduler_cache_size")  # labels: (type,)
)
