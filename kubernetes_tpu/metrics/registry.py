"""Minimal metrics registry with Prometheus-compatible series naming.

Reference: staging/src/k8s.io/component-base/metrics (counter/gauge/histogram
wrappers over prometheus) + pkg/scheduler/metrics/metrics.go.  Quantile
extraction mirrors test/integration/scheduler_perf/util.go:238-276
(histogramQuantile over bucket counts).
"""

from __future__ import annotations

import bisect
import re
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..analysis import lockcheck


def exponential_buckets(start: float, factor: float, count: int) -> List[float]:
    return [start * (factor ** i) for i in range(count)]


class Metric:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_


class Counter(Metric):
    def __init__(self, name, help_=""):
        super().__init__(name, help_)
        self._v: Dict[Tuple, float] = {}
        self._lock = lockcheck.maybe_wrap(
            threading.Lock(), f"Counter[{name}]._lock")

    def inc(self, labels: Tuple = (), by: float = 1.0):
        with self._lock:
            self._v[labels] = self._v.get(labels, 0.0) + by

    def value(self, labels: Tuple = ()) -> float:
        return self._v.get(labels, 0.0)

    def items(self) -> Dict[Tuple, float]:
        """Snapshot of every labeled series (collectors summing across an
        unbounded label dimension, e.g. per-policy eviction counts)."""
        with self._lock:
            return dict(self._v)


class Gauge(Metric):
    def __init__(self, name, help_=""):
        super().__init__(name, help_)
        self._v: Dict[Tuple, float] = {}

    def set(self, value: float, labels: Tuple = ()):
        self._v[labels] = value

    def value(self, labels: Tuple = ()) -> float:
        return self._v.get(labels, 0.0)

    def items(self) -> Dict[Tuple, float]:
        return dict(self._v)


class Histogram(Metric):
    def __init__(self, name, buckets: List[float], help_=""):
        super().__init__(name, help_)
        self.buckets = list(buckets)
        self._counts: Dict[Tuple, List[int]] = {}
        self._sum: Dict[Tuple, float] = {}
        self._n: Dict[Tuple, int] = {}
        # Exact samples alongside the buckets: prometheus histograms rail at
        # the top bucket (round 2's headline p99 WAS the bucket ceiling, i.e.
        # not a measurement), so perf windows also keep raw values and report
        # exact quantiles next to the bucket-interpolated parity ones.
        # Bounded (unlike the bucket counts, which are fixed-size anyway):
        # outside a measured window nothing calls reset(), and an unbounded
        # per-observation list would leak in a long-running scheduler.  Perf
        # windows reset() first and observe far fewer than the cap.
        self._samples: Dict[Tuple, List[float]] = {}
        self._samples_dropped: Dict[Tuple, int] = {}
        self.max_samples = 200_000
        self._lock = lockcheck.maybe_wrap(
            threading.Lock(), f"Histogram[{name}]._lock")

    def observe(self, v: float, labels: Tuple = ()):
        with self._lock:
            c = self._counts.setdefault(labels, [0] * (len(self.buckets) + 1))
            c[bisect.bisect_left(self.buckets, v)] += 1
            self._sum[labels] = self._sum.get(labels, 0.0) + v
            self._n[labels] = self._n.get(labels, 0) + 1
            s = self._samples.setdefault(labels, [])
            if len(s) < self.max_samples:
                s.append(v)
            else:
                self._samples_dropped[labels] = self._samples_dropped.get(labels, 0) + 1

    def reset(self):
        """Clear observations in place (measured-window deltas,
        scheduler_perf util.go:238-276 collects over a window)."""
        with self._lock:
            self._counts.clear()
            self._sum.clear()
            self._n.clear()
            self._samples.clear()
            self._samples_dropped.clear()

    def samples(self, labels: Tuple = ()) -> List[float]:
        with self._lock:
            return list(self._samples.get(labels, ()))

    def exact_quantile(self, q: float, labels: Tuple = ()) -> float:
        """Quantile over the raw samples (never saturates at a bucket edge)."""
        s = self.samples(labels)
        if not s:
            return 0.0
        s.sort()
        idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
        return s[idx]

    def count(self, labels: Tuple = ()) -> int:
        return self._n.get(labels, 0)

    def sum(self, labels: Tuple = ()) -> float:
        return self._sum.get(labels, 0.0)

    def quantile(self, q: float, labels: Tuple = ()) -> float:
        """Linear-interpolated bucket quantile (scheduler_perf util.go:238-276)."""
        return quantile_from_counts(self.buckets,
                                    self._counts.get(labels), q)


def quantile_from_counts(buckets: List[float],
                         counts: Optional[List[int]], q: float) -> float:
    """Linear-interpolated quantile over per-bucket counts (len(buckets)+1,
    last = +Inf overflow) — shared by Histogram.quantile and the CLI's
    ``ktpu slo --server`` path, which rebuilds counts from the /metrics
    bucket exposition (parse_text) instead of a live Histogram."""
    if not counts:
        return 0.0
    total = sum(counts)
    if total <= 0:
        return 0.0
    target = q * total
    acc = 0.0
    lo = 0.0
    for i, c in enumerate(counts):
        hi = buckets[i] if i < len(buckets) else float("inf")
        if acc + c >= target and c > 0:
            frac = (target - acc) / c
            if hi == float("inf"):
                return lo
            return lo + (hi - lo) * frac
        acc += c
        lo = hi
    return lo


class Registry:
    def __init__(self):
        self.metrics: Dict[str, Metric] = {}

    def register(self, m: Metric) -> Metric:
        self.metrics[m.name] = m
        return m

    def get(self, name: str) -> Optional[Metric]:
        return self.metrics.get(name)

    def reset(self):
        for name, m in list(self.metrics.items()):
            if isinstance(m, Histogram):
                self.metrics[name] = Histogram(m.name, m.buckets, m.help)
            else:
                self.metrics[name] = type(m)(m.name, m.help)


default_registry = Registry()


def _escape_label_value(v: str) -> str:
    """Escape one label value for the synthetic comma-joined ``label`` key:
    backslash, double-quote, newline (the Prometheus escapes) plus the
    comma, which is this format's tuple separator."""
    return (v.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n").replace(",", "\\,"))


def _unescape_split(joined: str) -> Tuple[str, ...]:
    """Split a rendered ``label`` value on unescaped commas and unescape
    each element — the exact inverse of the join in render_text."""
    parts: List[str] = []
    cur: List[str] = []
    i = 0
    while i < len(joined):
        c = joined[i]
        if c == "\\" and i + 1 < len(joined):
            nxt = joined[i + 1]
            cur.append({"n": "\n"}.get(nxt, nxt))
            i += 2
            continue
        if c == ",":
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    parts.append("".join(cur))
    return tuple(parts)


def render_text(registry: Optional[Registry] = None) -> str:
    """Prometheus-style text exposition of a registry (the apiserver's
    /metrics body; ``ktpu controlplane status --server`` and ``ktpu slo
    --server`` parse it back).

    Sim-grade format: the registry stores label VALUE tuples without label
    names, so every labeled series renders one synthetic ``label`` key
    holding the comma-joined (escaped) values — ``name{label="a,b"} 3``.
    Histograms emit the full exposition: cumulative ``_bucket`` series with
    ``le`` (including ``+Inf``) plus ``_count``/``_sum``, so a remote
    reader can compute interpolated quantiles (quantile_from_counts) —
    the ``ktpu slo --server`` dependency.  Known lossy corner, kept for
    back-compat with existing consumers: a SINGLE empty label value
    renders ``label=""`` which parses back to the EMPTY tuple (callers
    like ``ktpu nodehealth`` look both keys up)."""
    reg = registry or default_registry
    lines: List[str] = []
    for name in sorted(reg.metrics):
        metric = reg.metrics[name]
        series: List[Tuple[str, Tuple, Optional[str], float]] = []
        if isinstance(metric, Histogram):
            with metric._lock:
                for labels, counts in metric._counts.items():
                    acc = 0
                    for i, c in enumerate(counts):
                        acc += c
                        le = (f"{metric.buckets[i]:g}"
                              if i < len(metric.buckets) else "+Inf")
                        series.append((f"{name}_bucket", labels, le,
                                       float(acc)))
                series += [(f"{name}_count", labels, None, float(n))
                           for labels, n in metric._n.items()]
                series += [(f"{name}_sum", labels, None, s)
                           for labels, s in metric._sum.items()]
        elif isinstance(metric, (Counter, Gauge)):
            series = [(name, labels, None, v)
                      for labels, v in metric.items().items()]
        else:
            continue
        for sname, labels, le, v in sorted(
                series, key=lambda t: (t[0], t[1], t[2] or "")):
            parts = []
            if labels:
                joined = ",".join(_escape_label_value(str(x))
                                  for x in labels)
                parts.append(f'label="{joined}"')
            if le is not None:
                parts.append(f'le="{le}"')
            # repr() is the shortest exact round-trip for floats — ":g"
            # truncated to 6 significant digits, which silently corrupted
            # large counters through the --server parse path
            val = (f"{int(v)}" if float(v).is_integer() else repr(float(v)))
            if parts:
                lines.append(f"{sname}{{{','.join(parts)}}} {val}")
            else:
                lines.append(f"{sname} {val}")
    return "\n".join(lines) + "\n"


_LINE_RE = re.compile(
    r'^(?P<name>[^{\s]+)'
    r'(?:\{(?:label="(?P<label>(?:[^"\\]|\\.)*)")?,?'
    r'(?:le="(?P<le>[^"]*)")?\})?'
    r'\s+(?P<val>\S+)$')


def parse_text(body: str) -> Dict[Tuple[str, Tuple], float]:
    """Inverse of render_text: {(series name, label tuple) → value}.
    Histogram ``_bucket`` series key as (``name_bucket``, labels + (le,)) —
    ``bucket_counts_from_series`` rebuilds per-bucket count vectors from
    them for remote quantile computation."""
    out: Dict[Tuple[str, Tuple], float] = {}
    for line in body.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE_RE.match(line)
        if m is None:
            continue
        labels: Tuple = ()
        if m.group("label"):
            labels = _unescape_split(m.group("label"))
        if m.group("le") is not None:
            labels = labels + (m.group("le"),)
        try:
            out[(m.group("name"), labels)] = float(m.group("val"))
        except ValueError:
            continue
    return out


def bucket_counts_from_series(metrics: Dict[Tuple[str, Tuple], float],
                              name: str) -> Dict[Tuple, Tuple[List[float],
                                                              List[int]]]:
    """Rebuild {labels → (bucket uppers, per-bucket counts incl. +Inf
    overflow)} from a parse_text dict's cumulative ``name_bucket`` series —
    the remote half of Histogram.quantile (feed quantile_from_counts)."""
    rows: Dict[Tuple, List[Tuple[float, float]]] = {}
    for (sname, labels), v in metrics.items():
        if sname != f"{name}_bucket" or not labels:
            continue
        le = labels[-1]
        upper = float("inf") if le == "+Inf" else float(le)
        rows.setdefault(labels[:-1], []).append((upper, v))
    out: Dict[Tuple, Tuple[List[float], List[int]]] = {}
    for labels, pairs in rows.items():
        pairs.sort()
        uppers = [u for u, _ in pairs if u != float("inf")]
        cum = [c for _, c in pairs]
        counts = [int(round(c - (cum[i - 1] if i else 0.0)))
                  for i, c in enumerate(cum)]
        out[labels] = (uppers, counts)
    return out
