"""Minimal metrics registry with Prometheus-compatible series naming.

Reference: staging/src/k8s.io/component-base/metrics (counter/gauge/histogram
wrappers over prometheus) + pkg/scheduler/metrics/metrics.go.  Quantile
extraction mirrors test/integration/scheduler_perf/util.go:238-276
(histogramQuantile over bucket counts).
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..analysis import lockcheck


def exponential_buckets(start: float, factor: float, count: int) -> List[float]:
    return [start * (factor ** i) for i in range(count)]


class Metric:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_


class Counter(Metric):
    def __init__(self, name, help_=""):
        super().__init__(name, help_)
        self._v: Dict[Tuple, float] = {}
        self._lock = lockcheck.maybe_wrap(
            threading.Lock(), f"Counter[{name}]._lock")

    def inc(self, labels: Tuple = (), by: float = 1.0):
        with self._lock:
            self._v[labels] = self._v.get(labels, 0.0) + by

    def value(self, labels: Tuple = ()) -> float:
        return self._v.get(labels, 0.0)

    def items(self) -> Dict[Tuple, float]:
        """Snapshot of every labeled series (collectors summing across an
        unbounded label dimension, e.g. per-policy eviction counts)."""
        with self._lock:
            return dict(self._v)


class Gauge(Metric):
    def __init__(self, name, help_=""):
        super().__init__(name, help_)
        self._v: Dict[Tuple, float] = {}

    def set(self, value: float, labels: Tuple = ()):
        self._v[labels] = value

    def value(self, labels: Tuple = ()) -> float:
        return self._v.get(labels, 0.0)

    def items(self) -> Dict[Tuple, float]:
        return dict(self._v)


class Histogram(Metric):
    def __init__(self, name, buckets: List[float], help_=""):
        super().__init__(name, help_)
        self.buckets = list(buckets)
        self._counts: Dict[Tuple, List[int]] = {}
        self._sum: Dict[Tuple, float] = {}
        self._n: Dict[Tuple, int] = {}
        # Exact samples alongside the buckets: prometheus histograms rail at
        # the top bucket (round 2's headline p99 WAS the bucket ceiling, i.e.
        # not a measurement), so perf windows also keep raw values and report
        # exact quantiles next to the bucket-interpolated parity ones.
        # Bounded (unlike the bucket counts, which are fixed-size anyway):
        # outside a measured window nothing calls reset(), and an unbounded
        # per-observation list would leak in a long-running scheduler.  Perf
        # windows reset() first and observe far fewer than the cap.
        self._samples: Dict[Tuple, List[float]] = {}
        self._samples_dropped: Dict[Tuple, int] = {}
        self.max_samples = 200_000
        self._lock = lockcheck.maybe_wrap(
            threading.Lock(), f"Histogram[{name}]._lock")

    def observe(self, v: float, labels: Tuple = ()):
        with self._lock:
            c = self._counts.setdefault(labels, [0] * (len(self.buckets) + 1))
            c[bisect.bisect_left(self.buckets, v)] += 1
            self._sum[labels] = self._sum.get(labels, 0.0) + v
            self._n[labels] = self._n.get(labels, 0) + 1
            s = self._samples.setdefault(labels, [])
            if len(s) < self.max_samples:
                s.append(v)
            else:
                self._samples_dropped[labels] = self._samples_dropped.get(labels, 0) + 1

    def reset(self):
        """Clear observations in place (measured-window deltas,
        scheduler_perf util.go:238-276 collects over a window)."""
        with self._lock:
            self._counts.clear()
            self._sum.clear()
            self._n.clear()
            self._samples.clear()
            self._samples_dropped.clear()

    def samples(self, labels: Tuple = ()) -> List[float]:
        with self._lock:
            return list(self._samples.get(labels, ()))

    def exact_quantile(self, q: float, labels: Tuple = ()) -> float:
        """Quantile over the raw samples (never saturates at a bucket edge)."""
        s = self.samples(labels)
        if not s:
            return 0.0
        s.sort()
        idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
        return s[idx]

    def count(self, labels: Tuple = ()) -> int:
        return self._n.get(labels, 0)

    def sum(self, labels: Tuple = ()) -> float:
        return self._sum.get(labels, 0.0)

    def quantile(self, q: float, labels: Tuple = ()) -> float:
        """Linear-interpolated bucket quantile (scheduler_perf util.go:238-276)."""
        counts = self._counts.get(labels)
        if not counts:
            return 0.0
        total = sum(counts)
        target = q * total
        acc = 0.0
        lo = 0.0
        for i, c in enumerate(counts):
            hi = self.buckets[i] if i < len(self.buckets) else float("inf")
            if acc + c >= target and c > 0:
                frac = (target - acc) / c
                if hi == float("inf"):
                    return lo
                return lo + (hi - lo) * frac
            acc += c
            lo = hi
        return lo


class Registry:
    def __init__(self):
        self.metrics: Dict[str, Metric] = {}

    def register(self, m: Metric) -> Metric:
        self.metrics[m.name] = m
        return m

    def get(self, name: str) -> Optional[Metric]:
        return self.metrics.get(name)

    def reset(self):
        for name, m in list(self.metrics.items()):
            if isinstance(m, Histogram):
                self.metrics[name] = Histogram(m.name, m.buckets, m.help)
            else:
                self.metrics[name] = type(m)(m.name, m.help)


default_registry = Registry()


def render_text(registry: Optional[Registry] = None) -> str:
    """Prometheus-style text exposition of a registry (the apiserver's
    /metrics body; ``ktpu controlplane status --server`` parses it back).

    Sim-grade format: the registry stores label VALUE tuples without label
    names, so every labeled series renders one synthetic ``label`` key
    holding the comma-joined values — ``name{label="a,b"} 3``.  Histograms
    emit ``_count``/``_sum`` only (bucket vectors are an in-process
    concern; the quantile helpers read them directly)."""
    reg = registry or default_registry
    lines: List[str] = []
    for name in sorted(reg.metrics):
        metric = reg.metrics[name]
        if isinstance(metric, Histogram):
            with metric._lock:
                series = [(f"{name}_count", labels, float(n))
                          for labels, n in metric._n.items()]
                series += [(f"{name}_sum", labels, s)
                           for labels, s in metric._sum.items()]
        elif isinstance(metric, (Counter, Gauge)):
            series = [(name, labels, v) for labels, v in metric.items().items()]
        else:
            continue
        for sname, labels, v in sorted(series, key=lambda t: (t[0], t[1])):
            if labels:
                joined = ",".join(str(x) for x in labels)
                lines.append(f'{sname}{{label="{joined}"}} {v:g}')
            else:
                lines.append(f"{sname} {v:g}")
    return "\n".join(lines) + "\n"


def parse_text(body: str) -> Dict[Tuple[str, Tuple], float]:
    """Inverse of render_text: {(series name, label tuple) → value}."""
    out: Dict[Tuple[str, Tuple], float] = {}
    for line in body.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        head, _, val = line.rpartition(" ")
        if not head:
            continue
        if "{" in head:
            name, _, rest = head.partition("{")
            joined = rest.rstrip("}").partition('label="')[2].rstrip('"')
            labels: Tuple = tuple(joined.split(",")) if joined else ()
        else:
            name, labels = head, ()
        try:
            out[(name, labels)] = float(val)
        except ValueError:
            continue
    return out
