"""Prometheus-name-compatible metrics (reference: pkg/scheduler/metrics)."""

from .registry import Histogram, Counter, Gauge, Registry, default_registry  # noqa: F401
from . import scheduler_metrics  # noqa: F401
