"""TaintToleration as a batched tensor program.

Reference: pkg/scheduler/framework/plugins/tainttoleration/taint_toleration.go
  Filter :64-82  — any untolerated NoSchedule/NoExecute taint →
                   UnschedulableAndUnresolvable
  Score  :133-162 — count of intolerable PreferNoSchedule taints (only tolerations
                   with effect "" or PreferNoSchedule participate)
  NormalizeScore :165-167 — DefaultNormalizeScore reversed
"""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.events import ActionType, ClusterEvent, EventResource
from ..framework.interface import Plugin
from ..framework.podbatch import TOL_OP_EXISTS
from ..state.dictionary import MISSING
from .helpers import default_normalize

# taint effect codes (state/encoding.py EFFECT_CODE)
NO_SCHEDULE, PREFER_NO_SCHEDULE, NO_EXECUTE = 0, 1, 2


def _tolerated(batch, snap, tol_mask_extra=None):
    """bool[B, N, T]: is taint t on node n tolerated by any toleration of pod b.

    Toleration.ToleratesTaint semantics: effect filter (empty → all), key filter
    (empty key → all, valid only with Exists), Exists → true, Equal → value match.
    """
    tk = snap.taint_keys[None, :, :, None]  # [1, N, T, 1]
    tv = snap.taint_vals[None, :, :, None]
    te = snap.taint_effects[None, :, :, None]
    pk = batch.tol_key[:, None, None, :]  # [B, 1, 1, TT]
    pv = batch.tol_val[:, None, None, :]
    pe = batch.tol_effect[:, None, None, :]
    po = batch.tol_op[:, None, None, :]
    ok = batch.tol_valid[:, None, None, :]
    if tol_mask_extra is not None:
        ok = ok & tol_mask_extra[:, None, None, :]
    key_ok = (pk == MISSING) | (pk == tk)
    effect_ok = (pe == -1) | (pe == te)
    value_ok = (po == TOL_OP_EXISTS) | (pv == tv)
    return jnp.any(ok & key_ok & effect_ok & value_ok, axis=-1)  # [B, N, T]


class TaintTolerationPlugin(Plugin):
    name = "TaintToleration"

    def events_to_register(self):
        return [ClusterEvent(EventResource.NODE, ActionType.ADD | ActionType.UPDATE_NODE_TAINT)]

    def filter(self, batch, snap, dyn, aux=None):
        hard = (snap.taint_effects == NO_SCHEDULE) | (snap.taint_effects == NO_EXECUTE)
        tolerated = _tolerated(batch, snap)  # [B, N, T]
        return jnp.all(~hard[None, :, :] | tolerated, axis=-1)  # [B, N]

    def score(self, batch, snap, dyn, aux=None, mask=None):
        # only tolerations with effect "" or PreferNoSchedule count (:133-147)
        extra = (batch.tol_effect == -1) | (batch.tol_effect == PREFER_NO_SCHEDULE)
        tolerated = _tolerated(batch, snap, extra)
        prefer = snap.taint_effects[None, :, :] == PREFER_NO_SCHEDULE
        return jnp.sum(prefer & ~tolerated, axis=-1).astype(jnp.float32)  # [B, N]

    def normalize(self, scores, mask):
        return default_normalize(scores, mask, reverse=True)
