"""InterPodAffinity as a batched tensor program with in-scan updates.

Reference: pkg/scheduler/framework/plugins/interpodaffinity/
  filtering.go:44-55,187-266 — PreFilter builds 3 topologyPair→count maps:
      existingAntiAffinityCounts (existing pods' req anti terms vs incoming pod),
      affinityCounts (existing pods matching ALL of incoming's req affinity terms),
      antiAffinityCounts (incoming's req anti terms vs existing pods, per term)
  filtering.go:308-360 — Filter: the three satisfy* checks, incl. the
      "first pod in a series" escape (affinityCounts empty + self-match)
  scoring.go:49-123   — PreScore accumulates weighted pair scores from 4 term
      sources (incoming pref ±, existing req×HardPodAffinityWeight, existing pref ±)
  scoring.go:255+     — NormalizeScore: 100·(s−min)/(max−min)

Device design: the *incoming* batch's term groups are compiled arrays, so the
incoming-vs-existing maps are matmuls + domain scatter-adds; the
*existing-pods'-own-terms* contributions (exist-anti blocks, symmetric score
terms) live in the INCREMENTAL device-resident group index
(state/affinity_index.py — maintained by deltas at encoder-sync time, the
round-6 replacement for the per-cycle host rebuild walk over
HavePodsWith(Required)AffinityList) and expand to [B, N] planes on device in
prepare().  In-scan, cross-match tensors between pending pods update the
tables/planes in O(B·N) per placement — the device analog of
preFilterState.updateWithPod (filtering.go:74-85); chain_prev extends the
same updates across still-in-flight batches for the deep pipeline.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from ..framework.events import ActionType, ClusterEvent, EventResource
from ..ops import domain_gather, domain_scatter_add, point_scatter_add
from ..ops.segment import domain_gather_backend
from ..framework.interface import MAX_NODE_SCORE, Plugin
from ..state.affinity_index import KIND_BLOCK, KIND_SCORE_REQ
from ..state.dictionary import MISSING
from .helpers import flat_selector_matrix

DEFAULT_HARD_POD_AFFINITY_WEIGHT = 1  # apis/config InterPodAffinityArgs default


class IPAAux(NamedTuple):
    # domain index of each node under each term's topology key; D = trash slot
    dom_aff: jnp.ndarray  # i32[B, T1, N]
    dom_anti: jnp.ndarray  # i32[B, T2, N]
    dom_paff: jnp.ndarray  # i32[B, T3, N]
    dom_panti: jnp.ndarray  # i32[B, T4, N]
    # Count state in one of two STATICALLY-chosen representations
    # (InterPodAffinityPlugin._use_planes): per-node PLANES [B, T, N]
    # (plane[b,t,n] = matching pods in node n's domain — O(N) step reads,
    # no O(N·D) gathers; right when D ≈ N, i.e. hostname topology) or the
    # original per-domain TABLES [B, T, D+1] (right when D ≪ N — carrying
    # [B,T,N] planes would cost ~N/D more per scan step than the tables).
    aff_cnt: jnp.ndarray  # i32[B, T1, N or D+1]
    anti_cnt: jnp.ndarray  # i32[B, T2, N or D+1]
    paff_cnt: jnp.ndarray  # i32[B, T3, N or D+1]
    panti_cnt: jnp.ndarray  # i32[B, T4, N or D+1]
    aff_total: jnp.ndarray  # i32[B] Σ affinityCounts (len()==0 test)
    self_match_all: jnp.ndarray  # bool[B]
    # host-precomputed static planes
    exist_anti_block: jnp.ndarray  # bool[B, N]
    score_static: jnp.ndarray  # f32[B, N]
    # cross-match tensors between pending pods (for in-scan updates)
    aff_term_cross: jnp.ndarray  # bool[B, T1, B] term t of pod b matches pod j
    aff_cross_all: jnp.ndarray  # bool[B, B] pod j matches ALL req-aff terms of b
    anti_cross: jnp.ndarray  # bool[B, T2, B]
    paff_cross: jnp.ndarray  # bool[B, T3, B]
    panti_cross: jnp.ndarray  # bool[B, T4, B]
    # dynamic planes accumulated during the scan
    block_dyn: jnp.ndarray  # bool[B, N]
    score_dyn: jnp.ndarray  # f32[B, N]


class InterPodAffinityPlugin(Plugin):
    name = "InterPodAffinity"
    dynamic = True

    def _d(self, batch) -> int:
        """Batch-local domain axis (PodBatch.ipa_domain_bucket): the global
        domain_cap covers every registered topo key, so one hostname key
        would size a zone-affinity batch's tables (and flip it to planes)
        for 5k domains when its own keys have 3."""
        return getattr(batch, "ipa_domain_bucket", None) or self.domain_cap

    def _use_planes(self, batch, snap) -> bool:
        """Static (trace-time) representation choice for the count state:
        per-node PLANES [B,T,N] when domains are dense (hostname topology,
        D ≈ N — the per-step table gathers would be O(N²)); per-domain
        TABLES [B,T,D+1] when D ≪ N (zone/rack topologies — carrying and
        rewriting [B,T,N] planes per scan step would cost ~N/D more than
        the tables they replace).  The bucket and num_nodes are both static
        shapes, so each regime compiles its own program."""
        return self._d(batch) * 4 >= snap.num_nodes

    def _present(self, batch, name: str) -> bool:
        """Static batch-content flag: does the batch have ANY valid term in
        this group?  Empty groups compile out of the per-step update work
        (PodBatch.group_present)."""
        from ..framework.podbatch import AFFINITY_GROUPS

        return name in getattr(batch, "group_present", AFFINITY_GROUPS)

    def _read_cnt(self, snap, cnt, dom):
        """cnt state → per-node counts [..., N] under either representation
        (planes iff the count axis IS the node axis; the table axis d+1 is
        odd, the node tier is a power of two, so the shapes never alias)."""
        if cnt.shape[-1] == dom.shape[-1]:
            return cnt
        return domain_gather(cnt, dom)

    def __init__(self, domain_cap: int = 256,
                 hard_pod_affinity_weight: int = DEFAULT_HARD_POD_AFFINITY_WEIGHT):
        self.domain_cap = domain_cap
        self.hard_weight = float(hard_pod_affinity_weight)

    def events_to_register(self):
        return [
            ClusterEvent(EventResource.POD, ActionType.ALL),
            ClusterEvent(EventResource.NODE, ActionType.ADD | ActionType.UPDATE_NODE_LABEL),
        ]

    # --- host precompute ------------------------------------------------------

    def host_prepare(self, batch, snapshot, encoder, namespace_labels=None):
        """Existing pods' own (anti)affinity terms → the per-batch match
        matrix against the encoder's INCREMENTAL affinity-group index.

        The per-cycle rebuild walk over HavePodsWith(Required)AffinityList
        (the measured host bottleneck of the 5k-node anti-affinity suite,
        178→336ms/cycle at 3k nodes and growing with cluster fill) moved to
        ``state/affinity_index.AffinityIndex``: contributions are applied
        once per pod state change at encoder-sync time (assume/forget/bind/
        node-delete), and the per-signature count tables are device-resident
        (DeviceSnapshot.aff_*) via the fused row-scatter upload.  Host work
        here is only the [live-groups × batch] match matrix, memoized per
        pod identity — O(batch delta) for templated workloads.  A full
        rebuild survives as the resync/repair path (AffinityIndex.rebuild).
        The index is hardPodAffinityWeight-FREE: required-affinity score
        groups store weight 1.0 and prepare() multiplies by THIS plugin's
        weight at expansion (a trace-time constant), so profiles configured
        with different weights share the one index without rebuild thrash."""
        return encoder.aff.match_batch(batch.pods, batch.size,
                                       namespace_labels)

    # --- device prepare -------------------------------------------------------

    def _group_arrays(self, group, snap, d):
        """dom [B, T, N] with trash slot, plus validity."""
        key = jnp.clip(group.topo_key, 0, snap.node_topo.shape[1] - 1)
        dom = jnp.transpose(snap.node_topo[:, key], (1, 2, 0))  # [B, T, N]
        has = (dom != MISSING) & jnp.asarray(group.valid)[:, :, None]
        return jnp.where(has, jnp.clip(dom, 0, d - 1), d)

    def _match_vs(self, group, keys, vals, ns, numeric):
        """Term (b, t) matches target pods → bool[B, T, P] (validity + ns + selector)."""
        b, t = group.valid.shape
        m = flat_selector_matrix(group.selectors, b, t, keys, vals, numeric)
        ns_ok = jnp.asarray(group.all_namespaces)[:, :, None] | jnp.any(
            jnp.asarray(group.ns_ids)[:, :, :, None] == ns[None, None, None, :],
            axis=2,
        )
        return m & ns_ok & jnp.asarray(group.valid)[:, :, None]

    def _counts(self, match, dom, pod_node, pod_valid, d):
        """Per-term matches of scheduled pods → domain tables.

        TPU: two contractions — matches×(pod→node one-hot) gives per-node
        counts, then a domain scatter-add folds nodes into domains (both
        MXU-friendly — the per-(pod,term) gather this replaces serializes
        on TPU).  CPU: the [P, N] one-hot materializes 33MB PER PREPARE at
        a 4k-pod/2k-node tier (measured as the affinity suites' dominant
        per-cycle device cost on the 1-core container) — a native
        last-axis ``.at[].add`` scatter is O(B·T·P) instead."""
        import jax

        b, t, _p = match.shape
        n = dom.shape[-1]
        prow = jnp.clip(pod_node, 0, n - 1)
        ok = match & pod_valid[None, None, :] & (pod_node >= 0)[None, None, :]
        if jax.default_backend() == "cpu":
            count_node = jnp.zeros((b, t, n), jnp.float32).at[..., prow].add(
                ok.astype(jnp.float32))
        else:
            onehot = (
                (prow[:, None] == jnp.arange(n)[None, :])
                & (pod_node >= 0)[:, None]
            ).astype(jnp.float32)  # [P, N]
            count_node = jnp.einsum(
                "btp,pn->btn", ok.astype(jnp.float32), onehot)
        from ..ops.segment import domain_scatter_add_backend

        tbl = domain_scatter_add_backend(count_node, dom, d + 1)  # trash at D
        return tbl.astype(jnp.int32)

    def prepare(self, batch, snap, dyn, host_aux=None):
        # STATIC skip: no affinity terms in the batch AND no existing-pod
        # anti-affinity/affinity host planes (host_aux is None) → this
        # plugin's O(N·D) domain programs are compiled out entirely
        if not getattr(batch, "has_affinity", True) and host_aux is None:
            return None
        d = self._d(batch)
        b = batch.valid.shape[0]
        n = snap.num_nodes
        g_aff, g_anti = batch.req_affinity, batch.req_anti_affinity
        g_paff, g_panti = batch.pref_affinity, batch.pref_anti_affinity
        num = snap.numeric
        use_planes = self._use_planes(batch, snap)

        def group_state(group, name, match_builder):
            """(dom, cnt, cross) for one term group — ABSENT groups compile
            to cheap broadcast zeros/trash instead of the [B,T,P] selector
            matrices and [B,T,P,N] count einsums (the dominant per-cycle
            prepare cost for constraint-sparse batches)."""
            t = group.valid.shape[1]
            if not self._present(batch, name):
                dom = jnp.full((b, t, n), d, jnp.int32)  # all-trash
                cnt_w = n if use_planes else d + 1
                cnt = jnp.zeros((b, t, cnt_w), jnp.int32)
                cross = jnp.zeros((b, t, b), bool)
                return dom, cnt, cross
            dom = self._group_arrays(group, snap, d)
            m = match_builder(
                group, snap.pod_label_keys, snap.pod_label_vals, snap.pod_ns)
            counts = self._counts(m, dom, snap.pod_node, snap.pod_valid, d)
            cnt = (domain_gather(counts, dom).astype(jnp.int32)
                   if use_planes else counts)
            cross = self._match_vs(
                group, batch.label_keys, batch.label_vals, batch.ns, num)
            return dom, cnt, cross, counts

        def plain_match(group, keys, vals, ns):
            return self._match_vs(group, keys, vals, ns, num)

        # req-affinity: affinityCounts count pods matching ALL terms
        has_terms = jnp.any(jnp.asarray(g_aff.valid), axis=1)  # [B]
        if self._present(batch, "req_affinity"):
            dom_aff = self._group_arrays(g_aff, snap, d)
            m_aff = plain_match(g_aff, snap.pod_label_keys,
                                snap.pod_label_vals, snap.pod_ns)
            all_match = (
                jnp.all(m_aff | ~jnp.asarray(g_aff.valid)[:, :, None], axis=1)
                & has_terms[:, None]
            )  # [B, P]
            m_aff_all = jnp.broadcast_to(
                all_match[:, None, :], m_aff.shape
            ) & jnp.asarray(g_aff.valid)[:, :, None]
            aff_counts = self._counts(
                m_aff_all, dom_aff, snap.pod_node, snap.pod_valid, d)
            aff_total = jnp.sum(aff_counts[..., :d], axis=(1, 2))  # [B]
            aff_cnt = (domain_gather(aff_counts, dom_aff).astype(jnp.int32)
                       if use_planes else aff_counts)
            x_aff = self._match_vs(
                g_aff, batch.label_keys, batch.label_vals, batch.ns, num)
            x_aff_all = (
                jnp.all(x_aff | ~jnp.asarray(g_aff.valid)[:, :, None], axis=1)
                & has_terms[:, None]
                & batch.valid[None, :]
            )  # [B, B]
        else:
            t1 = g_aff.valid.shape[1]
            dom_aff = jnp.full((b, t1, n), d, jnp.int32)
            aff_cnt = jnp.zeros(
                (b, t1, n if use_planes else d + 1), jnp.int32)
            aff_total = jnp.zeros((b,), jnp.int32)
            x_aff = jnp.zeros((b, t1, b), bool)
            x_aff_all = jnp.zeros((b, b), bool)

        dom_anti, anti_cnt, x_anti, *_ = group_state(
            g_anti, "req_anti_affinity", plain_match)
        dom_paff, paff_cnt, x_paff, *_ = group_state(
            g_paff, "pref_affinity", plain_match)
        dom_panti, panti_cnt, x_panti, *_ = group_state(
            g_panti, "pref_anti_affinity", plain_match)

        diag = jnp.arange(b)
        self_match_all = x_aff_all[diag, diag]

        if host_aux is None:
            exist_anti_block = jnp.zeros((b, n), bool)
            score_static = jnp.zeros((b, n), jnp.float32)
        else:
            # Expand the DEVICE-RESIDENT incremental group tables
            # (DeviceSnapshot.aff_*, maintained by scatter deltas at
            # assume/forget/node-delete time — state/affinity_index.py) into
            # the [B, N] block/score planes: per-group per-node owner counts
            # via one domain gather over the group's topology slot, then one
            # einsum against the host-computed [G, B] batch-match matrix.
            # Neither the count tables nor the dense planes ride the
            # host→device link per cycle.
            m = jnp.asarray(host_aux["match"])  # bool[G, B]
            k_cap = snap.node_topo.shape[1]
            slot = jnp.clip(snap.aff_slot, 0, k_cap - 1)
            dom_g = jnp.transpose(snap.node_topo[:, slot])  # [G, N]
            has = (dom_g != MISSING) & snap.aff_valid[:, None] \
                & (snap.aff_slot >= 0)[:, None]
            # domains at or past the table width have no recorded owners by
            # construction (the index grows the width before counting one) —
            # they must read 0, not alias into a clipped slot
            dwidth = snap.aff_counts.shape[1]
            has = has & (dom_g < dwidth)
            cnt = domain_gather_backend(
                snap.aff_counts,
                jnp.where(has, jnp.clip(dom_g, 0, dwidth - 1), 0),
            )
            cnt = jnp.where(has, cnt, 0.0)  # f32[G, N] owner counts
            mb = (m & (snap.aff_kind == KIND_BLOCK)[:, None]).astype(jnp.float32)
            exist_anti_block = jnp.einsum(
                "gb,gn->bn", mb, (cnt > 0.5).astype(jnp.float32)
            ) > 0.5
            # score rows: preferred groups carry their own signed weight;
            # required-affinity groups are stored weight-free and take THIS
            # plugin's hardPodAffinityWeight here (a trace-time constant, so
            # per-profile weights share one index)
            w = jnp.where(snap.aff_kind == KIND_SCORE_REQ,
                          jnp.float32(self.hard_weight), snap.aff_weight)
            ms = (m & (snap.aff_kind != KIND_BLOCK)[:, None]).astype(
                jnp.float32
            ) * w[:, None]
            score_static = jnp.einsum("gb,gn->bn", ms, cnt)
        return IPAAux(
            dom_aff=dom_aff, dom_anti=dom_anti, dom_paff=dom_paff, dom_panti=dom_panti,
            aff_cnt=aff_cnt, anti_cnt=anti_cnt,
            paff_cnt=paff_cnt, panti_cnt=panti_cnt,
            aff_total=aff_total, self_match_all=self_match_all,
            exist_anti_block=exist_anti_block,
            score_static=score_static,
            aff_term_cross=x_aff, aff_cross_all=x_aff_all, anti_cross=x_anti,
            paff_cross=x_paff, panti_cross=x_panti,
            block_dyn=jnp.zeros((b, n), bool),
            score_dyn=jnp.zeros((b, n), jnp.float32),
        )

    # --- filter ---------------------------------------------------------------

    def filter(self, batch, snap, dyn, aux: IPAAux):
        if aux is None:
            return jnp.ones((batch.valid.shape[0], snap.num_nodes), bool)
        d = self._d(batch)
        b, n = batch.valid.shape[0], snap.num_nodes
        if self._present(batch, "req_affinity"):
            g_aff_valid = jnp.asarray(batch.req_affinity.valid)  # [B, T1]
            # incoming required affinity (satisfyPodAffinity :338-360)
            cnt = self._read_cnt(snap, aux.aff_cnt, aux.dom_aff)  # [B, T1, N]
            key_ok = aux.dom_aff < d
            keys_all = jnp.all(~g_aff_valid[:, :, None] | key_ok, axis=1)
            pods_exist = jnp.all(~g_aff_valid[:, :, None] | (cnt > 0), axis=1)
            first_pod = (aux.aff_total == 0) & aux.self_match_all  # [B]
            aff_ok = keys_all & (pods_exist | first_pod[:, None])
        else:
            aff_ok = jnp.ones((b, n), bool)

        if self._present(batch, "req_anti_affinity"):
            g_anti_valid = jnp.asarray(batch.req_anti_affinity.valid)
            # incoming required anti-affinity (satisfyPodAntiAffinity :323-335)
            acnt = self._read_cnt(snap, aux.anti_cnt, aux.dom_anti)
            anti_bad = jnp.any(
                g_anti_valid[:, :, None] & (aux.dom_anti < d) & (acnt > 0),
                axis=1,
            )
            aff_ok = aff_ok & ~anti_bad

        return aff_ok & ~aux.exist_anti_block & ~aux.block_dyn

    # --- score ----------------------------------------------------------------

    def score(self, batch, snap, dyn, aux: IPAAux, mask=None):
        if aux is None:
            return jnp.zeros((batch.valid.shape[0], snap.num_nodes))
        d = self._d(batch)
        own = 0.0
        if self._present(batch, "pref_affinity"):
            w_paff = jnp.asarray(batch.pref_affinity.weight)  # [B, T3]
            c_paff = self._read_cnt(snap, aux.paff_cnt, aux.dom_paff)
            own = own + jnp.sum(
                jnp.where(aux.dom_paff < d, c_paff * w_paff[:, :, None], 0.0),
                axis=1)
        if self._present(batch, "pref_anti_affinity"):
            w_panti = jnp.asarray(batch.pref_anti_affinity.weight)
            c_panti = self._read_cnt(snap, aux.panti_cnt, aux.dom_panti)
            own = own - jnp.sum(
                jnp.where(aux.dom_panti < d, c_panti * w_panti[:, :, None], 0.0),
                axis=1)
        return own + aux.score_static + aux.score_dyn

    def normalize(self, scores, mask):
        """100·(s−min)/(max−min) over feasible nodes (scoring.go:255+)."""
        big = jnp.where(mask, scores, -jnp.inf)
        small = jnp.where(mask, scores, jnp.inf)
        mx = jnp.max(big, axis=-1, keepdims=True)
        mn = jnp.min(small, axis=-1, keepdims=True)
        diff = mx - mn
        ok = jnp.isfinite(diff) & (diff > 0)
        return jnp.where(
            ok & mask, MAX_NODE_SCORE * (scores - jnp.where(ok, mn, 0.0))
            / jnp.where(ok, diff, 1.0), 0.0
        )

    # --- row-sliced variants for the fast assignment scan ---------------------

    def filter_row(self, batch, snap, dyn, aux: IPAAux, i):
        if aux is None:
            return jnp.ones(snap.num_nodes, bool)
        d = self._d(batch)
        if self._present(batch, "req_affinity"):
            aff_valid = jnp.asarray(batch.req_affinity.valid)[i]  # [T1]
            cnt = self._read_cnt(snap, aux.aff_cnt[i], aux.dom_aff[i])
            key_ok = aux.dom_aff[i] < d
            keys_all = jnp.all(~aff_valid[:, None] | key_ok, axis=0)  # [N]
            pods_exist = jnp.all(~aff_valid[:, None] | (cnt > 0), axis=0)
            first_pod = (aux.aff_total[i] == 0) & aux.self_match_all[i]
            aff_ok = keys_all & (pods_exist | first_pod)
        else:
            aff_ok = jnp.ones(snap.num_nodes, bool)
        if self._present(batch, "req_anti_affinity"):
            anti_valid = jnp.asarray(batch.req_anti_affinity.valid)[i]
            acnt = self._read_cnt(snap, aux.anti_cnt[i], aux.dom_anti[i])
            anti_bad = jnp.any(
                anti_valid[:, None] & (aux.dom_anti[i] < d) & (acnt > 0),
                axis=0,
            )
            aff_ok = aff_ok & ~anti_bad
        return aff_ok & ~aux.exist_anti_block[i] & ~aux.block_dyn[i]

    def score_row(self, batch, snap, dyn, aux: IPAAux, i, mask_row=None):
        if aux is None:
            return jnp.zeros(snap.num_nodes)
        d = self._d(batch)
        own = 0.0
        if self._present(batch, "pref_affinity"):
            w_paff = jnp.asarray(batch.pref_affinity.weight)[i]  # [T3]
            c_paff = self._read_cnt(snap, aux.paff_cnt[i], aux.dom_paff[i])
            own = own + jnp.sum(
                jnp.where(aux.dom_paff[i] < d, c_paff * w_paff[:, None], 0.0),
                axis=0)
        if self._present(batch, "pref_anti_affinity"):
            w_panti = jnp.asarray(batch.pref_anti_affinity.weight)[i]
            c_panti = self._read_cnt(snap, aux.panti_cnt[i], aux.dom_panti[i])
            own = own - jnp.sum(
                jnp.where(aux.dom_panti[i] < d, c_panti * w_panti[:, None], 0.0),
                axis=0)
        return own + aux.score_static[i] + aux.score_dyn[i]

    # --- in-scan update -------------------------------------------------------

    def update(self, aux: IPAAux, i, node_row, batch, snap):
        if aux is None:
            return None
        """Pod i placed on node_row — the device analog of updateWithPod."""
        d = self._d(batch)
        t1 = aux.dom_aff.shape[1]

        use_planes = self._use_planes(batch, snap)

        def bump(cnt, dom, dom_at, inc):
            # inc[b,t] is already gated on (dom_at < d).  Planes: O(B·T·N)
            # same-domain compare-add (no D factor — the win for hostname
            # topology).  Tables: the original O(B·T·D) point scatter.
            if use_planes:
                same = dom == dom_at[:, :, None]
                return cnt + inc[:, :, None] * same.astype(cnt.dtype)
            return point_scatter_add(cnt, dom_at, inc)

        # 1) pending pods' affinityCounts: j gains where i matches ALL j's terms
        aff_cnt, aff_total = aux.aff_cnt, aux.aff_total
        if self._present(batch, "req_affinity"):
            dom_at_aff = aux.dom_aff[:, :, node_row]  # [B, T1]
            inc_aff = (
                aux.aff_cross_all[:, i][:, None]
                & jnp.asarray(batch.req_affinity.valid)
                & (dom_at_aff < d)
            ).astype(jnp.int32)
            aff_cnt = bump(aux.aff_cnt, aux.dom_aff, dom_at_aff, inc_aff)
            aff_total = aux.aff_total + jnp.sum(inc_aff, axis=1)

        # 2) pending pods' antiAffinityCounts (their own terms vs placed pod i)
        # 3) placed pod i's own req-anti terms block domains for matching pods j
        #    (anti_cross[i] is [T2, B]: term t of pod i vs pending pod j)
        anti_cnt, block_dyn = aux.anti_cnt, aux.block_dyn
        if self._present(batch, "req_anti_affinity"):
            dom_at_anti = aux.dom_anti[:, :, node_row]
            inc_anti = (aux.anti_cross[:, :, i] & (dom_at_anti < d)).astype(jnp.int32)
            anti_cnt = bump(aux.anti_cnt, aux.dom_anti, dom_at_anti, inc_anti)
            same_anti = (aux.dom_anti[i] == aux.dom_anti[i, :, node_row][:, None]) & (
                aux.dom_anti[i] < d
            )  # [T2, N]
            block_dyn = aux.block_dyn | jnp.any(
                aux.anti_cross[i][:, :, None] & same_anti[:, None, :], axis=0
            )  # [B, N]

        # 4) pending pods' own pref planes gain from placed pod i
        paff_cnt, panti_cnt = aux.paff_cnt, aux.panti_cnt
        if self._present(batch, "pref_affinity"):
            dom_at_paff = aux.dom_paff[:, :, node_row]
            paff_cnt = bump(
                aux.paff_cnt, aux.dom_paff, dom_at_paff,
                (aux.paff_cross[:, :, i] & (dom_at_paff < d)).astype(jnp.int32),
            )
        if self._present(batch, "pref_anti_affinity"):
            dom_at_panti = aux.dom_panti[:, :, node_row]
            panti_cnt = bump(
                aux.panti_cnt, aux.dom_panti, dom_at_panti,
                (aux.panti_cross[:, :, i] & (dom_at_panti < d)).astype(jnp.int32),
            )

        # 5) placed pod i's own terms add symmetric score for matching pods j:
        #    req-aff × hardWeight, pref-aff +w, pref-anti −w over i's term domains
        def plane(cross_i, dom_i, w_i):
            # cross_i [T, B], dom_i [T, N], w_i [T] → f32[B, N]
            same = ((dom_i == dom_i[:, node_row][:, None]) & (dom_i < d)).astype(jnp.float32)
            return jnp.einsum("tj,tn->jn", cross_i.astype(jnp.float32) * w_i[:, None], same)

        score_dyn = aux.score_dyn
        if self._present(batch, "req_affinity"):
            w1 = jnp.full((t1,), self.hard_weight, jnp.float32)
            score_dyn = score_dyn + plane(aux.aff_term_cross[i], aux.dom_aff[i], w1)
        if self._present(batch, "pref_affinity"):
            w3 = jnp.asarray(batch.pref_affinity.weight)[i]  # [T3]
            score_dyn = score_dyn + plane(aux.paff_cross[i], aux.dom_paff[i], w3)
        if self._present(batch, "pref_anti_affinity"):
            w4 = jnp.asarray(batch.pref_anti_affinity.weight)[i]
            score_dyn = score_dyn - plane(aux.panti_cross[i], aux.dom_panti[i], w4)

        return aux._replace(
            aff_cnt=aff_cnt, aff_total=aff_total, anti_cnt=anti_cnt,
            block_dyn=block_dyn, paff_cnt=paff_cnt, panti_cnt=panti_cnt,
            score_dyn=score_dyn,
        )

    # --- deep-pipeline cross-batch chaining -----------------------------------

    def chain_prev(self, aux: IPAAux, batch, snap, prev):
        """Fold a still-in-flight previous batch's placements into this
        batch's affinity state, exactly as if those pods were already in the
        snapshot — the device analog of what the next encoder sync + the
        incremental affinity index will record once the prev batch's assume
        lands.  This is what lets affinity-carrying batches ride the DEEP
        pipeline (pre-round-6 they forced depth 1, the documented root cause
        of the coupled-suite gap).

        Two halves, mirroring update_batch with the prev batch in the
        committed role:
          (i)  this batch's four term groups vs the prev batch's pod labels
               (PrevBatch.label_keys/label_vals/ns) bump this batch's count
               tables at the domain of each placed prev pod's node;
          (ii) the prev batch's OWN terms (PrevBatch.req_affinity …, carried
               only when the dispatching batch has affinity content — see
               TPUScheduler._dispatch_batch) block/score this batch's
               matching pods over the prev terms' topology domains, using
               RAW topology values (no domain bucketing, so chained batches
               with different ipa_domain_buckets stay exact).
        A no-op bundle (all rows -1) leaves every table unchanged, so
        shallow and deep cycles share one compiled program per variant."""
        if aux is None:
            return None
        # Static gate on the GROUP-CARRYING pytree variant: the scheduler
        # attaches term groups to every carry slot (real or zeroed) exactly
        # when affinity chaining is on AND the batch has affinity content.
        # Group-free carries mean nothing affinity-relevant can be in
        # flight, and tracing part (i)'s [B,T,N,D] scatter one-hots against
        # guaranteed-noop slots cost a measured ~0.27s/cycle on the CPU
        # backend's scaled preferred-affinity suite.
        if prev.req_anti_affinity is None:
            return aux
        d = self._d(batch)
        use_planes = self._use_planes(batch, snap)
        n = snap.num_nodes
        num = snap.numeric
        placed = (prev.rows >= 0) & jnp.asarray(prev.valid)  # [B0]
        rows = jnp.clip(prev.rows, 0, n - 1)
        u = (
            (rows[:, None] == jnp.arange(n)[None, :]) & placed[:, None]
        ).astype(jnp.float32)  # [B0, N] placement one-hot (zero row = unplaced)

        def count_inc(cross, dom):
            """cross [B, T, B0] (this batch's term (b,t) vs prev pod j) →
            count bump in the active representation + table mass, exactly
            update_batch's count_inc with the prev placement one-hot."""
            contrib = jnp.einsum("btj,jn->btn", cross.astype(jnp.float32), u)
            tbl = domain_scatter_add(contrib, dom, d + 1)
            tbl = tbl * (jnp.arange(d + 1) < d)
            inc = domain_gather(tbl, dom) if use_planes else tbl
            return inc, jnp.sum(tbl, axis=(1, 2))

        aff_cnt, aff_total = aux.aff_cnt, aux.aff_total
        if self._present(batch, "req_affinity"):
            g = batch.req_affinity
            gv = jnp.asarray(g.valid)
            m = self._match_vs(g, prev.label_keys, prev.label_vals, prev.ns, num)
            has_terms = jnp.any(gv, axis=1)
            x_all = jnp.all(m | ~gv[:, :, None], axis=1) & has_terms[:, None]
            inc, mass = count_inc(x_all[:, None, :] & gv[:, :, None], aux.dom_aff)
            aff_cnt = aff_cnt + inc.astype(jnp.int32)
            aff_total = aff_total + mass.astype(jnp.int32)
        anti_cnt = aux.anti_cnt
        if self._present(batch, "req_anti_affinity"):
            m = self._match_vs(batch.req_anti_affinity, prev.label_keys,
                               prev.label_vals, prev.ns, num)
            anti_cnt = anti_cnt + count_inc(m, aux.dom_anti)[0].astype(jnp.int32)
        paff_cnt = aux.paff_cnt
        if self._present(batch, "pref_affinity"):
            m = self._match_vs(batch.pref_affinity, prev.label_keys,
                               prev.label_vals, prev.ns, num)
            paff_cnt = paff_cnt + count_inc(m, aux.dom_paff)[0].astype(jnp.int32)
        panti_cnt = aux.panti_cnt
        if self._present(batch, "pref_anti_affinity"):
            m = self._match_vs(batch.pref_anti_affinity, prev.label_keys,
                               prev.label_vals, prev.ns, num)
            panti_cnt = panti_cnt + count_inc(m, aux.dom_panti)[0].astype(jnp.int32)

        # part (ii): the prev batch's OWN terms (the top gate guarantees the
        # groups are present from here on)
        k_cap = snap.node_topo.shape[1]

        def own_terms(pgroup):
            """(mm [B0, T, B1], same [B0, T, N]) for one PREV group:
            which of this batch's pods each prev term matches, and which
            nodes share the prev pod's placed-node topology value under
            that term's key (raw values — bucket-free)."""
            pv = jnp.asarray(pgroup.valid)
            mm = self._match_vs(pgroup, batch.label_keys,
                                batch.label_vals, batch.ns, num)
            key = jnp.clip(pgroup.topo_key, 0, k_cap - 1)
            domp = jnp.transpose(snap.node_topo[:, key], (1, 2, 0))
            hasp = (domp != MISSING) & pv[:, :, None]  # [B0, T, N]
            domp_f = jnp.where(hasp, domp, 0).astype(jnp.float32)
            dom_at = jnp.einsum("jtn,jn->jt", domp_f, u)
            has_at = jnp.einsum(
                "jtn,jn->jt", hasp.astype(jnp.float32), u) > 0.5
            same = hasp & has_at[:, :, None] & (
                domp_f == dom_at[:, :, None])
            return mm, same

        mm, same = own_terms(prev.req_anti_affinity)
        block_dyn = aux.block_dyn | (jnp.einsum(
            "jtb,jtn->bn", mm.astype(jnp.float32),
            same.astype(jnp.float32)) > 0.5)

        def own_score(pgroup, weights):
            mm, same = own_terms(pgroup)
            return jnp.einsum(
                "jtb,jtn->bn",
                mm.astype(jnp.float32) * weights[:, :, None],
                same.astype(jnp.float32),
            )

        score_dyn = aux.score_dyn
        if self.hard_weight > 0:
            w1 = jnp.full(
                jnp.asarray(prev.req_affinity.valid).shape,
                self.hard_weight, jnp.float32)
            score_dyn = score_dyn + own_score(prev.req_affinity, w1)
        score_dyn = score_dyn + own_score(
            prev.pref_affinity, jnp.asarray(prev.pref_affinity.weight))
        score_dyn = score_dyn - own_score(
            prev.pref_anti_affinity,
            jnp.asarray(prev.pref_anti_affinity.weight))

        return aux._replace(
            aff_cnt=aff_cnt, aff_total=aff_total, anti_cnt=anti_cnt,
            paff_cnt=paff_cnt, panti_cnt=panti_cnt,
            block_dyn=block_dyn, score_dyn=score_dyn,
        )

    def host_aux_take(self, aux, rows):
        """Identity-class rep view of the host aux: the [G, B] batch-match
        matrix's columns are pure functions of (namespace, labels) — class
        content — so gathering the rep columns is exact.  ``rows`` may be a
        traced i32 vector (the dedup path gathers inside the fused
        program)."""
        if aux is None:
            return None
        return {"match": jnp.asarray(aux["match"])[:, rows]}

    def update_batch_classes(self, aux: IPAAux, u_c, batch, rep_batch, snap,
                             class_of):
        """update_batch at identity-class granularity (the dedup engine's
        round update, runtime.py _batch_assign_dedup): the pending axis is
        the C class reps, and the round's commits arrive pre-aggregated as
        the CLASS placement counts ``u_c`` f32[Cp, N] (committer class →
        node).  Every cross tensor is a pure function of the two pods'
        classes, so folding committers per class is exact — and the whole
        round update is O(C·T·N) instead of the full path's O(B·T·N)."""
        if aux is None:
            return None
        d = self._d(rep_batch)
        use_planes = self._use_planes(rep_batch, snap)
        # backend-aware domain ops: these run once per AUCTION ROUND, and at
        # hostname topology (D ≈ N) the one-hot einsum forms are O(N²)
        # memory traffic per round on the CPU backend — measured as the
        # whole preferred-affinity window (19s of 20s) before the switch
        from ..ops.segment import domain_gather_backend as _dgather
        from ..ops.segment import domain_scatter_add_backend as _dscatter

        def count_inc(cross_kk, dom):
            # cross_kk [C, T, C]: term (c, t) vs a committer CLASS k; the
            # class form of update_batch's "bti,in->btn" contraction
            contrib = jnp.einsum(
                "ctk,kn->ctn", cross_kk.astype(jnp.float32), u_c)
            tbl = _dscatter(contrib, dom, d + 1)
            tbl = tbl * (jnp.arange(d + 1) < d)
            inc = _dgather(tbl, dom) if use_planes else tbl
            return inc, jnp.sum(tbl, axis=(1, 2))

        aff_cnt, aff_total = aux.aff_cnt, aux.aff_total
        if self._present(rep_batch, "req_affinity"):
            gv = jnp.asarray(rep_batch.req_affinity.valid)
            aff_cross = aux.aff_cross_all[:, None, :] & gv[:, :, None]
            inc, mass = count_inc(aff_cross, aux.dom_aff)
            aff_cnt = aux.aff_cnt + inc.astype(jnp.int32)
            aff_total = aux.aff_total + mass.astype(jnp.int32)
        anti_cnt = aux.anti_cnt
        if self._present(rep_batch, "req_anti_affinity"):
            anti_cnt = aux.anti_cnt + count_inc(
                aux.anti_cross, aux.dom_anti)[0].astype(jnp.int32)
        paff_cnt = aux.paff_cnt
        if self._present(rep_batch, "pref_affinity"):
            paff_cnt = aux.paff_cnt + count_inc(
                aux.paff_cross, aux.dom_paff)[0].astype(jnp.int32)
        panti_cnt = aux.panti_cnt
        if self._present(rep_batch, "pref_anti_affinity"):
            panti_cnt = aux.panti_cnt + count_inc(
                aux.panti_cross, aux.dom_panti)[0].astype(jnp.int32)

        def same_mass(dom):
            # committed classes' same-domain commit mass per node: scatter
            # u_c into each term's domain space, zero the trash column
            # (absent-key nodes and absent-key commits contribute nothing —
            # update_batch's (dom < d) gates), gather back per node.  The
            # class form of same_domains, f32 multiplicity instead of bool.
            w = _dscatter(
                jnp.broadcast_to(u_c[:, None, :], dom.shape), dom, d + 1)
            w = w * (jnp.arange(d + 1) < d)
            return _dgather(w, dom)  # f32[C, T, N]

        block_dyn = aux.block_dyn
        if self._present(rep_batch, "req_anti_affinity"):
            block_add = jnp.einsum(
                "ktj,ktn->jn", aux.anti_cross.astype(jnp.float32),
                same_mass(aux.dom_anti)) > 0.5
            block_dyn = aux.block_dyn | block_add

        def plane(cross, dom, w):
            return jnp.einsum(
                "ktj,ktn->jn", cross.astype(jnp.float32) * w, same_mass(dom))

        score_dyn = aux.score_dyn
        if self._present(rep_batch, "req_affinity"):
            w1 = jnp.full(aux.dom_aff.shape[:2], self.hard_weight,
                          jnp.float32)[:, :, None]
            score_dyn = score_dyn + plane(aux.aff_term_cross, aux.dom_aff, w1)
        if self._present(rep_batch, "pref_affinity"):
            w3 = jnp.asarray(rep_batch.pref_affinity.weight)[:, :, None]
            score_dyn = score_dyn + plane(aux.paff_cross, aux.dom_paff, w3)
        if self._present(rep_batch, "pref_anti_affinity"):
            w4 = jnp.asarray(rep_batch.pref_anti_affinity.weight)[:, :, None]
            score_dyn = score_dyn - plane(aux.panti_cross, aux.dom_panti, w4)

        return aux._replace(
            aff_cnt=aff_cnt, aff_total=aff_total, anti_cnt=anti_cnt,
            block_dyn=block_dyn, paff_cnt=paff_cnt, panti_cnt=panti_cnt,
            score_dyn=score_dyn,
        )

    def update_batch(self, aux: IPAAux, commit, choice, u, batch, snap):
        if aux is None:
            return None
        """All of a round's placements at once (batch_assign): every per-pod
        contribution in `update` is a commutative add/OR, so the whole round
        folds into einsum contractions against the commit one-hot ``u``
        [B, N] (placed pod i → its node)."""
        d = self._d(batch)

        use_planes = self._use_planes(batch, snap)

        def count_inc(cross, dom):
            """cross [B, T, B] (term (b,t) vs pending pod i) → (count-state
            bump in the active representation, table mass [B]) from all
            committed pods: scatter to domains, zero the trash column (the
            serial path never bumps trash), then gather back when carrying
            planes — O(N·D) once per round, not per scan step."""
            contrib = jnp.einsum("bti,in->btn", cross.astype(jnp.float32), u)
            tbl = domain_scatter_add(contrib, dom, d + 1)
            tbl = tbl * (jnp.arange(d + 1) < d)
            inc = domain_gather(tbl, dom) if use_planes else tbl
            return inc, jnp.sum(tbl, axis=(1, 2))

        aff_cnt, aff_total = aux.aff_cnt, aux.aff_total
        if self._present(batch, "req_affinity"):
            g_aff_valid = jnp.asarray(batch.req_affinity.valid)
            aff_cross = (
                aux.aff_cross_all[:, None, :] & g_aff_valid[:, :, None]
            )  # [B, T1, B]
            aff_inc, aff_mass = count_inc(aff_cross, aux.dom_aff)
            # aff_total adds the TABLE mass (one bump per domain), not the
            # plane mass (which would multiply by domain size)
            aff_total = aux.aff_total + aff_mass.astype(jnp.int32)
            aff_cnt = aux.aff_cnt + aff_inc.astype(jnp.int32)
        anti_cnt = aux.anti_cnt
        if self._present(batch, "req_anti_affinity"):
            anti_cnt = aux.anti_cnt + count_inc(
                aux.anti_cross, aux.dom_anti
            )[0].astype(jnp.int32)
        paff_cnt = aux.paff_cnt
        if self._present(batch, "pref_affinity"):
            paff_cnt = aux.paff_cnt + count_inc(
                aux.paff_cross, aux.dom_paff
            )[0].astype(jnp.int32)
        panti_cnt = aux.panti_cnt
        if self._present(batch, "pref_anti_affinity"):
            panti_cnt = aux.panti_cnt + count_inc(
                aux.panti_cross, aux.dom_panti
            )[0].astype(jnp.int32)

        def same_domains(dom):
            """same[i, t, n] — node n shares committed pod i's domain under
            i's term t (zero rows for uncommitted pods since u is zero)."""
            dom_at = jnp.einsum("itn,in->it", dom.astype(jnp.float32), u)
            return (
                (dom.astype(jnp.float32) == dom_at[:, :, None])
                & (dom < d)
                & commit[:, None, None]
            )

        # placed pods' own req-anti terms block matching pods over their domains
        block_dyn = aux.block_dyn
        if self._present(batch, "req_anti_affinity"):
            same_anti = same_domains(aux.dom_anti)
            block_add = (
                jnp.einsum(
                    "itj,itn->jn",
                    aux.anti_cross.astype(jnp.float32),
                    same_anti.astype(jnp.float32),
                )
                > 0.5
            )
            block_dyn = aux.block_dyn | block_add

        # symmetric score: placed pods' own terms credit matching pods
        def plane(cross, dom, w):
            same = same_domains(dom).astype(jnp.float32)
            return jnp.einsum(
                "itj,itn->jn", cross.astype(jnp.float32) * w, same
            )

        score_dyn = aux.score_dyn
        if self._present(batch, "req_affinity"):
            w1 = jnp.full(aux.dom_aff.shape[:2], self.hard_weight, jnp.float32)[
                :, :, None
            ]
            score_dyn = score_dyn + plane(aux.aff_term_cross, aux.dom_aff, w1)
        if self._present(batch, "pref_affinity"):
            w3 = jnp.asarray(batch.pref_affinity.weight)[:, :, None]
            score_dyn = score_dyn + plane(aux.paff_cross, aux.dom_paff, w3)
        if self._present(batch, "pref_anti_affinity"):
            w4 = jnp.asarray(batch.pref_anti_affinity.weight)[:, :, None]
            score_dyn = score_dyn - plane(aux.panti_cross, aux.dom_panti, w4)

        return aux._replace(
            aff_cnt=aff_cnt, aff_total=aff_total, anti_cnt=anti_cnt,
            block_dyn=block_dyn, paff_cnt=paff_cnt, panti_cnt=panti_cnt,
            score_dyn=score_dyn,
        )
