"""InterPodAffinity as a batched tensor program with in-scan updates.

Reference: pkg/scheduler/framework/plugins/interpodaffinity/
  filtering.go:44-55,187-266 — PreFilter builds 3 topologyPair→count maps:
      existingAntiAffinityCounts (existing pods' req anti terms vs incoming pod),
      affinityCounts (existing pods matching ALL of incoming's req affinity terms),
      antiAffinityCounts (incoming's req anti terms vs existing pods, per term)
  filtering.go:308-360 — Filter: the three satisfy* checks, incl. the
      "first pod in a series" escape (affinityCounts empty + self-match)
  scoring.go:49-123   — PreScore accumulates weighted pair scores from 4 term
      sources (incoming pref ±, existing req×HardPodAffinityWeight, existing pref ±)
  scoring.go:255+     — NormalizeScore: 100·(s−min)/(max−min)

Device design: the *incoming* batch's term groups are compiled arrays, so the
incoming-vs-existing maps are matmuls + domain scatter-adds; the sparse
*existing-pods'-own-terms* contributions (exist-anti blocks, symmetric score
terms) are precomputed host-side over HavePodsWith(Required)AffinityList —
mirroring exactly which pods the reference walks (scoring.go:149-159).
In-scan, cross-match tensors between pending pods update the tables/planes in
O(B·N) per placement — the device analog of preFilterState.updateWithPod
(filtering.go:74-85).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from ..api.labels import affinity_term_matches
from ..framework.events import ActionType, ClusterEvent, EventResource
from ..ops import domain_gather, domain_scatter_add, point_scatter_add
from ..framework.interface import MAX_NODE_SCORE, Plugin
from ..state.dictionary import MISSING
from .helpers import flat_selector_matrix

DEFAULT_HARD_POD_AFFINITY_WEIGHT = 1  # apis/config InterPodAffinityArgs default


def _pow2_g(x: int) -> int:
    """Smallest pow2 ≥ max(x, 1) (signature-group capacity)."""
    g = 1
    while g < max(x, 1):
        g *= 2
    return g


def _selector_signature(sel) -> tuple:
    """Hashable identity of a LabelSelector's match semantics."""
    if sel is None:
        return None
    return (
        tuple(sorted(sel.match_labels.items())),
        tuple(
            (e.key, e.operator, tuple(e.values)) for e in sel.match_expressions
        ),
    )


def _term_signature(term, owner_ns: str) -> tuple:
    """Two terms with equal signatures match exactly the same target pods
    (affinity_term_matches semantics: namespaces list, namespaceSelector, the
    owner-namespace default when both are unset, and the label selector)."""
    if term.namespaces:
        ns_key = ("list", tuple(sorted(term.namespaces)))
        if term.namespace_selector is not None:
            ns_key = ns_key + ("sel", _selector_signature(term.namespace_selector))
    elif term.namespace_selector is not None:
        ns_key = ("sel", _selector_signature(term.namespace_selector))
    else:
        ns_key = ("owner", owner_ns)
    return (term.topology_key, ns_key, _selector_signature(term.label_selector))


class IPAAux(NamedTuple):
    # domain index of each node under each term's topology key; D = trash slot
    dom_aff: jnp.ndarray  # i32[B, T1, N]
    dom_anti: jnp.ndarray  # i32[B, T2, N]
    dom_paff: jnp.ndarray  # i32[B, T3, N]
    dom_panti: jnp.ndarray  # i32[B, T4, N]
    # Count state in one of two STATICALLY-chosen representations
    # (InterPodAffinityPlugin._use_planes): per-node PLANES [B, T, N]
    # (plane[b,t,n] = matching pods in node n's domain — O(N) step reads,
    # no O(N·D) gathers; right when D ≈ N, i.e. hostname topology) or the
    # original per-domain TABLES [B, T, D+1] (right when D ≪ N — carrying
    # [B,T,N] planes would cost ~N/D more per scan step than the tables).
    aff_cnt: jnp.ndarray  # i32[B, T1, N or D+1]
    anti_cnt: jnp.ndarray  # i32[B, T2, N or D+1]
    paff_cnt: jnp.ndarray  # i32[B, T3, N or D+1]
    panti_cnt: jnp.ndarray  # i32[B, T4, N or D+1]
    aff_total: jnp.ndarray  # i32[B] Σ affinityCounts (len()==0 test)
    self_match_all: jnp.ndarray  # bool[B]
    # host-precomputed static planes
    exist_anti_block: jnp.ndarray  # bool[B, N]
    score_static: jnp.ndarray  # f32[B, N]
    # cross-match tensors between pending pods (for in-scan updates)
    aff_term_cross: jnp.ndarray  # bool[B, T1, B] term t of pod b matches pod j
    aff_cross_all: jnp.ndarray  # bool[B, B] pod j matches ALL req-aff terms of b
    anti_cross: jnp.ndarray  # bool[B, T2, B]
    paff_cross: jnp.ndarray  # bool[B, T3, B]
    panti_cross: jnp.ndarray  # bool[B, T4, B]
    # dynamic planes accumulated during the scan
    block_dyn: jnp.ndarray  # bool[B, N]
    score_dyn: jnp.ndarray  # f32[B, N]


class InterPodAffinityPlugin(Plugin):
    name = "InterPodAffinity"
    dynamic = True

    def _d(self, batch) -> int:
        """Batch-local domain axis (PodBatch.ipa_domain_bucket): the global
        domain_cap covers every registered topo key, so one hostname key
        would size a zone-affinity batch's tables (and flip it to planes)
        for 5k domains when its own keys have 3."""
        return getattr(batch, "ipa_domain_bucket", None) or self.domain_cap

    def _use_planes(self, batch, snap) -> bool:
        """Static (trace-time) representation choice for the count state:
        per-node PLANES [B,T,N] when domains are dense (hostname topology,
        D ≈ N — the per-step table gathers would be O(N²)); per-domain
        TABLES [B,T,D+1] when D ≪ N (zone/rack topologies — carrying and
        rewriting [B,T,N] planes per scan step would cost ~N/D more than
        the tables they replace).  The bucket and num_nodes are both static
        shapes, so each regime compiles its own program."""
        return self._d(batch) * 4 >= snap.num_nodes

    def _present(self, batch, name: str) -> bool:
        """Static batch-content flag: does the batch have ANY valid term in
        this group?  Empty groups compile out of the per-step update work
        (PodBatch.group_present)."""
        from ..framework.podbatch import AFFINITY_GROUPS

        return name in getattr(batch, "group_present", AFFINITY_GROUPS)

    def _read_cnt(self, snap, cnt, dom):
        """cnt state → per-node counts [..., N] under either representation
        (planes iff the count axis IS the node axis; the table axis d+1 is
        odd, the node tier is a power of two, so the shapes never alias)."""
        if cnt.shape[-1] == dom.shape[-1]:
            return cnt
        return domain_gather(cnt, dom)

    def __init__(self, domain_cap: int = 256,
                 hard_pod_affinity_weight: int = DEFAULT_HARD_POD_AFFINITY_WEIGHT):
        self.domain_cap = domain_cap
        self.hard_weight = float(hard_pod_affinity_weight)

    def events_to_register(self):
        return [
            ClusterEvent(EventResource.POD, ActionType.ALL),
            ClusterEvent(EventResource.NODE, ActionType.ADD | ActionType.UPDATE_NODE_LABEL),
        ]

    # --- host precompute ------------------------------------------------------

    def host_prepare(self, batch, snapshot, encoder, namespace_labels=None):
        """Existing pods' own (anti)affinity terms → static block/score planes.

        Walks only HavePodsWithRequiredAntiAffinityList / HavePodsWithAffinityList
        (sparse), like the reference — but DEDUPLICATED by term signature:
        identical terms (selector + namespaces + topology key + weight; the
        common case is a workload's replicas all carrying the same term) are
        matched against the batch ONCE, and their owners' topology-domain
        values aggregate into one count table per signature.  The naive walk
        was O(scheduled_pods × batch) Python selector matches per cycle —
        the measured host bottleneck of the 5k-node anti-affinity suite,
        growing as the run scheduled more pods (178→336ms/cycle profiled at
        3k nodes).
        """
        b = batch.size
        n = encoder._n
        node_topo = encoder.node_topo

        # sig → [representative term, representative owner pod, topo slot,
        #        {domain val → owner-term count}]
        groups: dict = {}

        def collect(pi, term, kind, weight):
            slot = encoder.topo_slot(term.topology_key)
            row = encoder.node_rows.get(pi.pod.spec.node_name)
            if row is None:
                return
            val = int(node_topo[row, slot])
            if val == MISSING:
                return
            sig = (kind, weight, _term_signature(term, pi.pod.namespace))
            g = groups.get(sig)
            if g is None:
                groups[sig] = g = [term, pi.pod, slot, {}]
            g[3][val] = g[3].get(val, 0) + 1

        for info in snapshot.have_pods_with_required_anti_affinity_list:
            for pi in info.pods_with_required_anti_affinity:
                for term in pi.required_anti_affinity_terms:
                    collect(pi, term, "block", 0.0)
        for info in snapshot.have_pods_with_affinity_list:
            for pi in info.pods_with_affinity:
                if self.hard_weight > 0:
                    for term in pi.required_affinity_terms:
                        collect(pi, term, "score", self.hard_weight)
                for wt in pi.preferred_affinity_terms:
                    collect(pi, wt.pod_affinity_term, "score", float(wt.weight))
                for wt in pi.preferred_anti_affinity_terms:
                    collect(pi, wt.pod_affinity_term, "score", -float(wt.weight))

        if not groups:
            # nothing in the cluster interacts with this batch — skip the
            # [B, N] bool + f32 uploads; prepare() makes traced zeros instead
            return None

        # COMPACT upload form: per-signature (batch-match row, node plane)
        # factor pairs instead of dense [B, N] planes.  The dense block +
        # score planes are ~5MB/cycle at 5k nodes, and the host→device
        # tunnel flush of that upload (~15MB/s effective) dominated the
        # anti-affinity cycle; the factored form is G×(B+N) ≈ tens of KB
        # and expands on device in prepare() (one einsum).
        blk_rows: list = []  # (match[B] bool, plane[N] bool)
        sc_rows: list = []  # (match[B] bool, plane[N] f32)
        for (kind, weight, _s), (term, owner, slot, val_counts) in groups.items():
            matched = np.zeros(b, dtype=bool)
            for i, pod in enumerate(batch.pods):
                if affinity_term_matches(term, owner, pod, namespace_labels):
                    matched[i] = True
            if not matched.any():
                continue
            node_vals = node_topo[:, slot]  # [N]
            if kind == "block":
                nmask = np.isin(
                    node_vals, np.fromiter(val_counts, dtype=np.int64)
                )
                blk_rows.append((matched, nmask))
            else:
                # per-node owner count under this signature's key, via LUT
                lut = np.zeros(int(node_vals.max(initial=0)) + 2, np.float32)
                for v, c in val_counts.items():
                    if 0 <= v < lut.size:
                        lut[v] = c
                per_node = lut[np.clip(node_vals, 0, lut.size - 1)]
                per_node = np.where(node_vals == MISSING, 0.0, per_node)
                sc_rows.append((matched, weight * per_node))
        if not blk_rows and not sc_rows:
            return None
        # sticky pow2 caps so signature-count churn doesn't recompile
        gb = max(_pow2_g(len(blk_rows)), getattr(self, "_gb_cap", 2))
        gs = max(_pow2_g(len(sc_rows)), getattr(self, "_gs_cap", 2))
        self._gb_cap, self._gs_cap = gb, gs
        blk_match = np.zeros((gb, b), dtype=bool)
        blk_plane = np.zeros((gb, n), dtype=bool)
        for g, (mrow, prow) in enumerate(blk_rows):
            blk_match[g], blk_plane[g] = mrow, prow
        sc_match = np.zeros((gs, b), dtype=bool)
        sc_plane = np.zeros((gs, n), dtype=np.float32)
        for g, (mrow, prow) in enumerate(sc_rows):
            sc_match[g], sc_plane[g] = mrow, prow
        return {
            "blk_match": blk_match, "blk_plane": blk_plane,
            "sc_match": sc_match, "sc_plane": sc_plane,
        }

    # --- device prepare -------------------------------------------------------

    def _group_arrays(self, group, snap, d):
        """dom [B, T, N] with trash slot, plus validity."""
        key = jnp.clip(group.topo_key, 0, snap.node_topo.shape[1] - 1)
        dom = jnp.transpose(snap.node_topo[:, key], (1, 2, 0))  # [B, T, N]
        has = (dom != MISSING) & jnp.asarray(group.valid)[:, :, None]
        return jnp.where(has, jnp.clip(dom, 0, d - 1), d)

    def _match_vs(self, group, keys, vals, ns, numeric):
        """Term (b, t) matches target pods → bool[B, T, P] (validity + ns + selector)."""
        b, t = group.valid.shape
        m = flat_selector_matrix(group.selectors, b, t, keys, vals, numeric)
        ns_ok = jnp.asarray(group.all_namespaces)[:, :, None] | jnp.any(
            jnp.asarray(group.ns_ids)[:, :, :, None] == ns[None, None, None, :],
            axis=2,
        )
        return m & ns_ok & jnp.asarray(group.valid)[:, :, None]

    def _counts(self, match, dom, pod_node, pod_valid, d):
        """Per-term matches of scheduled pods → domain tables, as two
        contractions: matches×(pod→node one-hot) gives per-node counts, then
        a domain scatter-add folds nodes into domains (both MXU-friendly —
        the per-(pod,term) gather this replaces serializes on TPU)."""
        b, t, _p = match.shape
        n = dom.shape[-1]
        prow = jnp.clip(pod_node, 0, n - 1)
        ok = match & pod_valid[None, None, :] & (pod_node >= 0)[None, None, :]
        onehot = (
            (prow[:, None] == jnp.arange(n)[None, :]) & (pod_node >= 0)[:, None]
        ).astype(jnp.float32)  # [P, N]
        count_node = jnp.einsum("btp,pn->btn", ok.astype(jnp.float32), onehot)
        tbl = domain_scatter_add(count_node, dom, d + 1)  # trash slot at D absorbs
        return tbl.astype(jnp.int32)

    def prepare(self, batch, snap, dyn, host_aux=None):
        # STATIC skip: no affinity terms in the batch AND no existing-pod
        # anti-affinity/affinity host planes (host_aux is None) → this
        # plugin's O(N·D) domain programs are compiled out entirely
        if not getattr(batch, "has_affinity", True) and host_aux is None:
            return None
        d = self._d(batch)
        b = batch.valid.shape[0]
        n = snap.num_nodes
        g_aff, g_anti = batch.req_affinity, batch.req_anti_affinity
        g_paff, g_panti = batch.pref_affinity, batch.pref_anti_affinity
        num = snap.numeric
        use_planes = self._use_planes(batch, snap)

        def group_state(group, name, match_builder):
            """(dom, cnt, cross) for one term group — ABSENT groups compile
            to cheap broadcast zeros/trash instead of the [B,T,P] selector
            matrices and [B,T,P,N] count einsums (the dominant per-cycle
            prepare cost for constraint-sparse batches)."""
            t = group.valid.shape[1]
            if not self._present(batch, name):
                dom = jnp.full((b, t, n), d, jnp.int32)  # all-trash
                cnt_w = n if use_planes else d + 1
                cnt = jnp.zeros((b, t, cnt_w), jnp.int32)
                cross = jnp.zeros((b, t, b), bool)
                return dom, cnt, cross
            dom = self._group_arrays(group, snap, d)
            m = match_builder(
                group, snap.pod_label_keys, snap.pod_label_vals, snap.pod_ns)
            counts = self._counts(m, dom, snap.pod_node, snap.pod_valid, d)
            cnt = (domain_gather(counts, dom).astype(jnp.int32)
                   if use_planes else counts)
            cross = self._match_vs(
                group, batch.label_keys, batch.label_vals, batch.ns, num)
            return dom, cnt, cross, counts

        def plain_match(group, keys, vals, ns):
            return self._match_vs(group, keys, vals, ns, num)

        # req-affinity: affinityCounts count pods matching ALL terms
        has_terms = jnp.any(jnp.asarray(g_aff.valid), axis=1)  # [B]
        if self._present(batch, "req_affinity"):
            dom_aff = self._group_arrays(g_aff, snap, d)
            m_aff = plain_match(g_aff, snap.pod_label_keys,
                                snap.pod_label_vals, snap.pod_ns)
            all_match = (
                jnp.all(m_aff | ~jnp.asarray(g_aff.valid)[:, :, None], axis=1)
                & has_terms[:, None]
            )  # [B, P]
            m_aff_all = jnp.broadcast_to(
                all_match[:, None, :], m_aff.shape
            ) & jnp.asarray(g_aff.valid)[:, :, None]
            aff_counts = self._counts(
                m_aff_all, dom_aff, snap.pod_node, snap.pod_valid, d)
            aff_total = jnp.sum(aff_counts[..., :d], axis=(1, 2))  # [B]
            aff_cnt = (domain_gather(aff_counts, dom_aff).astype(jnp.int32)
                       if use_planes else aff_counts)
            x_aff = self._match_vs(
                g_aff, batch.label_keys, batch.label_vals, batch.ns, num)
            x_aff_all = (
                jnp.all(x_aff | ~jnp.asarray(g_aff.valid)[:, :, None], axis=1)
                & has_terms[:, None]
                & batch.valid[None, :]
            )  # [B, B]
        else:
            t1 = g_aff.valid.shape[1]
            dom_aff = jnp.full((b, t1, n), d, jnp.int32)
            aff_cnt = jnp.zeros(
                (b, t1, n if use_planes else d + 1), jnp.int32)
            aff_total = jnp.zeros((b,), jnp.int32)
            x_aff = jnp.zeros((b, t1, b), bool)
            x_aff_all = jnp.zeros((b, b), bool)

        dom_anti, anti_cnt, x_anti, *_ = group_state(
            g_anti, "req_anti_affinity", plain_match)
        dom_paff, paff_cnt, x_paff, *_ = group_state(
            g_paff, "pref_affinity", plain_match)
        dom_panti, panti_cnt, x_panti, *_ = group_state(
            g_panti, "pref_anti_affinity", plain_match)

        diag = jnp.arange(b)
        self_match_all = x_aff_all[diag, diag]

        if host_aux is None:
            exist_anti_block = jnp.zeros((b, n), bool)
            score_static = jnp.zeros((b, n), jnp.float32)
        else:
            # expand the factored per-signature planes (host_prepare) on
            # device: [G, B] × [G, N] → [B, N]; the dense planes never ride
            # the host→device link
            exist_anti_block = jnp.einsum(
                "gb,gn->bn",
                jnp.asarray(host_aux["blk_match"], jnp.float32),
                jnp.asarray(host_aux["blk_plane"], jnp.float32),
            ) > 0.5
            score_static = jnp.einsum(
                "gb,gn->bn",
                jnp.asarray(host_aux["sc_match"], jnp.float32),
                jnp.asarray(host_aux["sc_plane"], jnp.float32),
            )
        return IPAAux(
            dom_aff=dom_aff, dom_anti=dom_anti, dom_paff=dom_paff, dom_panti=dom_panti,
            aff_cnt=aff_cnt, anti_cnt=anti_cnt,
            paff_cnt=paff_cnt, panti_cnt=panti_cnt,
            aff_total=aff_total, self_match_all=self_match_all,
            exist_anti_block=exist_anti_block,
            score_static=score_static,
            aff_term_cross=x_aff, aff_cross_all=x_aff_all, anti_cross=x_anti,
            paff_cross=x_paff, panti_cross=x_panti,
            block_dyn=jnp.zeros((b, n), bool),
            score_dyn=jnp.zeros((b, n), jnp.float32),
        )

    # --- filter ---------------------------------------------------------------

    def filter(self, batch, snap, dyn, aux: IPAAux):
        if aux is None:
            return jnp.ones((batch.valid.shape[0], snap.num_nodes), bool)
        d = self._d(batch)
        b, n = batch.valid.shape[0], snap.num_nodes
        if self._present(batch, "req_affinity"):
            g_aff_valid = jnp.asarray(batch.req_affinity.valid)  # [B, T1]
            # incoming required affinity (satisfyPodAffinity :338-360)
            cnt = self._read_cnt(snap, aux.aff_cnt, aux.dom_aff)  # [B, T1, N]
            key_ok = aux.dom_aff < d
            keys_all = jnp.all(~g_aff_valid[:, :, None] | key_ok, axis=1)
            pods_exist = jnp.all(~g_aff_valid[:, :, None] | (cnt > 0), axis=1)
            first_pod = (aux.aff_total == 0) & aux.self_match_all  # [B]
            aff_ok = keys_all & (pods_exist | first_pod[:, None])
        else:
            aff_ok = jnp.ones((b, n), bool)

        if self._present(batch, "req_anti_affinity"):
            g_anti_valid = jnp.asarray(batch.req_anti_affinity.valid)
            # incoming required anti-affinity (satisfyPodAntiAffinity :323-335)
            acnt = self._read_cnt(snap, aux.anti_cnt, aux.dom_anti)
            anti_bad = jnp.any(
                g_anti_valid[:, :, None] & (aux.dom_anti < d) & (acnt > 0),
                axis=1,
            )
            aff_ok = aff_ok & ~anti_bad

        return aff_ok & ~aux.exist_anti_block & ~aux.block_dyn

    # --- score ----------------------------------------------------------------

    def score(self, batch, snap, dyn, aux: IPAAux, mask=None):
        if aux is None:
            return jnp.zeros((batch.valid.shape[0], snap.num_nodes))
        d = self._d(batch)
        own = 0.0
        if self._present(batch, "pref_affinity"):
            w_paff = jnp.asarray(batch.pref_affinity.weight)  # [B, T3]
            c_paff = self._read_cnt(snap, aux.paff_cnt, aux.dom_paff)
            own = own + jnp.sum(
                jnp.where(aux.dom_paff < d, c_paff * w_paff[:, :, None], 0.0),
                axis=1)
        if self._present(batch, "pref_anti_affinity"):
            w_panti = jnp.asarray(batch.pref_anti_affinity.weight)
            c_panti = self._read_cnt(snap, aux.panti_cnt, aux.dom_panti)
            own = own - jnp.sum(
                jnp.where(aux.dom_panti < d, c_panti * w_panti[:, :, None], 0.0),
                axis=1)
        return own + aux.score_static + aux.score_dyn

    def normalize(self, scores, mask):
        """100·(s−min)/(max−min) over feasible nodes (scoring.go:255+)."""
        big = jnp.where(mask, scores, -jnp.inf)
        small = jnp.where(mask, scores, jnp.inf)
        mx = jnp.max(big, axis=-1, keepdims=True)
        mn = jnp.min(small, axis=-1, keepdims=True)
        diff = mx - mn
        ok = jnp.isfinite(diff) & (diff > 0)
        return jnp.where(
            ok & mask, MAX_NODE_SCORE * (scores - jnp.where(ok, mn, 0.0))
            / jnp.where(ok, diff, 1.0), 0.0
        )

    # --- row-sliced variants for the fast assignment scan ---------------------

    def filter_row(self, batch, snap, dyn, aux: IPAAux, i):
        if aux is None:
            return jnp.ones(snap.num_nodes, bool)
        d = self._d(batch)
        if self._present(batch, "req_affinity"):
            aff_valid = jnp.asarray(batch.req_affinity.valid)[i]  # [T1]
            cnt = self._read_cnt(snap, aux.aff_cnt[i], aux.dom_aff[i])
            key_ok = aux.dom_aff[i] < d
            keys_all = jnp.all(~aff_valid[:, None] | key_ok, axis=0)  # [N]
            pods_exist = jnp.all(~aff_valid[:, None] | (cnt > 0), axis=0)
            first_pod = (aux.aff_total[i] == 0) & aux.self_match_all[i]
            aff_ok = keys_all & (pods_exist | first_pod)
        else:
            aff_ok = jnp.ones(snap.num_nodes, bool)
        if self._present(batch, "req_anti_affinity"):
            anti_valid = jnp.asarray(batch.req_anti_affinity.valid)[i]
            acnt = self._read_cnt(snap, aux.anti_cnt[i], aux.dom_anti[i])
            anti_bad = jnp.any(
                anti_valid[:, None] & (aux.dom_anti[i] < d) & (acnt > 0),
                axis=0,
            )
            aff_ok = aff_ok & ~anti_bad
        return aff_ok & ~aux.exist_anti_block[i] & ~aux.block_dyn[i]

    def score_row(self, batch, snap, dyn, aux: IPAAux, i, mask_row=None):
        if aux is None:
            return jnp.zeros(snap.num_nodes)
        d = self._d(batch)
        own = 0.0
        if self._present(batch, "pref_affinity"):
            w_paff = jnp.asarray(batch.pref_affinity.weight)[i]  # [T3]
            c_paff = self._read_cnt(snap, aux.paff_cnt[i], aux.dom_paff[i])
            own = own + jnp.sum(
                jnp.where(aux.dom_paff[i] < d, c_paff * w_paff[:, None], 0.0),
                axis=0)
        if self._present(batch, "pref_anti_affinity"):
            w_panti = jnp.asarray(batch.pref_anti_affinity.weight)[i]
            c_panti = self._read_cnt(snap, aux.panti_cnt[i], aux.dom_panti[i])
            own = own - jnp.sum(
                jnp.where(aux.dom_panti[i] < d, c_panti * w_panti[:, None], 0.0),
                axis=0)
        return own + aux.score_static[i] + aux.score_dyn[i]

    # --- in-scan update -------------------------------------------------------

    def update(self, aux: IPAAux, i, node_row, batch, snap):
        if aux is None:
            return None
        """Pod i placed on node_row — the device analog of updateWithPod."""
        d = self._d(batch)
        t1 = aux.dom_aff.shape[1]

        use_planes = self._use_planes(batch, snap)

        def bump(cnt, dom, dom_at, inc):
            # inc[b,t] is already gated on (dom_at < d).  Planes: O(B·T·N)
            # same-domain compare-add (no D factor — the win for hostname
            # topology).  Tables: the original O(B·T·D) point scatter.
            if use_planes:
                same = dom == dom_at[:, :, None]
                return cnt + inc[:, :, None] * same.astype(cnt.dtype)
            return point_scatter_add(cnt, dom_at, inc)

        # 1) pending pods' affinityCounts: j gains where i matches ALL j's terms
        aff_cnt, aff_total = aux.aff_cnt, aux.aff_total
        if self._present(batch, "req_affinity"):
            dom_at_aff = aux.dom_aff[:, :, node_row]  # [B, T1]
            inc_aff = (
                aux.aff_cross_all[:, i][:, None]
                & jnp.asarray(batch.req_affinity.valid)
                & (dom_at_aff < d)
            ).astype(jnp.int32)
            aff_cnt = bump(aux.aff_cnt, aux.dom_aff, dom_at_aff, inc_aff)
            aff_total = aux.aff_total + jnp.sum(inc_aff, axis=1)

        # 2) pending pods' antiAffinityCounts (their own terms vs placed pod i)
        # 3) placed pod i's own req-anti terms block domains for matching pods j
        #    (anti_cross[i] is [T2, B]: term t of pod i vs pending pod j)
        anti_cnt, block_dyn = aux.anti_cnt, aux.block_dyn
        if self._present(batch, "req_anti_affinity"):
            dom_at_anti = aux.dom_anti[:, :, node_row]
            inc_anti = (aux.anti_cross[:, :, i] & (dom_at_anti < d)).astype(jnp.int32)
            anti_cnt = bump(aux.anti_cnt, aux.dom_anti, dom_at_anti, inc_anti)
            same_anti = (aux.dom_anti[i] == aux.dom_anti[i, :, node_row][:, None]) & (
                aux.dom_anti[i] < d
            )  # [T2, N]
            block_dyn = aux.block_dyn | jnp.any(
                aux.anti_cross[i][:, :, None] & same_anti[:, None, :], axis=0
            )  # [B, N]

        # 4) pending pods' own pref planes gain from placed pod i
        paff_cnt, panti_cnt = aux.paff_cnt, aux.panti_cnt
        if self._present(batch, "pref_affinity"):
            dom_at_paff = aux.dom_paff[:, :, node_row]
            paff_cnt = bump(
                aux.paff_cnt, aux.dom_paff, dom_at_paff,
                (aux.paff_cross[:, :, i] & (dom_at_paff < d)).astype(jnp.int32),
            )
        if self._present(batch, "pref_anti_affinity"):
            dom_at_panti = aux.dom_panti[:, :, node_row]
            panti_cnt = bump(
                aux.panti_cnt, aux.dom_panti, dom_at_panti,
                (aux.panti_cross[:, :, i] & (dom_at_panti < d)).astype(jnp.int32),
            )

        # 5) placed pod i's own terms add symmetric score for matching pods j:
        #    req-aff × hardWeight, pref-aff +w, pref-anti −w over i's term domains
        def plane(cross_i, dom_i, w_i):
            # cross_i [T, B], dom_i [T, N], w_i [T] → f32[B, N]
            same = ((dom_i == dom_i[:, node_row][:, None]) & (dom_i < d)).astype(jnp.float32)
            return jnp.einsum("tj,tn->jn", cross_i.astype(jnp.float32) * w_i[:, None], same)

        score_dyn = aux.score_dyn
        if self._present(batch, "req_affinity"):
            w1 = jnp.full((t1,), self.hard_weight, jnp.float32)
            score_dyn = score_dyn + plane(aux.aff_term_cross[i], aux.dom_aff[i], w1)
        if self._present(batch, "pref_affinity"):
            w3 = jnp.asarray(batch.pref_affinity.weight)[i]  # [T3]
            score_dyn = score_dyn + plane(aux.paff_cross[i], aux.dom_paff[i], w3)
        if self._present(batch, "pref_anti_affinity"):
            w4 = jnp.asarray(batch.pref_anti_affinity.weight)[i]
            score_dyn = score_dyn - plane(aux.panti_cross[i], aux.dom_panti[i], w4)

        return aux._replace(
            aff_cnt=aff_cnt, aff_total=aff_total, anti_cnt=anti_cnt,
            block_dyn=block_dyn, paff_cnt=paff_cnt, panti_cnt=panti_cnt,
            score_dyn=score_dyn,
        )

    def update_batch(self, aux: IPAAux, commit, choice, u, batch, snap):
        if aux is None:
            return None
        """All of a round's placements at once (batch_assign): every per-pod
        contribution in `update` is a commutative add/OR, so the whole round
        folds into einsum contractions against the commit one-hot ``u``
        [B, N] (placed pod i → its node)."""
        d = self._d(batch)

        use_planes = self._use_planes(batch, snap)

        def count_inc(cross, dom):
            """cross [B, T, B] (term (b,t) vs pending pod i) → (count-state
            bump in the active representation, table mass [B]) from all
            committed pods: scatter to domains, zero the trash column (the
            serial path never bumps trash), then gather back when carrying
            planes — O(N·D) once per round, not per scan step."""
            contrib = jnp.einsum("bti,in->btn", cross.astype(jnp.float32), u)
            tbl = domain_scatter_add(contrib, dom, d + 1)
            tbl = tbl * (jnp.arange(d + 1) < d)
            inc = domain_gather(tbl, dom) if use_planes else tbl
            return inc, jnp.sum(tbl, axis=(1, 2))

        aff_cnt, aff_total = aux.aff_cnt, aux.aff_total
        if self._present(batch, "req_affinity"):
            g_aff_valid = jnp.asarray(batch.req_affinity.valid)
            aff_cross = (
                aux.aff_cross_all[:, None, :] & g_aff_valid[:, :, None]
            )  # [B, T1, B]
            aff_inc, aff_mass = count_inc(aff_cross, aux.dom_aff)
            # aff_total adds the TABLE mass (one bump per domain), not the
            # plane mass (which would multiply by domain size)
            aff_total = aux.aff_total + aff_mass.astype(jnp.int32)
            aff_cnt = aux.aff_cnt + aff_inc.astype(jnp.int32)
        anti_cnt = aux.anti_cnt
        if self._present(batch, "req_anti_affinity"):
            anti_cnt = aux.anti_cnt + count_inc(
                aux.anti_cross, aux.dom_anti
            )[0].astype(jnp.int32)
        paff_cnt = aux.paff_cnt
        if self._present(batch, "pref_affinity"):
            paff_cnt = aux.paff_cnt + count_inc(
                aux.paff_cross, aux.dom_paff
            )[0].astype(jnp.int32)
        panti_cnt = aux.panti_cnt
        if self._present(batch, "pref_anti_affinity"):
            panti_cnt = aux.panti_cnt + count_inc(
                aux.panti_cross, aux.dom_panti
            )[0].astype(jnp.int32)

        def same_domains(dom):
            """same[i, t, n] — node n shares committed pod i's domain under
            i's term t (zero rows for uncommitted pods since u is zero)."""
            dom_at = jnp.einsum("itn,in->it", dom.astype(jnp.float32), u)
            return (
                (dom.astype(jnp.float32) == dom_at[:, :, None])
                & (dom < d)
                & commit[:, None, None]
            )

        # placed pods' own req-anti terms block matching pods over their domains
        block_dyn = aux.block_dyn
        if self._present(batch, "req_anti_affinity"):
            same_anti = same_domains(aux.dom_anti)
            block_add = (
                jnp.einsum(
                    "itj,itn->jn",
                    aux.anti_cross.astype(jnp.float32),
                    same_anti.astype(jnp.float32),
                )
                > 0.5
            )
            block_dyn = aux.block_dyn | block_add

        # symmetric score: placed pods' own terms credit matching pods
        def plane(cross, dom, w):
            same = same_domains(dom).astype(jnp.float32)
            return jnp.einsum(
                "itj,itn->jn", cross.astype(jnp.float32) * w, same
            )

        score_dyn = aux.score_dyn
        if self._present(batch, "req_affinity"):
            w1 = jnp.full(aux.dom_aff.shape[:2], self.hard_weight, jnp.float32)[
                :, :, None
            ]
            score_dyn = score_dyn + plane(aux.aff_term_cross, aux.dom_aff, w1)
        if self._present(batch, "pref_affinity"):
            w3 = jnp.asarray(batch.pref_affinity.weight)[:, :, None]
            score_dyn = score_dyn + plane(aux.paff_cross, aux.dom_paff, w3)
        if self._present(batch, "pref_anti_affinity"):
            w4 = jnp.asarray(batch.pref_anti_affinity.weight)[:, :, None]
            score_dyn = score_dyn - plane(aux.panti_cross, aux.dom_panti, w4)

        return aux._replace(
            aff_cnt=aff_cnt, aff_total=aff_total, anti_cnt=anti_cnt,
            block_dyn=block_dyn, paff_cnt=paff_cnt, panti_cnt=panti_cnt,
            score_dyn=score_dyn,
        )
