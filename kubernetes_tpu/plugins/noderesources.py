"""NodeResourcesFit + BalancedAllocation as batched tensor programs.

Reference: pkg/scheduler/framework/plugins/noderesources/
  fit.go:255-328      fitsRequest — per-dim ``request ≤ allocatable − requested``
  least_allocated.go:29-57   Σ_r w_r·(cap−req)·100/cap / Σw     (non-zero requests)
  most_allocated.go          Σ_r w_r·req·100/cap / Σw
  requested_to_capacity_ratio.go   piecewise-linear shape over utilization
  balanced_allocation.go:90-140    (1 − std(fractions)) · 100   (true requests)
  resource_allocation.go:49-110    per-resource alloc/req gathering
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..framework.events import ActionType, ClusterEvent, EventResource
from ..framework.interface import MAX_NODE_SCORE, DynamicState, Plugin
from ..state import units

LEAST_ALLOCATED = "LeastAllocated"
MOST_ALLOCATED = "MostAllocated"
REQUESTED_TO_CAPACITY_RATIO = "RequestedToCapacityRatio"


def fit_filter(batch, snap, dyn: DynamicState):
    """bool[B, N] — per-dim fit incl. extended resources (fit.go:255-328).

    A zero request always fits (the reference skips zero-valued resources even on
    overcommitted nodes).
    """
    free = snap.allocatable[None, :, :] - dyn.requested[None, :, :]  # [1, N, R]
    req = batch.request[:, None, :]  # [B, 1, R]
    return jnp.all((req == 0) | (req <= free), axis=-1)  # [B, N]


class FitPlugin(Plugin):
    name = "NodeResourcesFit"
    dynamic = True

    def __init__(
        self,
        strategy: str = LEAST_ALLOCATED,
        resources: Optional[Dict[str, int]] = None,
        num_resource_dims: int = 8,
        extended_index: Optional[Dict[str, int]] = None,
        shape: Optional[Sequence[Tuple[int, int]]] = None,
    ):
        """resources: resource name → weight (default {"cpu": 1, "memory": 1}).
        shape: RequestedToCapacityRatio (utilization%, score) points."""
        self.strategy = strategy
        resources = resources or {"cpu": 1, "memory": 1}
        w = np.zeros(num_resource_dims, dtype=np.float32)
        base = {"cpu": units.DIM_CPU, "memory": units.DIM_MEMORY,
                "ephemeral-storage": units.DIM_EPHEMERAL, "pods": units.DIM_PODS}
        for name, weight in resources.items():
            if name in base:
                w[base[name]] = weight
            elif extended_index and name in extended_index:
                w[extended_index[name]] = weight
        self.weights = w
        if shape is None:
            # defaults for RequestedToCapacityRatio (utilization 0 → score 0,
            # utilization 100 → score 10 — apis/config defaults)
            shape = [(0, 0), (100, 10)]
        self.shape_x = np.asarray([p[0] for p in shape], dtype=np.float32)
        self.shape_y = np.asarray(
            [p[1] * (MAX_NODE_SCORE // 10) for p in shape], dtype=np.float32
        )

    def events_to_register(self):
        return [
            ClusterEvent(EventResource.POD, ActionType.DELETE),
            ClusterEvent(EventResource.NODE, ActionType.ADD | ActionType.UPDATE_NODE_ALLOCATABLE),
        ]

    def filter(self, batch, snap, dyn: DynamicState, aux=None):
        return fit_filter(batch, snap, dyn)

    def score(self, batch, snap, dyn: DynamicState, aux=None, mask=None):
        w = jnp.asarray(self.weights)  # [R]
        alloc = snap.allocatable.astype(jnp.float32)  # [N, R]
        # LeastAllocated/MostAllocated use *non-zero* requests for cpu/memory
        # (resource_allocation.go useRequested=false → NonZeroRequested).
        req = dyn.requested.astype(jnp.float32)
        nz_req = req.at[:, units.DIM_CPU].set(dyn.non_zero[:, 0].astype(jnp.float32))
        nz_req = nz_req.at[:, units.DIM_MEMORY].set(dyn.non_zero[:, 1].astype(jnp.float32))
        pod_req = batch.request.astype(jnp.float32)
        pod_nz = pod_req.at[:, units.DIM_CPU].set(batch.non_zero[:, 0].astype(jnp.float32))
        pod_nz = pod_nz.at[:, units.DIM_MEMORY].set(batch.non_zero[:, 1].astype(jnp.float32))

        # floor mirrors the reference's per-resource int64 division
        # (leastRequestedScore / mostRequestedScore)
        if self.strategy == LEAST_ALLOCATED:
            total = nz_req[None, :, :] + pod_nz[:, None, :]  # [B, N, R]
            per_dim = jnp.where(
                (alloc[None] == 0) | (total > alloc[None]),
                0.0,
                jnp.floor((alloc[None] - total) * MAX_NODE_SCORE / jnp.maximum(alloc[None], 1.0)),
            )
        elif self.strategy == MOST_ALLOCATED:
            total = nz_req[None, :, :] + pod_nz[:, None, :]
            per_dim = jnp.where(
                (alloc[None] == 0) | (total > alloc[None]),
                0.0,
                jnp.floor(total * MAX_NODE_SCORE / jnp.maximum(alloc[None], 1.0)),
            )
        else:  # RequestedToCapacityRatio: piecewise-linear over utilization %
            total = nz_req[None, :, :] + pod_nz[:, None, :]
            util = jnp.where(
                alloc[None] == 0, 100.0,
                jnp.minimum(total / jnp.maximum(alloc[None], 1.0), 1.0) * 100.0,
            )
            per_dim = jnp.interp(util, jnp.asarray(self.shape_x), jnp.asarray(self.shape_y))
        # include a dim iff weighted and allocatable non-zero; extended dims also
        # require the pod to request them (resource_allocation.go:84-95)
        included = (w[None, None, :] > 0) & (alloc[None] > 0)
        is_ext = jnp.arange(alloc.shape[-1]) >= units.NUM_BASE_DIMS
        included &= ~is_ext[None, None, :] | (pod_req[:, None, :] > 0)
        wsum = jnp.sum(jnp.where(included, w[None, None, :], 0.0), axis=-1)  # [B, N]
        total_score = jnp.sum(jnp.where(included, per_dim * w[None, None, :], 0.0), axis=-1)
        return jnp.where(
            wsum == 0, 0.0, jnp.floor(total_score / jnp.maximum(wsum, 1.0))
        )

    def normalize(self, scores, mask):
        return scores  # already 0..100

    # --- row-sliced variants for the fast assignment scan --------------------

    def filter_row(self, batch, snap, dyn, aux, i):
        import jax

        free = snap.allocatable - dyn.requested  # [N, R]
        req = jax.lax.dynamic_slice_in_dim(batch.request, i, 1, 0)  # [1, R]
        return jnp.all((req == 0) | (req <= free), axis=-1)  # [N]

    def score_row(self, batch, snap, dyn, aux, i, mask_row=None):
        import jax
        from types import SimpleNamespace

        sub = SimpleNamespace(
            request=jax.lax.dynamic_slice_in_dim(batch.request, i, 1, 0),
            non_zero=jax.lax.dynamic_slice_in_dim(batch.non_zero, i, 1, 0),
        )
        return self.score(sub, snap, dyn)[0]


class BalancedAllocationPlugin(Plugin):
    name = "NodeResourcesBalancedAllocation"
    dynamic = True

    def __init__(self, resources: Optional[Dict[str, int]] = None,
                 num_resource_dims: int = 8,
                 extended_index: Optional[Dict[str, int]] = None):
        resources = resources or {"cpu": 1, "memory": 1}
        sel = np.zeros(num_resource_dims, dtype=bool)
        base = {"cpu": units.DIM_CPU, "memory": units.DIM_MEMORY,
                "ephemeral-storage": units.DIM_EPHEMERAL, "pods": units.DIM_PODS}
        for name in resources:
            if name in base:
                sel[base[name]] = True
            elif extended_index and name in extended_index:
                sel[extended_index[name]] = True
        self.sel = sel

    def score(self, batch, snap, dyn: DynamicState, aux=None, mask=None):
        """(1 − std(utilization fractions)) · 100 (balanced_allocation.go:90-140;
        uses TRUE requests, useRequested=true)."""
        sel = jnp.asarray(self.sel)
        alloc = snap.allocatable.astype(jnp.float32)  # [N, R]
        total = (dyn.requested[None, :, :] + batch.request[:, None, :]).astype(jnp.float32)
        # include dims: selected, alloc > 0; extended dims only when pod requests
        is_ext = jnp.arange(alloc.shape[-1]) >= units.NUM_BASE_DIMS
        included = sel[None, None, :] & (alloc[None] > 0)
        included &= ~is_ext[None, None, :] | (batch.request[:, None, :] > 0)
        frac = jnp.minimum(total / jnp.maximum(alloc[None], 1.0), 1.0)  # [B, N, R]
        n_inc = jnp.sum(included, axis=-1)  # [B, N]
        mean = jnp.sum(jnp.where(included, frac, 0.0), axis=-1) / jnp.maximum(n_inc, 1)
        # the reference's 2-resource |f1−f2|/2 fast path equals this std formula
        var = jnp.sum(jnp.where(included, (frac - mean[..., None]) ** 2, 0.0), axis=-1)
        std = jnp.sqrt(var / jnp.maximum(n_inc, 1))
        score = (1.0 - std) * MAX_NODE_SCORE
        return jnp.where(n_inc == 0, 0.0, score)

    def normalize(self, scores, mask):
        return scores

    def score_row(self, batch, snap, dyn, aux, i, mask_row=None):
        import jax
        from types import SimpleNamespace

        sub = SimpleNamespace(
            request=jax.lax.dynamic_slice_in_dim(batch.request, i, 1, 0),
        )
        return self.score(sub, snap, dyn)[0]
