"""Vectorized default plugin set.

Reference: pkg/scheduler/framework/plugins/ (registry.go:47-81).  Each plugin here
is a batched tensor program: Filter returns ``bool[B, N]``, Score ``float32[B, N]``,
computed for the whole PodBatch × DeviceSnapshot plane in one fused XLA program.
"""

from .noderesources import FitPlugin, BalancedAllocationPlugin  # noqa: F401
from .tainttoleration import TaintTolerationPlugin  # noqa: F401
from .nodeaffinity import NodeAffinityPlugin  # noqa: F401
from .trivial import (  # noqa: F401
    NodeNamePlugin,
    NodePortsPlugin,
    NodeUnschedulablePlugin,
    ImageLocalityPlugin,
)
from .podtopologyspread import PodTopologySpreadPlugin  # noqa: F401
from .interpodaffinity import InterPodAffinityPlugin  # noqa: F401
from .selectorspread import SelectorSpreadPlugin  # noqa: F401
from .volumes import (  # noqa: F401
    NodeVolumeLimitsPlugin,
    VolumeBindingPlugin,
    VolumeRestrictionsPlugin,
    VolumeZonePlugin,
)

DEFAULT_PLUGIN_WEIGHTS = {
    # apis/config/v1beta3/default_plugins.go:32-51
    "TaintToleration": 3,
    "NodeAffinity": 2,
    "PodTopologySpread": 2,
    "InterPodAffinity": 2,
    "NodeResourcesFit": 1,
    "NodeResourcesBalancedAllocation": 1,
    "ImageLocality": 1,
}
