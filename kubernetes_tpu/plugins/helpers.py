"""Shared device helpers for plugin tensor programs."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.interface import MAX_NODE_SCORE
from ..state.selectors import eval_label_selector, eval_requirements


def label_selector_matrix(cs, node_keys, node_vals, numeric):
    """CompiledLabelSelectors (batch B) × node label sets [N, L] → bool[B, N]."""
    b = cs.req_key.shape[0]

    def one_sel(i):
        return jax.vmap(
            lambda keys, vals: eval_label_selector(cs, i, keys, vals, numeric)
        )(node_keys, node_vals)

    return jax.vmap(one_sel)(jnp.arange(b))


def node_selector_matrix(cns, node_keys, node_vals, numeric):
    """CompiledNodeSelectors (batch B) × node label sets [N, L] → bool[B, N].

    OR over valid terms, AND over each term's requirements; match_all rows → True.
    """
    rk = jnp.asarray(cns.req_key)      # [B, T, S]
    ro = jnp.asarray(cns.req_op)
    rv = jnp.asarray(cns.req_vals)     # [B, T, S, V]
    rn = jnp.asarray(cns.req_num)
    tv = jnp.asarray(cns.term_valid)   # [B, T]
    ma = jnp.asarray(cns.match_all)    # [B]

    def one_node(keys, vals):
        per_term = jax.vmap(
            jax.vmap(lambda k, o, v, n: eval_requirements(k, o, v, n, keys, vals, numeric))
        )(rk, ro, rv, rn)  # [B, T]
        return ma | jnp.any(per_term & tv, axis=-1)  # [B]

    return jax.vmap(one_node, out_axes=1)(node_keys, node_vals)  # [B, N]


def weighted_term_matrix(req_key, req_op, req_vals, req_num, term_valid, weight,
                         node_keys, node_vals, numeric):
    """Preferred-term arrays [B, T, ...] × nodes [N, L] → f32[B, N] summed weights
    of matching terms (nodeaffinity/node_affinity.go Score)."""

    def one_node(keys, vals):
        match = jax.vmap(
            jax.vmap(lambda k, o, v, n: eval_requirements(k, o, v, n, keys, vals, numeric))
        )(jnp.asarray(req_key), jnp.asarray(req_op),
          jnp.asarray(req_vals), jnp.asarray(req_num))  # [B, T]
        return jnp.sum(jnp.where(match & term_valid, weight, 0.0), axis=-1)  # [B]

    return jax.vmap(one_node, out_axes=1)(node_keys, node_vals)  # [B, N]


def flat_selector_matrix(cs, b, t, keys, vals, numeric):
    """Flattened CompiledLabelSelectors (batch b·t, row-major) × label sets
    [P, L] → bool[b, t, P]."""

    def one_sel(fi):
        return jax.vmap(
            lambda k, v: eval_label_selector(cs, fi, k, v, numeric)
        )(keys, vals)

    return jax.vmap(one_sel)(jnp.arange(b * t)).reshape(b, t, -1)


def default_normalize(scores, mask, reverse: bool = False):
    """framework.DefaultNormalizeScore: scale per-pod row to [0, MaxNodeScore] by
    the row max over feasible nodes; reverse flips (max - score)."""
    neg = jnp.where(mask, scores, -jnp.inf)
    row_max = jnp.max(neg, axis=-1, keepdims=True)  # [B, 1]
    row_max = jnp.where(jnp.isfinite(row_max), row_max, 0.0)
    zero_max = row_max == 0
    # floor before the reverse: the reference computes score*max/maxCount with
    # int64 division, then maxPriority − score
    scaled = jnp.floor(scores * MAX_NODE_SCORE / jnp.where(zero_max, 1.0, row_max))
    scaled = jnp.where(
        zero_max, jnp.where(reverse, float(MAX_NODE_SCORE), 0.0),
        jnp.where(reverse, MAX_NODE_SCORE - scaled, scaled),
    )
    return scaled
