"""Shared device helpers for plugin tensor programs.

All selector-vs-object matrices go through the batched matrix evaluators in
state/selectors.py (unique-selector dedup + broadcast compares, no per-element
gathers); the vmap-of-scalar-eval forms they replace lowered to serial
minor-axis gathers on TPU and dominated prepare at 5k nodes.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.interface import MAX_NODE_SCORE
from ..state.selectors import (
    label_match_matrix,
    node_match_matrix,
    requirements_match_matrix,
)


def label_selector_matrix(cs, node_keys, node_vals, numeric, vals_num=None):
    """CompiledLabelSelectors (batch B) × label sets [N, L] → bool[B, N]."""
    return label_match_matrix(cs, node_keys, node_vals, vals_num=vals_num, numeric=numeric)


def node_selector_matrix(cns, node_keys, node_vals, numeric, vals_num=None):
    """CompiledNodeSelectors (batch B) × node label sets [N, L] → bool[B, N].

    OR over valid terms, AND over each term's requirements; match_all rows → True.
    """
    return node_match_matrix(cns, node_keys, node_vals, vals_num=vals_num, numeric=numeric)


def weighted_term_matrix(req_key, req_op, req_vals, req_num, term_valid, weight,
                         node_keys, node_vals, numeric, vals_num=None):
    """Preferred-term arrays [B, T, ...] × nodes [N, L] → f32[B, N] summed weights
    of matching terms (nodeaffinity/node_affinity.go Score)."""
    b, t = np.shape(req_key)[0], np.shape(req_key)[1]
    s = np.shape(req_key)[2]
    match = requirements_match_matrix(
        jnp.reshape(jnp.asarray(req_key), (b * t, s)),
        jnp.reshape(jnp.asarray(req_op), (b * t, s)),
        jnp.reshape(jnp.asarray(req_vals), (b * t, s, -1)),
        jnp.reshape(jnp.asarray(req_num), (b * t, s)),
        node_keys, node_vals, vals_num=vals_num, numeric=numeric,
    ).reshape(b, t, -1)  # [B, T, N]
    w = jnp.asarray(weight)[:, :, None]
    return jnp.sum(jnp.where(match & jnp.asarray(term_valid)[:, :, None], w, 0.0), axis=1)


def flat_selector_matrix(cs, b, t, keys, vals, numeric):
    """Flattened CompiledLabelSelectors (batch b·t, row-major) × label sets
    [P, L] → bool[b, t, P]."""
    return label_match_matrix(cs, keys, vals, numeric=numeric).reshape(b, t, -1)


def default_normalize(scores, mask, reverse: bool = False):
    """framework.DefaultNormalizeScore: scale per-pod row to [0, MaxNodeScore] by
    the row max over feasible nodes; reverse flips (max - score)."""
    neg = jnp.where(mask, scores, -jnp.inf)
    row_max = jnp.max(neg, axis=-1, keepdims=True)  # [B, 1]
    row_max = jnp.where(jnp.isfinite(row_max), row_max, 0.0)
    zero_max = row_max == 0
    # floor before the reverse: the reference computes score*max/maxCount with
    # int64 division, then maxPriority − score
    scaled = jnp.floor(scores * MAX_NODE_SCORE / jnp.where(zero_max, 1.0, row_max))
    scaled = jnp.where(
        zero_max, jnp.where(reverse, float(MAX_NODE_SCORE), 0.0),
        jnp.where(reverse, MAX_NODE_SCORE - scaled, scaled),
    )
    return scaled
