"""NodeName, NodePorts, NodeUnschedulable, ImageLocality — small batched plugins.

Reference: pkg/scheduler/framework/plugins/{nodename,nodeports,nodeunschedulable,
imagelocality}/.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.events import ActionType, ClusterEvent, EventResource
from ..framework.interface import MAX_NODE_SCORE, Plugin
from ..framework.podbatch import TOL_OP_EXISTS
from ..state.dictionary import ID_UNSCHEDULABLE_TAINT, MISSING

_MB = 1024 * 1024
MIN_THRESHOLD = 23 * _MB  # imagelocality/image_locality.go:34
MAX_CONTAINER_THRESHOLD = 1000 * _MB  # :35


class NodeNamePlugin(Plugin):
    """pod.Spec.NodeName == node.Name (nodename/node_name.go)."""

    name = "NodeName"

    def filter(self, batch, snap, dyn, aux=None):
        unset = batch.node_name_id == MISSING  # [B]
        return unset[:, None] | (batch.node_name_id[:, None] == snap.node_name_ids[None, :])


class NodePortsPlugin(Plugin):
    """hostPort conflicts vs NodeInfo.UsedPorts (nodeports/node_ports.go).

    Exact HostPortInfo.CheckConflict semantics (framework/types.go): entries
    with equal (proto<<16 | port) codes conflict iff the hostIPs are equal or
    either side is 0.0.0.0 (ID_WILDCARD_IP) — pods differing only by concrete
    hostIP coexist, matching the host oracle's host_ports_conflict.
    """

    name = "NodePorts"

    def events_to_register(self):
        return [ClusterEvent(EventResource.POD, ActionType.DELETE)]

    def filter(self, batch, snap, dyn, aux=None):
        from ..state.dictionary import ID_WILDCARD_IP

        pod_ports = batch.ports[:, None, :, None]  # [B, 1, PP, 1]
        node_ports = snap.ports[None, :, None, :]  # [1, N, 1, NP]
        pod_ip = batch.ports_ip[:, None, :, None]
        node_ip = snap.ports_ip[None, :, None, :]
        ip_clash = (
            (pod_ip == node_ip)
            | (pod_ip == ID_WILDCARD_IP)
            | (node_ip == ID_WILDCARD_IP)
        )
        conflict = jnp.any(
            (pod_ports == node_ports) & (pod_ports != MISSING) & ip_clash,
            axis=(-2, -1),
        )
        return ~conflict


class NodeUnschedulablePlugin(Plugin):
    """node.Spec.Unschedulable, escapable by tolerating the
    node.kubernetes.io/unschedulable:NoSchedule taint
    (nodeunschedulable/node_unschedulable.go)."""

    name = "NodeUnschedulable"

    def events_to_register(self):
        return [ClusterEvent(EventResource.NODE, ActionType.ADD | ActionType.UPDATE_NODE_TAINT)]

    def filter(self, batch, snap, dyn, aux=None):
        # tolerates synthetic taint {key: unschedulable, value: "", effect NoSchedule}
        key_ok = (batch.tol_key == MISSING) | (batch.tol_key == ID_UNSCHEDULABLE_TAINT)
        effect_ok = (batch.tol_effect == -1) | (batch.tol_effect == 0)
        value_ok = (batch.tol_op == TOL_OP_EXISTS)  # Equal would need value ""
        tolerates = jnp.any(batch.tol_valid & key_ok & effect_ok & value_ok, axis=-1)  # [B]
        return ~snap.unschedulable[None, :] | tolerates[:, None]


class ImageLocalityPlugin(Plugin):
    """Scaled sum of present-image sizes × spread ratio
    (imagelocality/image_locality.go:84-117)."""

    name = "ImageLocality"

    def score(self, batch, snap, dyn, aux=None, mask=None):
        # per-image-id spread counts and sizes via scatter-add over dictionary ids
        # (replaces the reference's per-node ImageStates map walk)
        img = snap.image_ids  # [N, I]
        valid_img = (img != MISSING) & snap.node_valid[:, None]
        num_ids = snap.numeric.shape[0]
        flat = jnp.clip(img, 0, num_ids - 1).reshape(-1)
        w = valid_img.reshape(-1).astype(jnp.float32)
        counts_by_id = jnp.zeros(num_ids, jnp.float32).at[flat].add(w)
        size_by_id = jnp.zeros(num_ids, jnp.float32).at[flat].max(
            jnp.where(valid_img, snap.image_sizes, 0.0).reshape(-1)
        )
        n_nodes = jnp.maximum(jnp.sum(snap.node_valid), 1)
        scaled_by_id = size_by_id * (counts_by_id / n_nodes)  # spread-scaled size
        pod_img = jnp.clip(batch.image_ids, 0, num_ids - 1)  # [B, CI]
        pod_scaled = jnp.where(batch.image_ids != MISSING, scaled_by_id[pod_img], 0.0)
        present = jnp.any(
            (batch.image_ids[:, None, :, None] == img[None, :, None, :])
            & valid_img[None, :, None, :],
            axis=-1,
        )  # [B, N, CI]
        sum_scores = jnp.sum(pod_scaled[:, None, :] * present, axis=-1)  # [B, N]
        num_containers = jnp.sum(batch.image_ids != MISSING, axis=-1)  # [B]
        max_threshold = (MAX_CONTAINER_THRESHOLD * jnp.maximum(num_containers, 1)).astype(jnp.float32)
        clamped = jnp.clip(sum_scores, MIN_THRESHOLD, max_threshold[:, None])
        return (
            MAX_NODE_SCORE
            * (clamped - MIN_THRESHOLD)
            / (max_threshold[:, None] - MIN_THRESHOLD)
        )

    def normalize(self, scores, mask):
        return scores
