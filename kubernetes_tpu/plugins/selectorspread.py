"""SelectorSpread (non-default in v1.24): spread pods of the same
Service/ReplicaSet/StatefulSet across nodes and zones.

Reference: pkg/scheduler/framework/plugins/selectorspread/selector_spread.go —
PreScore merges the selectors of every Service/RC/RS/SS owning the pod
(helper.DefaultSelector: requirements AND together); Score = count of matching
pods on the node; NormalizeScore inverts against the max and blends a zone
score with weight 2/3 when zones exist.

Counts are host-computed per batch over the snapshot (the listers are API-object
lookups); the ``[B, N]`` planes ride to device as aux and the final invert/blend
is row-local at scan time (mask-dependent maxima).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..api import objects as v1
from ..api.labels import match_label_selector
from ..framework.events import ActionType, ClusterEvent, EventResource
from ..framework.interface import MAX_NODE_SCORE, Plugin

ZONE_WEIGHTING = 2.0 / 3.0  # selector_spread.go zoneWeighting


class SelectorSpreadPlugin(Plugin):
    name = "SelectorSpread"
    dynamic = True  # mask-dependent normalize at scan time (no carried state)

    def __init__(self, store=None):
        self.store = store

    def events_to_register(self):
        return [
            ClusterEvent(EventResource.POD, ActionType.ALL),
            ClusterEvent(EventResource.SERVICE, ActionType.ALL),
        ]

    def _selectors_for(self, pod: v1.Pod):
        """helper.DefaultSelector: label selectors of every owning object."""
        sels = []
        if self.store is None:
            return sels
        for svc in self.store.list("Service")[0]:
            if svc.metadata.namespace != pod.namespace or not svc.selector:
                continue
            if all(pod.metadata.labels.get(k) == val for k, val in svc.selector.items()):
                sels.append(
                    v1.LabelSelector(match_labels=dict(svc.selector))
                )
        for rs in self.store.list("ReplicaSet")[0]:
            if rs.metadata.namespace != pod.namespace or rs.selector is None:
                continue
            if match_label_selector(rs.selector, pod.metadata.labels):
                sels.append(rs.selector)
        return sels

    def host_prepare(self, batch, snapshot, encoder, namespace_labels=None):
        b, n = batch.size, encoder._n
        counts = np.zeros((b, n), dtype=np.float32)
        zone_counts = np.zeros((b, n), dtype=np.float32)
        has_zone = np.zeros(n, dtype=bool)
        zone_of = {}
        for info in snapshot.node_info_list:
            r = encoder.node_rows.get(info.node_name)
            if r is None:
                continue
            z = info.node.metadata.labels.get("topology.kubernetes.io/zone") or \
                info.node.metadata.labels.get("failure-domain.beta.kubernetes.io/zone")
            zone_of[r] = z
            has_zone[r] = z is not None
        for i, pod in enumerate(batch.pods):
            sels = self._selectors_for(pod)
            if not sels:
                continue
            for info in snapshot.node_info_list:
                r = encoder.node_rows.get(info.node_name)
                if r is None:
                    continue
                c = 0
                for pi in info.pods:
                    p = pi.pod
                    if p.namespace != pod.namespace or p.metadata.deletion_timestamp:
                        continue
                    if all(match_label_selector(s, p.metadata.labels) for s in sels):
                        c += 1
                counts[i, r] = c
            by_zone = {}
            for r, z in zone_of.items():
                if z is not None:
                    by_zone[z] = by_zone.get(z, 0.0) + counts[i, r]
            for r, z in zone_of.items():
                if z is not None:
                    zone_counts[i, r] = by_zone[z]
        return {"counts": counts, "zone_counts": zone_counts, "has_zone": has_zone}

    def prepare(self, batch, snap, dyn, host_aux=None):
        import jax.numpy as jnp

        if host_aux is None:
            z = jnp.zeros((batch.valid.shape[0], snap.num_nodes), jnp.float32)
            return {"counts": z, "zone_counts": z,
                    "has_zone": jnp.zeros(snap.num_nodes, bool)}
        return {k: jnp.asarray(v) for k, v in host_aux.items()}

    def score_row(self, batch, snap, dyn, aux, i, mask_row=None):
        import jax.numpy as jnp

        counts = aux["counts"][i]
        zcounts = aux["zone_counts"][i]
        has_zone = aux["has_zone"]
        if mask_row is None:
            mask_row = jnp.ones(counts.shape, bool)
        max_c = jnp.max(jnp.where(mask_row, counts, 0.0))
        max_z = jnp.max(jnp.where(mask_row, zcounts, 0.0))
        node_score = jnp.where(
            max_c > 0, (max_c - counts) * MAX_NODE_SCORE / jnp.maximum(max_c, 1.0),
            float(MAX_NODE_SCORE),
        )
        zone_score = jnp.where(
            max_z > 0, (max_z - zcounts) * MAX_NODE_SCORE / jnp.maximum(max_z, 1.0),
            float(MAX_NODE_SCORE),
        )
        blended = jnp.where(
            has_zone & (max_z > 0),
            (1.0 - ZONE_WEIGHTING) * node_score + ZONE_WEIGHTING * zone_score,
            node_score,
        )
        return jnp.floor(blended)

    def score(self, batch, snap, dyn, aux=None, mask=None):
        """Batched variant for the dense/compute path."""
        import jax

        b = batch.valid.shape[0]
        if mask is None:
            import jax.numpy as jnp

            mask = jnp.ones((b, snap.num_nodes), bool)
        return jax.vmap(
            lambda i, m: self.score_row(batch, snap, dyn, aux, i, m)
        )(jax.numpy.arange(b), mask)

    def normalize(self, scores, mask):
        return scores
