"""PodTopologySpread as a batched tensor program with in-scan updates.

Reference: pkg/scheduler/framework/plugins/podtopologyspread/
  filtering.go:256-289 — PreFilter counts matching pods per (topologyKey, value)
      over nodes passing the pod's nodeSelector/affinity that carry ALL hard keys
  filtering.go:343-358 — Filter: matchNum + selfMatch − globalMin > maxSkew;
      node missing a key → UnschedulableAndUnresolvable
  scoring.go:108-175  — PreScore counts per pair over affinity-eligible nodes,
      restricted to pairs present among feasible nodes
  scoring.go:180-213  — Score: Σ_c cnt·log(topoSize+2) + (maxSkew−1)
  scoring.go:216+     — NormalizeScore: 100·(max+min−s)/max, ignored nodes → 0

Device design: topology keys are encoder slots; label values under a key are
compact domain indices (state/encoding.py topo registry).  Counts live in dense
``[B, C, D+1]`` tables (last slot = trash for MISSING), built by one
pods×nodes matmul + scatter-add, and updated in O(B·C) inside the greedy
assignment scan when a pending pod is placed (the device analog of ``assume``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from ..framework.events import ActionType, ClusterEvent, EventResource
from ..framework.interface import MAX_NODE_SCORE, Plugin
from ..framework.podbatch import WHEN_DO_NOT_SCHEDULE, WHEN_SCHEDULE_ANYWAY
from ..ops import domain_any, domain_gather, domain_scatter_add, point_scatter_add
from ..state.dictionary import MISSING
from ..state.selectors import label_match_matrix
from .helpers import label_selector_matrix, node_selector_matrix

# plain Python int, NOT a module-level device array: a concrete jax.Array
# captured as a jit closure constant permanently degrades every subsequent
# host sync to ~100 ms through the axon TPU tunnel (measured; see
# tests/test_ops.py microbench + memory note axon-closure-constant-poison)
BIG = 2**30


class TSAux(NamedTuple):
    hard_valid: jnp.ndarray  # bool[B, C]
    soft_valid: jnp.ndarray  # bool[B, C]
    max_skew: jnp.ndarray  # i32[B, C]
    min_domains: jnp.ndarray  # i32[B, C]
    self_match: jnp.ndarray  # bool[B, C]
    dom_val: jnp.ndarray  # i32[B, C, N] (domain index of node under c's key; D=trash)
    has_key: jnp.ndarray  # bool[B, C, N]
    counted_hard: jnp.ndarray  # bool[B, N] nodes counted for hard constraints
    counted_soft: jnp.ndarray  # bool[B, N]
    hard_counts: jnp.ndarray  # i32[B, C, D+1]
    soft_counts: jnp.ndarray  # i32[B, C, D+1]
    hard_present: jnp.ndarray  # bool[B, C, D+1] domains with ≥1 counted node
    match_pending: jnp.ndarray  # bool[B, C, B] — selector (b,c) matches pending pod j


class PodTopologySpreadPlugin(Plugin):
    name = "PodTopologySpread"
    dynamic = True

    def __init__(self, domain_cap: int = 256, enable_min_domains: bool = True):
        self.domain_cap = domain_cap  # static D; runtime refreshes on growth
        self.enable_min_domains = enable_min_domains

    def events_to_register(self):
        return [
            ClusterEvent(EventResource.POD, ActionType.ALL),
            ClusterEvent(EventResource.NODE, ActionType.ADD | ActionType.UPDATE_NODE_LABEL),
        ]

    # --- prepare (PreFilter + the static part of PreScore) -------------------

    def prepare(self, batch, snap, dyn, host_aux=None):
        # STATIC skip: a batch with no spread constraints compiles without
        # any of this plugin's O(N·D) domain programs (batch.has_spread is
        # trace-time constant pytree aux)
        if not getattr(batch, "has_spread", True):
            return None
        # batch-local domain axis (PodBatch.tsc_domain_bucket): the GLOBAL
        # domain_cap covers every registered topo key — a hostname key at 5k
        # nodes would make a zone-spread batch's every gather contract a
        # [C, N, 8192] one-hot for 3 live domains (measured 2.4s/batch in
        # the TopologySpreading suite's scan)
        d = getattr(batch, "tsc_domain_bucket", None) or self.domain_cap
        b, c_cap = batch.tsc_valid.shape
        n = snap.num_nodes

        hard_valid = batch.tsc_valid & (batch.tsc_when == WHEN_DO_NOT_SCHEDULE)
        soft_valid = batch.tsc_valid & (batch.tsc_when == WHEN_SCHEDULE_ANYWAY)

        key = jnp.clip(batch.tsc_key, 0, snap.node_topo.shape[1] - 1)  # [B, C]
        dom_val = snap.node_topo[:, key]  # [N, B, C] → transpose
        dom_val = jnp.transpose(dom_val, (1, 2, 0))  # [B, C, N]
        has_key = dom_val != MISSING
        dom_val = jnp.where(has_key, jnp.clip(dom_val, 0, d - 1), d)  # trash slot D

        # nodes eligible for counting: pass pod's nodeSelector + required affinity
        sel_ok = label_selector_matrix(
            batch.node_selector, snap.node_label_keys, snap.node_label_vals,
            snap.numeric, vals_num=snap.node_label_num,
        )
        aff_ok = node_selector_matrix(
            batch.node_affinity, snap.node_label_keys, snap.node_label_vals,
            snap.numeric, vals_num=snap.node_label_num,
        )
        affinity_ok = sel_ok & aff_ok & snap.node_valid[None, :]  # [B, N]
        has_all_hard = jnp.all(~hard_valid[:, :, None] | has_key, axis=1)  # [B, N]
        has_all_soft = jnp.all(~soft_valid[:, :, None] | has_key, axis=1)
        counted_hard = affinity_ok & has_all_hard
        counted_soft = affinity_ok & has_all_soft

        # selector (b,c) vs scheduled pods (same namespace only) → [B, C, P]
        match_sched = self._selector_vs_pods(
            batch, snap.pod_label_keys, snap.pod_label_vals, snap.pod_ns, snap.numeric
        )
        match_sched = match_sched & snap.pod_valid[None, None, :]
        # per-node match count via one matmul [B*C, P] × [P, N]
        pod_node = jnp.clip(snap.pod_node, 0, n - 1)
        onehot = (
            (pod_node[:, None] == jnp.arange(n)[None, :]) & (snap.pod_node >= 0)[:, None]
        ).astype(jnp.float32)  # [P, N]
        count_node = (
            match_sched.reshape(b * c_cap, -1).astype(jnp.float32) @ onehot
        ).reshape(b, c_cap, n).astype(jnp.int32)  # [B, C, N]

        def scatter(count_mask, node_mask):
            vals = jnp.where(node_mask[:, None, :], count_mask, 0)  # [B, C, N]
            return domain_scatter_add(vals, dom_val, d + 1).astype(jnp.int32)

        hard_counts = scatter(count_node, counted_hard)
        soft_counts = scatter(count_node, counted_soft)
        hard_present = domain_any(
            counted_hard[:, None, :] & (dom_val < d), dom_val, d + 1
        )

        # constraint selectors vs PENDING pods (same-namespace check applies both
        # to in-scan counting and to the diagonal selfMatchNum, where ns is equal)
        self_match = self._selector_vs_pods(
            batch, batch.label_keys, batch.label_vals, batch.ns, snap.numeric,
        )  # [B, C, B] — diagonal is selfMatch
        diag = jnp.arange(b)
        match_pending = self_match & batch.valid[None, None, :]
        self_diag = match_pending[diag, :, diag]  # [B, C]

        return TSAux(
            hard_valid=hard_valid, soft_valid=soft_valid,
            max_skew=batch.tsc_max_skew, min_domains=batch.tsc_min_domains,
            self_match=self_diag, dom_val=dom_val, has_key=has_key,
            counted_hard=counted_hard, counted_soft=counted_soft,
            hard_counts=hard_counts, soft_counts=soft_counts,
            hard_present=hard_present, match_pending=match_pending,
        )

    def _selector_vs_pods(self, batch, pl_keys, pl_vals, p_ns, numeric, same_ns=True):
        """Constraint selectors [B, C] vs pod label sets [P, L] → bool[B, C, P]."""
        b, c_cap = batch.tsc_valid.shape
        m = label_match_matrix(
            batch.tsc_selectors, pl_keys, pl_vals, numeric=numeric
        ).reshape(b, c_cap, -1)  # [B, C, P] (evaluated at U unique selectors)
        if same_ns:
            m = m & (batch.ns[:, None, None] == p_ns[None, None, :])
        return m

    # --- filter ---------------------------------------------------------------

    def filter(self, batch, snap, dyn, aux: TSAux = None):
        if aux is None:
            return jnp.ones((batch.valid.shape[0], snap.num_nodes), bool)
        # global min over present domains (criticalPaths); empty → +BIG (pass)
        min_match = jnp.min(
            jnp.where(aux.hard_present, aux.hard_counts, BIG), axis=-1
        )  # [B, C]
        if self.enable_min_domains:
            num_domains = jnp.sum(aux.hard_present, axis=-1)  # [B, C]
            min_match = jnp.where(
                (aux.min_domains > 0) & (num_domains < aux.min_domains), 0, min_match
            )
        match_num = domain_gather(aux.hard_counts, aux.dom_val).astype(jnp.int32)  # [B, C, N]
        skew = match_num + aux.self_match[:, :, None].astype(jnp.int32) - min_match[:, :, None]
        ok_c = skew <= aux.max_skew[:, :, None]
        ok = jnp.all(~aux.hard_valid[:, :, None] | (ok_c & aux.has_key), axis=1)
        return ok  # [B, N]

    # --- score ----------------------------------------------------------------

    def score(self, batch, snap, dyn, aux: TSAux, mask=None):
        """Raw score; NaN marks ignored nodes (handled in normalize)."""
        if aux is None:
            return jnp.zeros((batch.valid.shape[0], snap.num_nodes))
        d = aux.soft_counts.shape[-1] - 1
        # pairs present among feasible (mask) non-ignored nodes restrict counting
        if mask is None:
            mask = jnp.ones(aux.counted_soft.shape, bool)
        ignored = ~jnp.all(~aux.soft_valid[:, :, None] | aux.has_key, axis=1)  # [B,N]
        scored = mask & ~ignored  # [B, N]
        b, c_cap, _ = aux.dom_val.shape
        soft_present = domain_any(
            scored[:, None, :] & (aux.dom_val < d), aux.dom_val, d + 1
        )
        topo_size = jnp.sum(soft_present[..., :d], axis=-1)  # [B, C]
        tp_weight = jnp.log(topo_size.astype(jnp.float32) + 2.0)
        counts = domain_gather(aux.soft_counts, aux.dom_val)  # [B,C,N]
        in_present = domain_gather(soft_present, aux.dom_val) > 0.5
        per_c = (
            counts.astype(jnp.float32) * tp_weight[:, :, None]
            + (aux.max_skew[:, :, None].astype(jnp.float32) - 1.0)
        )
        raw = jnp.round(jnp.sum(
            jnp.where(aux.soft_valid[:, :, None] & aux.has_key & in_present, per_c, 0.0),
            axis=1,
        ))  # [B, N] — int64(math.Round(score)) parity (scoring.go:213)
        has_soft = jnp.any(aux.soft_valid, axis=1)  # [B]
        return jnp.where(
            has_soft[:, None] & ~scored, jnp.nan, jnp.where(has_soft[:, None], raw, 0.0)
        )

    def normalize(self, scores, mask):
        """100·(max+min−s)/max over scored nodes; NaN (ignored) → 0
        (scoring.go NormalizeScore)."""
        valid = mask & ~jnp.isnan(scores)
        big = jnp.where(valid, scores, -jnp.inf)
        small = jnp.where(valid, scores, jnp.inf)
        mx = jnp.max(big, axis=-1, keepdims=True)
        mn = jnp.min(small, axis=-1, keepdims=True)
        mx = jnp.where(jnp.isfinite(mx), mx, 0.0)
        mn = jnp.where(jnp.isfinite(mn), mn, 0.0)
        out = jnp.where(
            mx == 0,
            float(MAX_NODE_SCORE),
            MAX_NODE_SCORE * (mx + mn - scores) / jnp.where(mx == 0, 1.0, mx),
        )
        return jnp.where(valid, out, 0.0)

    # --- row-sliced variants for the fast assignment scan ---------------------

    def filter_row(self, batch, snap, dyn, aux: TSAux, i):
        if aux is None:
            return jnp.ones(snap.num_nodes, bool)
        counts = aux.hard_counts[i]  # [C, D+1]
        present = aux.hard_present[i]
        dom = aux.dom_val[i]  # [C, N]
        min_match = jnp.min(jnp.where(present, counts, BIG), axis=-1)  # [C]
        if self.enable_min_domains:
            ndom = jnp.sum(present, axis=-1)
            md = aux.min_domains[i]
            min_match = jnp.where((md > 0) & (ndom < md), 0, min_match)
        match_num = domain_gather(counts, dom).astype(jnp.int32)  # [C, N]
        skew = (
            match_num + aux.self_match[i][:, None].astype(jnp.int32)
            - min_match[:, None]
        )
        ok_c = (skew <= aux.max_skew[i][:, None]) & aux.has_key[i]
        return jnp.all(~aux.hard_valid[i][:, None] | ok_c, axis=0)  # [N]

    def score_row(self, batch, snap, dyn, aux: TSAux, i, mask_row=None):
        if aux is None:
            return jnp.zeros(snap.num_nodes)
        d = aux.soft_counts.shape[-1] - 1
        soft_valid = aux.soft_valid[i]  # [C]
        has_key = aux.has_key[i]  # [C, N]
        dom = aux.dom_val[i]
        counts = aux.soft_counts[i]  # [C, D+1]
        if mask_row is None:
            mask_row = jnp.ones(dom.shape[-1], bool)
        ignored = ~jnp.all(~soft_valid[:, None] | has_key, axis=0)  # [N]
        scored = mask_row & ~ignored
        c_cap = dom.shape[0]
        soft_present = domain_any(scored[None, :] & (dom < d), dom, counts.shape[-1])
        topo_size = jnp.sum(soft_present[:, :d], axis=-1)  # [C]
        tp_weight = jnp.log(topo_size.astype(jnp.float32) + 2.0)
        cnt = domain_gather(counts, dom)  # [C, N]
        in_present = domain_gather(soft_present, dom) > 0.5
        per_c = (
            cnt.astype(jnp.float32) * tp_weight[:, None]
            + (aux.max_skew[i][:, None].astype(jnp.float32) - 1.0)
        )
        raw = jnp.round(jnp.sum(
            jnp.where(soft_valid[:, None] & has_key & in_present, per_c, 0.0), axis=0
        ))
        has_soft = jnp.any(soft_valid)
        return jnp.where(
            has_soft & ~scored, jnp.nan, jnp.where(has_soft, raw, 0.0)
        )

    # --- in-scan update -------------------------------------------------------

    def update(self, aux: TSAux, i, node_row, batch, snap):
        """Pod i was placed on node_row: bump (j, c) tables where pod i matches
        pending pod j's constraint selectors and the node is counted for j."""
        if aux is None:
            return None
        b, c_cap, _ = aux.dom_val.shape
        dom_at = aux.dom_val[:, :, node_row]  # [B, C]
        inc = (
            aux.match_pending[:, :, i]
            & aux.counted_hard[:, node_row][:, None]
        ).astype(jnp.int32)  # [B, C]
        hard_counts = point_scatter_add(aux.hard_counts, dom_at, inc)
        inc_soft = (
            aux.match_pending[:, :, i]
            & aux.counted_soft[:, node_row][:, None]
        ).astype(jnp.int32)
        soft_counts = point_scatter_add(aux.soft_counts, dom_at, inc_soft)
        return aux._replace(hard_counts=hard_counts, soft_counts=soft_counts)

    def chain_prev(self, aux: TSAux, batch, snap, prev):
        """Deep-pipeline cross-BATCH chaining: fold the still-in-flight
        previous batch's placements (device-resident ``prev.rows``) into this
        batch's count tables, exactly as if those pods were already in the
        snapshot.  The cross-match (this batch's constraint selectors vs the
        previous batch's pod labels, same namespace) is computed from the
        prev batch's label arrays inside the program, so no host round trip
        touches the chain."""
        if aux is None:
            return None
        d = aux.hard_counts.shape[-1] - 1
        n = snap.num_nodes
        placed = (prev.rows >= 0) & jnp.asarray(prev.valid)  # [B0]
        rows = jnp.clip(prev.rows, 0, n - 1)
        # selector (b, c) vs prev batch's pods → [B1, C, B0] — the same
        # helper prepare() uses against snapshot/pending pods
        m = self._selector_vs_pods(
            batch, prev.label_keys, prev.label_vals, prev.ns, snap.numeric
        )
        m = m & placed[None, None, :]
        # counted-node gates + domain of each prev pod's node under (b, c)
        counted_h = aux.counted_hard[:, rows]  # [B1, B0]
        counted_s = aux.counted_soft[:, rows]
        dom_at = aux.dom_val[:, :, rows]  # [B1, C, B0]
        inc_h = domain_scatter_add(
            (m & counted_h[:, None, :]).astype(jnp.float32), dom_at, d + 1
        )
        inc_s = domain_scatter_add(
            (m & counted_s[:, None, :]).astype(jnp.float32), dom_at, d + 1
        )
        return aux._replace(
            hard_counts=aux.hard_counts + inc_h.astype(jnp.int32),
            soft_counts=aux.soft_counts + inc_s.astype(jnp.int32),
        )

    def update_batch_classes(self, aux: TSAux, u_c, batch, rep_batch, snap,
                             class_of):
        """update_batch at identity-class granularity (the dedup engine's
        round update): ``aux`` is the rep view ([C, ...] pending axis) and
        ``u_c`` f32[Cp, N] holds the round's commits aggregated per
        COMMITTER class.  match_pending is a pure function of the two pods'
        classes, so the class fold is exact — O(C·Cc·N) per round."""
        if aux is None:
            return None
        from ..ops.segment import domain_scatter_add_backend as _dscatter

        d = aux.hard_counts.shape[-1] - 1
        contrib = jnp.einsum(
            "bck,kn->bcn", aux.match_pending.astype(jnp.float32), u_c)
        # backend-aware scatter: runs once per auction ROUND, where the
        # one-hot einsum form is O(N·D) memory traffic per call on CPU
        hard_inc = _dscatter(
            contrib * aux.counted_hard[:, None, :], aux.dom_val, d + 1)
        soft_inc = _dscatter(
            contrib * aux.counted_soft[:, None, :], aux.dom_val, d + 1)
        return aux._replace(
            hard_counts=aux.hard_counts + hard_inc.astype(jnp.int32),
            soft_counts=aux.soft_counts + soft_inc.astype(jnp.int32),
        )

    def update_batch(self, aux: TSAux, commit, choice, u, batch, snap):
        """All of a round's placements at once (batch_assign):
        contributions are commutative scatter-adds, so the per-pod update
        folds into two einsums against the commit one-hot ``u`` [B, N]."""
        if aux is None:
            return None
        d = aux.hard_counts.shape[-1] - 1
        # pending-pod j's table (b, c) gains at the domain of each committed
        # pod i's node, where i matches (b, c)'s selector and the node counts
        contrib = jnp.einsum(
            "bci,in->bcn", aux.match_pending.astype(jnp.float32), u
        )  # [B, C, N]
        hard_inc = domain_scatter_add(
            contrib * aux.counted_hard[:, None, :], aux.dom_val, d + 1
        )
        soft_inc = domain_scatter_add(
            contrib * aux.counted_soft[:, None, :], aux.dom_val, d + 1
        )
        return aux._replace(
            hard_counts=aux.hard_counts + hard_inc.astype(jnp.int32),
            soft_counts=aux.soft_counts + soft_inc.astype(jnp.int32),
        )

