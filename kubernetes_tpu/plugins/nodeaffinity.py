"""NodeAffinity as a batched tensor program.

Reference: pkg/scheduler/framework/plugins/nodeaffinity/node_affinity.go
  Filter — pod.spec.nodeSelector (AND of exact matches) AND
           requiredDuringSchedulingIgnoredDuringExecution (OR of terms)
  Score  — Σ weights of matching preferredDuringScheduling terms
  NormalizeScore — DefaultNormalizeScore (not reversed)

matchFields(metadata.name) works because the encoder interns the node name as the
pseudo-label "metadata.name" (state/encoding.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.events import ActionType, ClusterEvent, EventResource
from ..framework.interface import Plugin
from .helpers import (
    default_normalize,
    label_selector_matrix,
    node_selector_matrix,
    weighted_term_matrix,
)


class NodeAffinityPlugin(Plugin):
    name = "NodeAffinity"

    def events_to_register(self):
        return [ClusterEvent(EventResource.NODE, ActionType.ADD | ActionType.UPDATE_NODE_LABEL)]

    def filter(self, batch, snap, dyn, aux=None):
        sel_ok = label_selector_matrix(
            batch.node_selector, snap.node_label_keys, snap.node_label_vals,
            snap.numeric, vals_num=snap.node_label_num,
        )
        aff_ok = node_selector_matrix(
            batch.node_affinity, snap.node_label_keys, snap.node_label_vals,
            snap.numeric, vals_num=snap.node_label_num,
        )
        return sel_ok & aff_ok  # [B, N]

    def score(self, batch, snap, dyn, aux=None, mask=None):
        return weighted_term_matrix(
            batch.pref_req_key, batch.pref_req_op, batch.pref_req_vals,
            batch.pref_req_num, batch.pref_valid, batch.pref_weight,
            snap.node_label_keys, snap.node_label_vals, snap.numeric,
            vals_num=snap.node_label_num,
        )

    def normalize(self, scores, mask):
        return default_normalize(scores, mask)
