"""Volume plugins: VolumeBinding, VolumeZone, NodeVolumeLimits, VolumeRestrictions.

Reference: pkg/scheduler/framework/plugins/
  volumebinding/ (binder.go FindPodVolumes/AssumePodVolumes/BindPodVolumes,
    assume_cache.go; volume_binding.go PreFilter/Filter/Reserve/PreBind)
  volumezone/volume_zone.go    — bound-PV zone/region labels must match node
  nodevolumelimits/{csi,non_csi}.go — per-node attachable-volume counts vs limit
  volumerestrictions/volume_restrictions.go — same-volume read-write conflicts

Design: volume feasibility is *data-dependent on API objects* (PVCs/PVs/classes)
rather than on dense per-node numeric state, and volumes are sparse in practice
— so these plugins compute their ``[B, N]`` masks host-side at host_prepare time
(the PreFilter analog) from the listers, and the device program just ANDs the
uploaded mask.  Binding decisions (WaitForFirstConsumer) are assumed at Reserve
and written at PreBind, exactly the reference's extension-point split.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import objects as v1
from ..api.labels import match_node_selector
from ..api.resource import parse_quantity
from ..framework.events import ActionType, ClusterEvent, EventResource
from ..framework.interface import Plugin, Status

ZONE_LABELS = (
    "topology.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/zone",
)
REGION_LABELS = (
    "topology.kubernetes.io/region",
    "failure-domain.beta.kubernetes.io/region",
)
DEFAULT_EBS_LIMIT = 39  # nodevolumelimits defaults
DEFAULT_GCE_PD_LIMIT = 16


class StoreVolumeListers:
    """Listers over the sim ObjectStore (client-go lister analog)."""

    def __init__(self, store):
        self.store = store

    def pvc(self, namespace: str, name: str) -> Optional[v1.PersistentVolumeClaim]:
        return self.store.get("PersistentVolumeClaim", namespace, name)

    def pv(self, name: str) -> Optional[v1.PersistentVolume]:
        return self.store.get("PersistentVolume", "", name)

    def pvs(self) -> List[v1.PersistentVolume]:
        return self.store.list("PersistentVolume")[0]

    def storage_class(self, name: str) -> Optional[v1.StorageClass]:
        return self.store.get("StorageClass", "", name)

    def csinode(self, node_name: str) -> Optional[v1.CSINode]:
        return self.store.get("CSINode", "", node_name)


class _HostMaskPlugin(Plugin):
    """Base: host_prepare computes a bool[B, N] mask; filter returns it."""

    def host_prepare(self, batch, snapshot, encoder, namespace_labels=None):
        mask = np.ones((batch.size, encoder._n), dtype=bool)
        self._fill(mask, batch, snapshot, encoder)
        if mask.all():
            # Unconstrained (no PVCs in the batch, the common case): skip the
            # [B, N] host→device upload entirely — at 5k nodes these masks are
            # ~1 MB/plugin/cycle over the device link; filter() emits ones
            # inside the traced program instead.
            return None
        return {"mask": mask}

    def prepare(self, batch, snap, dyn, host_aux=None):
        import jax.numpy as jnp

        if host_aux is None:
            return None
        return jnp.asarray(host_aux["mask"])

    def filter(self, batch, snap, dyn, aux=None):
        import jax.numpy as jnp

        if aux is None:
            return jnp.ones((batch.valid.shape[0], snap.num_nodes), bool)
        return aux

    def _fill(self, mask, batch, snapshot, encoder):  # pragma: no cover
        raise NotImplementedError


def _pod_pvcs(pod: v1.Pod):
    return [v.pvc_name for v in pod.spec.volumes if v.pvc_name]


class VolumeBindingPlugin(_HostMaskPlugin):
    name = "VolumeBinding"

    def __init__(self, listers: Optional[StoreVolumeListers] = None):
        self.listers = listers
        # assume cache: pv name → claimed "ns/name" (assume_cache.go analog)
        self._assumed_pv: Dict[str, str] = {}
        self._decisions: Dict[str, List[Tuple[str, v1.PersistentVolume]]] = {}

    def events_to_register(self):
        return [
            ClusterEvent(EventResource.PVC, ActionType.ALL),
            ClusterEvent(EventResource.PV, ActionType.ALL),
            ClusterEvent(EventResource.STORAGE_CLASS, ActionType.ALL),
            ClusterEvent(EventResource.NODE, ActionType.ADD | ActionType.UPDATE_NODE_LABEL),
        ]

    # --- PreFilter/Filter -----------------------------------------------------

    def _pv_available(self, pv: v1.PersistentVolume, claim_key: str) -> bool:
        owner = self._assumed_pv.get(pv.metadata.name)
        if owner is not None and owner != claim_key:
            return False
        return pv.claim_ref is None or pv.claim_ref == claim_key

    def _pv_matches(self, pv: v1.PersistentVolume, pvc: v1.PersistentVolumeClaim) -> bool:
        if (pv.storage_class_name or "") != (pvc.storage_class_name or ""):
            return False
        cap = parse_quantity(pv.capacity.get("storage", 0))
        want = parse_quantity(pvc.requested_storage or 0)
        if cap < want:
            return False
        if pvc.access_modes and not set(pvc.access_modes) <= set(pv.access_modes or pvc.access_modes):
            return False
        return True

    def _fill(self, mask, batch, snapshot, encoder):
        if self.listers is None:
            return
        rows = encoder.node_rows
        # per-_fill memo: a class's AllowedTopologies node mask depends only
        # on (class, node) — computing it per pod per claim would be
        # O(B x N) redundant Python selector matches on the hot path
        topo_rows_cache: Dict[str, List[int]] = {}

        def class_blocked_rows(sc_name: str, sel) -> List[int]:
            hit = topo_rows_cache.get(sc_name)
            if hit is None:
                hit = [
                    r for info in snapshot.node_info_list
                    if (r := rows.get(info.node_name)) is not None
                    and not match_node_selector(sel, info.node)
                ]
                topo_rows_cache[sc_name] = hit
            return hit

        for i, pod in enumerate(batch.pods):
            for claim in _pod_pvcs(pod):
                pvc = self.listers.pvc(pod.namespace, claim)
                if pvc is None:
                    mask[i, :] = False  # UnschedulableAndUnresolvable
                    break
                claim_key = f"{pod.namespace}/{claim}"
                if pvc.volume_name:  # bound: PV node affinity gates nodes
                    pv = self.listers.pv(pvc.volume_name)
                    if pv is None:
                        mask[i, :] = False
                        break
                    if pv.node_affinity is not None:
                        for info in snapshot.node_info_list:
                            r = rows.get(info.node_name)
                            if r is not None and not match_node_selector(
                                pv.node_affinity, info.node
                            ):
                                mask[i, r] = False
                    continue
                sc = self.listers.storage_class(pvc.storage_class_name or "")
                if sc is None or sc.volume_binding_mode != v1.VOLUME_BINDING_WAIT:
                    # unbound immediate-binding PVC → wait for the PV controller
                    # (volume_binding.go PreFilter: UnschedulableAndUnresolvable)
                    mask[i, :] = False
                    break
                # WaitForFirstConsumer: node must have a matching available PV,
                # or the class must be provisionable (dynamic provisioning)
                if sc.provisioner:
                    # topology-aware provisioning: only nodes inside the
                    # class's AllowedTopologies can host the provisioned PV
                    # (binder.go checkVolumeProvisions topology check)
                    if sc.allowed_topologies is not None:
                        blocked = class_blocked_rows(
                            pvc.storage_class_name or "", sc.allowed_topologies
                        )
                        if blocked:
                            mask[i, blocked] = False
                    continue  # provisioning happens at PreBind
                candidates = [
                    pv for pv in self.listers.pvs()
                    if self._pv_available(pv, claim_key) and self._pv_matches(pv, pvc)
                ]
                for info in snapshot.node_info_list:
                    r = rows.get(info.node_name)
                    if r is None:
                        continue
                    ok = any(
                        pv.node_affinity is None
                        or match_node_selector(pv.node_affinity, info.node)
                        for pv in candidates
                    )
                    if not ok:
                        mask[i, r] = False

    # --- Reserve / Unreserve / PreBind ---------------------------------------

    def reserve(self, state, pod: v1.Pod, node_name: str) -> Status:
        """AssumePodVolumes: pick a PV per unbound WaitForFirstConsumer PVC.

        A failure on a LATER claim rolls back the earlier claims' assumes —
        without this, a multi-PVC pod that can satisfy its first claim but
        not its second would leak the first PV's assume-cache entry and
        starve other claimants until process restart (the reference's
        AssumePodVolumes is all-or-nothing via RevertAssumedPodVolumes).
        """
        if self.listers is None:
            return Status.success()
        node = None
        decisions: List[Tuple[str, v1.PersistentVolume]] = []

        def fail(status: Status) -> Status:
            for _ck, pv in decisions:
                self._assumed_pv.pop(pv.metadata.name, None)
            return status

        for claim in _pod_pvcs(pod):
            pvc = self.listers.pvc(pod.namespace, claim)
            if pvc is None:
                return fail(Status.unschedulable(
                    f"PVC {claim} not found", plugin=self.name))
            if pvc.volume_name:
                continue
            claim_key = f"{pod.namespace}/{claim}"
            sc = self.listers.storage_class(pvc.storage_class_name or "")
            if sc is not None and sc.provisioner:
                # topology re-check at assume time (the selected node must
                # satisfy AllowedTopologies even under a stale filter mask)
                if sc.allowed_topologies is not None:
                    if node is None:
                        node = self._node_of(node_name)
                    if node is None or not match_node_selector(
                        sc.allowed_topologies, node
                    ):
                        return fail(Status.unschedulable(
                            f"node {node_name} outside class "
                            f"{pvc.storage_class_name} allowed topologies",
                            plugin=self.name,
                        ))
                continue  # dynamically provisioned at PreBind
            chosen = None
            # capacity-aware matching (volume.FindMatchingVolume): among
            # fitting PVs pick the SMALLEST capacity, name as tie-break, so
            # big volumes stay available for big claims
            fitting = []
            for pv in self.listers.pvs():
                if not (self._pv_available(pv, claim_key) and self._pv_matches(pv, pvc)):
                    continue
                if pv.node_affinity is not None:
                    if node is None:
                        node = self._node_of(node_name)
                    if node is None or not match_node_selector(pv.node_affinity, node):
                        continue
                fitting.append(pv)
            if fitting:
                chosen = min(
                    fitting,
                    key=lambda pv: (
                        parse_quantity(pv.capacity.get("storage", 0)),
                        pv.metadata.name,
                    ),
                )
            if chosen is None:
                return fail(Status.unschedulable(
                    f"no PersistentVolume fits PVC {claim} on {node_name}",
                    plugin=self.name,
                ))
            self._assumed_pv[chosen.metadata.name] = claim_key
            decisions.append((claim_key, chosen))
        if decisions:
            self._decisions[pod.uid] = decisions
        return Status.success()

    def unreserve(self, state, pod: v1.Pod, node_name: str) -> None:
        for _claim_key, pv in self._decisions.pop(pod.uid, []):
            self._assumed_pv.pop(pv.metadata.name, None)

    def pre_bind(self, state, pod: v1.Pod, node_name: str) -> Status:
        """BindPodVolumes: persist PV.claimRef + PVC.volumeName (the fake PV
        controller of the perf harness folded into PreBind)."""
        if self.listers is None:
            return Status.success()
        store = self.listers.store
        for claim_key, pv in self._decisions.pop(pod.uid, []):
            ns, name = claim_key.split("/", 1)
            pv.claim_ref = claim_key
            store.update("PersistentVolume", pv)
            pvc = self.listers.pvc(ns, name)
            if pvc is not None:
                pvc.volume_name = pv.metadata.name
                pvc.phase = "Bound"
                store.update("PersistentVolumeClaim", pvc)
            self._assumed_pv.pop(pv.metadata.name, None)
        # dynamic provisioning for provisioner-backed classes
        for claim in _pod_pvcs(pod):
            pvc = self.listers.pvc(pod.namespace, claim)
            if pvc is None or pvc.volume_name:
                continue
            sc = self.listers.storage_class(pvc.storage_class_name or "")
            if sc is not None and sc.provisioner:
                pv = v1.PersistentVolume(
                    capacity={"storage": pvc.requested_storage or "1Gi"},
                    storage_class_name=pvc.storage_class_name or "",
                    claim_ref=f"{pod.namespace}/{claim}",
                )
                pv.metadata.name = f"pvc-{pvc.metadata.uid or claim}"
                # topology-aware provisioning: the provisioned PV is pinned
                # to the selected node's topology segment — the class's
                # AllowedTopologies keys when set (the node's own values for
                # those keys), else the node's zone, else its hostname
                # (binder.go provisioning path; real provisioners pin via
                # PV.NodeAffinity so later restarts reschedule correctly)
                pv.node_affinity = self._provisioned_affinity(sc, node_name)
                store.create("PersistentVolume", pv)
                pvc.volume_name = pv.metadata.name
                pvc.phase = "Bound"
                store.update("PersistentVolumeClaim", pvc)
        return Status.success()

    _ZONE_KEY = "topology.kubernetes.io/zone"

    def _provisioned_affinity(self, sc, node_name: str):
        node = self._node_of(node_name)
        if node is None:
            return None
        labels = node.metadata.labels or {}
        keys: List[str] = []
        if sc.allowed_topologies is not None:
            for term in sc.allowed_topologies.node_selector_terms:
                for req in term.match_expressions:
                    if req.key and req.key not in keys:
                        keys.append(req.key)
        if not keys:
            keys = [self._ZONE_KEY] if self._ZONE_KEY in labels else [
                "kubernetes.io/hostname"
            ]
        reqs = [
            v1.NodeSelectorRequirement(key=k, operator=v1.OP_IN,
                                       values=[labels[k]])
            for k in keys if k in labels
        ]
        if not reqs:
            # no topology labels at all: pin to the node name itself
            reqs = [v1.NodeSelectorRequirement(
                key="kubernetes.io/hostname", operator=v1.OP_IN,
                values=[node_name],
            )]
        return v1.NodeSelector(
            node_selector_terms=[v1.NodeSelectorTerm(match_expressions=reqs)]
        )

    def _node_of(self, node_name: str) -> Optional[v1.Node]:
        return self.listers.store.get("Node", "", node_name)


class VolumeZonePlugin(_HostMaskPlugin):
    """Bound-PV zone/region labels must match the node (volumezone/)."""

    name = "VolumeZone"

    def __init__(self, listers: Optional[StoreVolumeListers] = None):
        self.listers = listers

    def events_to_register(self):
        return [
            ClusterEvent(EventResource.PVC, ActionType.ALL),
            ClusterEvent(EventResource.PV, ActionType.ALL),
            ClusterEvent(EventResource.NODE, ActionType.ADD | ActionType.UPDATE_NODE_LABEL),
        ]

    def _fill(self, mask, batch, snapshot, encoder):
        if self.listers is None:
            return
        rows = encoder.node_rows
        # per-_fill memo: a class's AllowedTopologies node mask depends only
        # on (class, node) — computing it per pod per claim would be
        # O(B x N) redundant Python selector matches on the hot path
        topo_rows_cache: Dict[str, List[int]] = {}

        def class_blocked_rows(sc_name: str, sel) -> List[int]:
            hit = topo_rows_cache.get(sc_name)
            if hit is None:
                hit = [
                    r for info in snapshot.node_info_list
                    if (r := rows.get(info.node_name)) is not None
                    and not match_node_selector(sel, info.node)
                ]
                topo_rows_cache[sc_name] = hit
            return hit

        for i, pod in enumerate(batch.pods):
            for claim in _pod_pvcs(pod):
                pvc = self.listers.pvc(pod.namespace, claim)
                if pvc is None or not pvc.volume_name:
                    continue
                pv = self.listers.pv(pvc.volume_name)
                if pv is None:
                    continue
                for label_set in (ZONE_LABELS, REGION_LABELS):
                    pv_vals = None
                    for lbl in label_set:
                        if lbl in pv.metadata.labels:
                            # reference: value may be a __-separated set
                            pv_vals = set(pv.metadata.labels[lbl].split("__"))
                            break
                    if pv_vals is None:
                        continue
                    for info in snapshot.node_info_list:
                        r = rows.get(info.node_name)
                        if r is None:
                            continue
                        node_val = None
                        for lbl in label_set:
                            node_val = info.node.metadata.labels.get(lbl) or node_val
                        if node_val is None or node_val not in pv_vals:
                            mask[i, r] = False


class NodeVolumeLimitsPlugin(_HostMaskPlugin):
    """Attachable-volume count limits (nodevolumelimits/{csi,non_csi}.go)."""

    name = "NodeVolumeLimits"

    def __init__(self, listers: Optional[StoreVolumeListers] = None,
                 ebs_limit: int = DEFAULT_EBS_LIMIT,
                 gce_limit: int = DEFAULT_GCE_PD_LIMIT):
        self.listers = listers
        self.ebs_limit = ebs_limit
        self.gce_limit = gce_limit

    def events_to_register(self):
        return [
            ClusterEvent(EventResource.CSI_NODE, ActionType.ALL),
            ClusterEvent(EventResource.POD, ActionType.DELETE),
        ]

    @staticmethod
    def _counts(pod: v1.Pod) -> Tuple[int, int]:
        ebs = sum(1 for vol in pod.spec.volumes if vol.aws_ebs_volume_id)
        gce = sum(1 for vol in pod.spec.volumes if vol.gce_pd_name)
        return ebs, gce

    def _fill(self, mask, batch, snapshot, encoder):
        rows = encoder.node_rows
        pod_counts = [self._counts(p) for p in batch.pods]
        if not any(e or g for e, g in pod_counts):
            return
        for info in snapshot.node_info_list:
            r = rows.get(info.node_name)
            if r is None:
                continue
            used_ebs = used_gce = 0
            for pi in info.pods:
                e, g = self._counts(pi.pod)
                used_ebs += e
                used_gce += g
            ebs_limit, gce_limit = self.ebs_limit, self.gce_limit
            if self.listers is not None:
                csin = self.listers.csinode(info.node_name)
                if csin is not None:
                    ebs_limit = csin.driver_limits.get("ebs.csi.aws.com", ebs_limit)
                    gce_limit = csin.driver_limits.get(
                        "pd.csi.storage.gke.io", gce_limit
                    )
            for i, (e, g) in enumerate(pod_counts):
                if (e and used_ebs + e > ebs_limit) or (g and used_gce + g > gce_limit):
                    mask[i, r] = False


class VolumeRestrictionsPlugin(_HostMaskPlugin):
    """Same-volume conflicts: a GCE PD / AWS EBS volume may only be attached by
    one pod per node (read-write) — volumerestrictions/volume_restrictions.go."""

    name = "VolumeRestrictions"

    def events_to_register(self):
        return [ClusterEvent(EventResource.POD, ActionType.DELETE)]

    @staticmethod
    def _exclusive_ids(pod: v1.Pod):
        out = set()
        for vol in pod.spec.volumes:
            if vol.gce_pd_name:
                out.add(("gce", vol.gce_pd_name))
            if vol.aws_ebs_volume_id:
                out.add(("ebs", vol.aws_ebs_volume_id))
        return out

    def _fill(self, mask, batch, snapshot, encoder):
        rows = encoder.node_rows
        pod_ids = [self._exclusive_ids(p) for p in batch.pods]
        if not any(pod_ids):
            return
        for info in snapshot.node_info_list:
            r = rows.get(info.node_name)
            if r is None:
                continue
            node_ids = set()
            for pi in info.pods:
                node_ids |= self._exclusive_ids(pi.pod)
            if not node_ids:
                continue
            for i, ids in enumerate(pod_ids):
                if ids & node_ids:
                    mask[i, r] = False
