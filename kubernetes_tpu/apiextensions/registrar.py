"""Dynamic-kind registrar: CRD objects → served kinds, at runtime.

Reference: apiextensions-apiserver/pkg/apiserver/customresource_handler.go —
the crdHandler that watches CustomResourceDefinitions and (un)installs REST
storage for the kinds they define.  Here the moving parts are narrower but
the same shape: on CRD create/update the registrar mints the served type
(api.make_kind_type) and registers it in the scheme — which is the single
source the apiserver's routing, the WAL's encoder, and every decode path
read — and flips the kind's store scoping; on CRD delete it cascades the
stored custom resources out (watchers see ordered DELETED events) and
removes the kind, so the plural 404s and open watches terminate.

Convergence discipline (the ghost-kind invariant):
  - every operation is idempotent — a replayed or re-listed CRD event
    re-derives the same registration (``_fingerprint`` match → no-op);
  - a CRD whose kind collides with a built-in is REFUSED (counted under
    ``crd_registrations_total{op="conflict"}``), never half-installed;
  - cascade deletes that fail under injected faults (429 storms) park the
    kind in a pending set that ``resync()``/the next drain retries — a
    deleted CRD's resources eventually disappear, exactly once each;
  - during WAL replay the registrar NEVER writes to the store (the log
    already contains whatever cascade completed before the crash);
    ``resync()`` after replay completes any interrupted cascade.

Threading: the registrar is driven by ONE store's synchronous watch
fan-out (events arrive under the store lock, in rv order) plus boot-time
``attach``/``resync`` calls made before serving starts — a single logical
writer, so its bookkeeping dicts need no lock of their own.  Cascade
deletes triggered by a live event re-enter the store through its reentrant
write path; during ``attach``'s history replay and WAL replay they are
deferred and drained outside the store lock.
"""

from __future__ import annotations

from typing import Dict, Optional, Type

from ..chaos.faults import (
    CRASH_MID_CRD_REGISTER,
    TransientApiError,
    maybe_crash,
)
from ..component_base import logging as klog
from ..metrics import scheduler_metrics as m
from ..sim.store import ObjectStore
from .api import CLUSTER_SCOPE, CustomResourceDefinition, make_kind_type

CRD_KIND = CustomResourceDefinition.kind


class DynamicKindRegistrar:
    def __init__(self, store: ObjectStore, scheme):
        self.store = store
        self.scheme = scheme
        # CRD name → the served type this registrar installed for it
        self._installed: Dict[str, Type] = {}
        # kinds whose stored resources still need cascade deletion
        self._pending_cascade: set = set()
        # True while a WAL replay drives the store: the log already holds
        # the pre-crash cascade, so the registrar must not issue writes
        self.replaying = False
        self._attaching = False
        self._unwatch = None

    # --- lifecycle -----------------------------------------------------------

    def attach(self, drain: bool = True) -> "DynamicKindRegistrar":
        """Subscribe to the store's watch stream.  History replays
        synchronously, so every CRD already stored installs before this
        returns; cascades discovered during the replay drain afterwards,
        outside the store lock."""
        self._attaching = True
        try:
            self._unwatch = self.store.watch(self._on_event)
        finally:
            self._attaching = False
        if drain:
            self._drain_cascades()
        return self

    def close(self) -> None:
        if self._unwatch is not None:
            self._unwatch()
            self._unwatch = None

    def installed_kinds(self) -> Dict[str, str]:
        """CRD name → kind currently served (a stable snapshot)."""
        return {name: typ.kind for name, typ in self._installed.items()}

    # --- event plane ---------------------------------------------------------

    def _on_event(self, ev) -> None:
        if ev.kind != CRD_KIND:
            return
        if ev.type in ("ADDED", "MODIFIED"):
            self._install(ev.obj)
        elif ev.type == "DELETED":
            self._uninstall(ev.obj.metadata.name)

    # --- install / uninstall -------------------------------------------------

    def _install(self, crd: CustomResourceDefinition) -> None:
        try:
            crd.validate()
        except ValueError as e:
            # stored but never served (decode is lenient so the wire/WAL
            # planes round-trip any doc; the invariants gate SERVING)
            m.crd_registrations.inc(("invalid",))
            klog.error_s(e, "CRD refused: invalid spec",
                         crd=crd.metadata.name)
            return
        kind = crd.names.kind
        typ = make_kind_type(crd)
        entry = self.scheme.kind_types().get(kind)
        op = "install"
        if entry is not None:
            current = entry[2]
            if not getattr(current, "_custom_resource", False):
                # a built-in already serves this kind: refuse — installing
                # over it would shadow core serving (the ghost-kind bug)
                m.crd_registrations.inc(("conflict",))
                klog.error_s(
                    None, "CRD refused: kind collides with a built-in",
                    crd=crd.metadata.name, kind=kind)
                return
            if getattr(current, "_fingerprint", None) == typ._fingerprint:
                # replayed/re-listed event for the registration we already
                # serve — the idempotent fast path
                self._installed[crd.metadata.name] = current
                return
            # schema/scope/version changed: re-mint under the same kind
            self.scheme.remove_known_type(kind)
            if getattr(current, "scope", "") == CLUSTER_SCOPE \
                    and crd.scope != CLUSTER_SCOPE:
                ObjectStore.CLUSTER_SCOPED.discard(kind)
            op = "update"
        # the crash window: the CRD write is durable (WAL) and visible
        # (watch fan-out reached us) but the kind is not yet served —
        # recovery must converge to exactly one registration
        maybe_crash(CRASH_MID_CRD_REGISTER)
        self.scheme.add_known_type(crd.group, crd.storage_version, typ)
        if crd.scope == CLUSTER_SCOPE:
            # in-place: client facades alias the SAME set object
            ObjectStore.CLUSTER_SCOPED.add(kind)
        self._installed[crd.metadata.name] = typ
        m.crd_registrations.inc((op,))
        m.crd_kinds_served.set(float(len(self._installed)))
        klog.V(1).info_s("custom kind installed", crd=crd.metadata.name,
                         kind=kind, group=crd.group, scope=crd.scope, op=op)

    def _uninstall(self, crd_name: str) -> None:
        typ = self._installed.pop(crd_name, None)
        if typ is None:
            return  # replayed delete of a registration already gone
        kind = typ.kind
        # cascade parks first and (when live) drains BEFORE the kind
        # leaves the scheme, so the DELETED events fan out while the kind
        # still encodes with its apiVersion — watchers decode an ordered
        # drain, then see the stream terminate.  A crash anywhere in the
        # window leaves either a pending cascade or a registration whose
        # CRD is gone; resync() converges both.
        self._pending_cascade.add(kind)
        if not self.replaying and not self._attaching:
            self._drain_cascades()
        self.scheme.remove_known_type(kind)
        if typ.scope == CLUSTER_SCOPE:
            ObjectStore.CLUSTER_SCOPED.discard(kind)
        m.crd_registrations.inc(("uninstall",))
        m.crd_kinds_served.set(float(len(self._installed)))
        klog.V(1).info_s("custom kind uninstalled", crd=crd_name, kind=kind)

    def _drain_cascades(self) -> None:
        """Delete every stored resource of each pending-cascade kind.
        Injected transient faults leave the kind pending for the next
        drain/resync — convergent, and exactly-once per object because
        delete of a missing object is a no-op."""
        for kind in list(self._pending_cascade):
            clean = True
            objs, _ = self.store.list(kind)
            for obj in objs:
                ns = getattr(obj.metadata, "namespace", "")
                try:
                    self.store.delete(kind, ns, obj.metadata.name)
                except TransientApiError as e:
                    clean = False
                    klog.V(1).info_s(
                        "cascade delete deferred", kind=kind,
                        name=obj.metadata.name,
                        err=f"{type(e).__name__}: {e}")
            if clean and not self.store.list(kind)[0]:
                self._pending_cascade.discard(kind)

    # --- convergence ---------------------------------------------------------

    def resync(self) -> "DynamicKindRegistrar":
        """Reconcile registrations against the stored CRDs: install every
        CRD present, uninstall every registration whose CRD is gone, and
        complete interrupted cascades.  The recovery entry point — after a
        WAL replay, a crash mid-register, or a fault storm, one resync
        restores the zero-ghost-kind invariant."""
        crds, _ = self.store.list(CRD_KIND)
        present = {crd.metadata.name: crd for crd in crds}
        for crd in present.values():
            self._install(crd)
        for name in [n for n in self._installed if n not in present]:
            self._uninstall(name)
        self._drain_cascades()
        return self


def attach_registrar(store: ObjectStore, scheme,
                     drain: bool = True) -> DynamicKindRegistrar:
    """Convenience: build + attach in one call (boot paths use it)."""
    return DynamicKindRegistrar(store, scheme).attach(drain=drain)
