"""apiextensions.k8s.io/v1 object model: CustomResourceDefinition + the
generic CustomResource type its registrations serve.

Reference: staging/src/k8s.io/apiextensions-apiserver/pkg/apis/apiextensions
(CustomResourceDefinitionSpec — group/versions/scope/names) and the
structural-schema validation of pkg/apiserver/validation, collapsed to the
subset the control plane actually enforces here: type checking, required
fields, enums, and numeric bounds over a declared openAPIV3Schema tree.

A ``CustomResourceDefinition`` is itself an ordinary built-in kind — it is
stored, WAL-logged, watched, and wire-encoded like any other object.  The
kinds it DEFINES are subclasses of ``CustomResource`` minted per CRD by
``make_kind_type`` and installed dynamically (registrar.py).  A custom
resource keeps its manifest body verbatim (everything except
kind/apiVersion/metadata), so serving it back — JSON or binary wire — is a
generic-document encode with no frozen vocabulary required.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Type

from ..api.objects import ObjectMeta

NAMESPACE_SCOPE = "Namespaced"
CLUSTER_SCOPE = "Cluster"

# manifest keys that are NOT part of a custom resource's body
_ENVELOPE_KEYS = ("kind", "apiVersion", "metadata")


@dataclass
class CRDNames:
    """spec.names: how the defined kind is addressed (REST plural, kind)."""

    plural: str = ""
    singular: str = ""
    kind: str = ""
    list_kind: str = ""

    @classmethod
    def from_dict(cls, d: Mapping) -> "CRDNames":
        kind = d.get("kind", "")
        return cls(
            plural=d.get("plural", ""),
            singular=d.get("singular", "") or kind.lower(),
            kind=kind,
            list_kind=d.get("listKind", "") or (kind + "List" if kind else ""),
        )


@dataclass
class CustomResourceDefinition:
    """One tenant-defined kind: group + served versions + scope + names +
    the storage version's structural schema."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    group: str = ""
    scope: str = NAMESPACE_SCOPE
    names: CRDNames = field(default_factory=CRDNames)
    versions: List[str] = field(default_factory=lambda: ["v1"])
    storage_version: str = "v1"
    schema: Optional[dict] = None  # the storage version's openAPIV3Schema

    kind = "CustomResourceDefinition"

    @property
    def name(self) -> str:
        return self.metadata.name

    def key(self) -> str:
        return self.metadata.name  # cluster-scoped

    @classmethod
    def from_dict(cls, d: Mapping) -> "CustomResourceDefinition":
        # decode is LENIENT: the wire/WAL planes must round-trip any stored
        # document bit-for-bit; invariant enforcement lives in validate(),
        # applied by the registrar before a registration is ever served
        spec = d.get("spec") or {}
        names = CRDNames.from_dict(spec.get("names") or {})
        group = spec.get("group", "")
        scope = spec.get("scope", NAMESPACE_SCOPE)
        meta = ObjectMeta.from_dict(d.get("metadata") or {})
        raw_versions = spec.get("versions") or [{"name": "v1",
                                                 "storage": True}]
        served: List[str] = []
        storage = ""
        schema = None
        for v in raw_versions:
            if isinstance(v, str):
                v = {"name": v}
            if not v.get("served", True):
                continue
            vname = v.get("name", "")
            if not vname:
                continue
            served.append(vname)
            if v.get("storage", False) or not storage:
                storage = vname
                schema = (v.get("schema") or {}).get("openAPIV3Schema")
        return cls(metadata=meta, group=group, scope=scope, names=names,
                   versions=served, storage_version=storage or "v1",
                   schema=schema)

    def validate(self) -> "CustomResourceDefinition":
        """The spec invariants a registration must satisfy to be SERVED
        (raises ValueError).  Kept out of from_dict deliberately: decode
        round-trips any stored doc, the registrar refuses invalid ones."""
        if not self.group:
            raise ValueError("CustomResourceDefinition spec.group is required")
        if not self.names.kind or not self.names.plural:
            raise ValueError(
                "CustomResourceDefinition spec.names needs kind and plural")
        if self.scope not in (NAMESPACE_SCOPE, CLUSTER_SCOPE):
            raise ValueError(
                f"CustomResourceDefinition spec.scope must be "
                f"{NAMESPACE_SCOPE!r} or {CLUSTER_SCOPE!r}, "
                f"got {self.scope!r}")
        expect = f"{self.names.plural}.{self.group}"
        if self.metadata.name and self.metadata.name != expect:
            # the reference's name invariant: <plural>.<group> — it is what
            # makes CRD names collision-free across groups
            raise ValueError(
                f"CustomResourceDefinition name must be {expect!r} "
                f"(plural.group), got {self.metadata.name!r}")
        if not self.versions:
            raise ValueError("CustomResourceDefinition serves no versions")
        return self


# --- structural schema validation -------------------------------------------

_TYPE_CHECKS = {
    "object": lambda v: isinstance(v, dict),
    "array": lambda v: isinstance(v, list),
    "string": lambda v: isinstance(v, str),
    # bool is an int subclass in Python; a schema saying integer must not
    # silently admit true/false
    "integer": lambda v: isinstance(v, int) and not isinstance(v, bool),
    "number": lambda v: isinstance(v, (int, float))
    and not isinstance(v, bool),
    "boolean": lambda v: isinstance(v, bool),
}


def validate_structural(schema: Optional[dict], value,
                        path: str = "") -> List[str]:
    """Errors from checking ``value`` against a structural-schema subset:
    ``type``, ``properties``/``required``/``additionalProperties`` (objects),
    ``items`` (arrays), ``enum``, ``minimum``/``maximum`` (numbers).
    Empty list = valid; an empty/absent schema admits everything (the
    reference's x-kubernetes-preserve-unknown-fields posture)."""
    if not schema:
        return []
    errors: List[str] = []
    where = path or "<root>"
    t = schema.get("type")
    if t:
        check = _TYPE_CHECKS.get(t)
        if check is None:
            errors.append(f"{where}: unsupported schema type {t!r}")
            return errors
        if not check(value):
            errors.append(
                f"{where}: expected {t}, got {type(value).__name__}")
            return errors  # children of a mistyped node are meaningless
    enum = schema.get("enum")
    if enum is not None and value not in enum:
        errors.append(f"{where}: {value!r} not in enum {enum!r}")
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        lo, hi = schema.get("minimum"), schema.get("maximum")
        if lo is not None and value < lo:
            errors.append(f"{where}: {value} below minimum {lo}")
        if hi is not None and value > hi:
            errors.append(f"{where}: {value} above maximum {hi}")
    if isinstance(value, dict):
        props = schema.get("properties") or {}
        for req in schema.get("required") or []:
            if req not in value:
                errors.append(f"{where}: missing required field {req!r}")
        for k, v in value.items():
            sub = props.get(k)
            if sub is not None:
                errors.extend(
                    validate_structural(sub, v, f"{path}.{k}" if path else k))
            elif schema.get("additionalProperties") is False:
                errors.append(f"{where}: unknown field {k!r}")
    if isinstance(value, list):
        items = schema.get("items")
        if items:
            for i, v in enumerate(value):
                errors.extend(validate_structural(items, v, f"{where}[{i}]"))
    return errors


# --- the generic custom resource type ---------------------------------------


class CustomResource:
    """Base of every dynamically-minted custom kind.

    Holds metadata plus the manifest body VERBATIM (``body``: every
    top-level key except kind/apiVersion/metadata) — serving it back is a
    generic-document encode, which is exactly how the wire codec handles
    kinds outside its frozen vocabulary.  Subclasses are minted per CRD by
    ``make_kind_type`` and carry kind/group/version/plural/scope/schema as
    class attributes; ``from_dict`` enforces the CRD's structural schema,
    so invalid bodies are rejected at decode time (HTTP 400) on every path
    — apiserver, WAL replay, in-process writes."""

    kind = ""
    group = ""
    version = "v1"
    plural = ""
    scope = NAMESPACE_SCOPE
    schema: Optional[dict] = None
    crd_name = ""
    # serializer marker (api/serialize.py dispatches on it without
    # importing this module — the same no-cycle discipline as the
    # name-based dispatch for DRA/autoscaler kinds)
    _custom_resource = True

    def __init__(self, metadata: Optional[ObjectMeta] = None,
                 body: Optional[dict] = None):
        self.metadata = metadata or ObjectMeta()
        self.body = body if body is not None else {}

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def spec(self) -> dict:
        return self.body.get("spec") or {}

    @property
    def status(self) -> dict:
        return self.body.get("status") or {}

    def key(self) -> str:
        if type(self).scope == CLUSTER_SCOPE:
            return self.metadata.name
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def __eq__(self, other) -> bool:
        return (type(other) is type(self)
                and other.metadata == self.metadata
                and other.body == self.body)

    def __repr__(self) -> str:
        return (f"{type(self).__name__}(kind={type(self).kind!r}, "
                f"name={self.metadata.name!r})")

    @classmethod
    def from_dict(cls, d: Mapping) -> "CustomResource":
        errors = validate_structural(cls.schema, dict(d))
        if errors:
            raise ValueError(
                f"{cls.kind} schema validation failed: "
                + "; ".join(errors[:8]))
        body = {k: copy.deepcopy(v) for k, v in d.items()
                if k not in _ENVELOPE_KEYS}
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   body=body)


def make_kind_type(crd: CustomResourceDefinition) -> Type[CustomResource]:
    """Mint the served type for one CRD: a CustomResource subclass whose
    class attributes pin the CRD's identity.  The scheme registers the
    subclass like any hand-written kind — decode dispatch, gv_of, and the
    serializer need nothing CRD-specific."""
    return type(crd.names.kind, (CustomResource,), {
        "kind": crd.names.kind,
        "group": crd.group,
        "version": crd.storage_version,
        "plural": crd.names.plural,
        "scope": crd.scope,
        "schema": copy.deepcopy(crd.schema) if crd.schema else None,
        "crd_name": crd.metadata.name,
        "_fingerprint": registration_fingerprint(crd),
    })


# fingerprint of the parts of a CRD that change the served type; the
# registrar skips reinstalling when a replayed/re-listed CRD matches
def registration_fingerprint(crd: CustomResourceDefinition) -> tuple:
    return (crd.group, crd.storage_version, crd.names.plural,
            crd.names.kind, crd.scope, repr(crd.schema))
