"""Dynamic kind registration (apiextensions.k8s.io).

Reference: staging/src/k8s.io/apiextensions-apiserver — the
CustomResourceDefinition object model (``api.py``) and the dynamic
registration machinery (``registrar.py``, the customresource_handler.go
analog) that installs tenant-defined kinds into the scheme, store scoping,
watch cache, WAL, and apiserver routing at runtime.
"""

from .api import (  # noqa: F401
    CLUSTER_SCOPE,
    NAMESPACE_SCOPE,
    CRDNames,
    CustomResource,
    CustomResourceDefinition,
    make_kind_type,
    validate_structural,
)
from .registrar import (  # noqa: F401
    DynamicKindRegistrar,
    attach_registrar,
)
