"""Failover soak: two scheduler replicas under leader election, the leader
killed at EVERY registered crash point in turn across a pod/gang churn.

The chaos soak (chaos/soak.py) proves convergence when the control plane is
faulty but the scheduler process survives; this soak kills the process —
at the exact states the kill-point catalog marks (chaos/faults.py
CRASH_POINTS) — and proves the successor reconstructs and converges:

  - every pod binds exactly once PER INCARNATION (a descheduler-evicted
    pod's harness-created replacement is a new incarnation, like a
    ReplicaSet's replacement — no pod is ever double-bound without an
    intervening delete);
  - gangs stay all-or-nothing end to end (a crash while members hold
    Permit leaves ZERO store binds; a crash mid-flush completes on the
    successor — never a lingering half-bound gang);
  - recovery is bounded (lease expiry + cold-start, measured in driver
    iterations on the injected clock);
  - the drift detector reports zero unrepaired divergence after every
    recovery and on a periodic cadence;
  - deterministic replay: the same seed kills at the same per-point hit
    sequence and converges to the same signature (chaos/faults.py
    determinism contract — crash decisions ride the same per-key op
    counters as every other fault class).

Single-threaded by design, on one injected clock: lease expiry, pod
backoff, and gang deadlines all advance deterministically with the driver
loop, never with the wall clock.
"""

from __future__ import annotations

import copy
import time
from collections import Counter
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..chaos.faults import FaultSchedule, ProcessCrash, crash_schedule
from ..chaos.retry import RetryingStore
from ..client.leaderelection import LeaderElector, LeaseLock
from ..component_base import logging as klog
from ..component_base.healthz import Readyz
from ..descheduler.policies import DRAIN_ANNOTATION
from ..sim.store import DELETED, ObjectStore
from .drift import DriftDetector
from .rebuild import cold_start

LEASE_NS, LEASE_NAME = "kube-system", "tpu-scheduler"
SOAK_LABEL = "failover-soak/workload"

# The kill order is part of the soak's contract: each point is armed only
# when its trigger still has supply (gangs pending before permit_held,
# overflow demand before mid_scaleup, a drain annotation before
# mid_plan_apply), so "killed at every registered crash point" is a real
# guarantee, not best-effort.
KILL_ORDER = (
    "crash.permit_held",
    "crash.after_assume",
    "crash.mid_bind",
    "crash.mid_scaleup",
    "crash.mid_plan_apply",
    "crash.post_lease_renew",
)


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@dataclass
class FailoverResult:
    pods: int  # live pods at the end (originals + replacements - evicted)
    bound: int
    unbound: List[str]
    duplicate_binds: int  # bind transitions beyond one per incarnation
    crashes: List[str]  # points fired, in firing order
    recoveries: int
    max_recovery_iterations: int  # worst crash → leader-ready gap
    gangs_partial: List[str]  # gangs not all-or-nothing at the end
    drift_divergent: int  # divergence incidents (pre-repair) across the run
    drift_unrepaired: int  # divergence surviving repair (must be 0)
    events_lost: int  # final leader's flush losses
    injected: Dict[str, int]
    store_rv: int
    iterations: int
    wall_seconds: float

    @property
    def converged(self) -> bool:
        return (self.bound == self.pods and not self.unbound
                and self.duplicate_binds == 0 and not self.gangs_partial
                and self.drift_unrepaired == 0)

    def determinism_signature(self) -> Dict[str, object]:
        """The replay-stable part of a run: fault+crash decisions, the op
        count they produced, and the converged shape.  Wall time excluded."""
        return {
            "injected": dict(self.injected),
            "crashes": list(self.crashes),
            "bound": self.bound,
            "store_rv": self.store_rv,
            "iterations": self.iterations,
        }


class _Replica:
    """One simulated scheduler process: elector + (lazily built) scheduler
    and controllers.  A crash discards the whole object; a restart is a NEW
    _Replica with a fresh identity generation — the lease held by the dead
    identity must expire before anyone (including the restart) leads."""

    def __init__(self, soak: "_Soak", identity: str):
        self.soak = soak
        self.identity = identity
        self.readyz = Readyz()
        self.sched = None
        self.autoscaler = None
        self.desched = None
        self.drift: Optional[DriftDetector] = None
        self.elector = LeaderElector(
            LeaseLock(soak.store, LEASE_NS, LEASE_NAME),
            identity=identity,
            lease_duration=soak.lease_duration,
            clock=soak.clock,
            on_stopped_leading=self._on_stopped_leading,
        )

    def _on_stopped_leading(self):
        # upstream exits the scheduler binary on a lost lease
        # (cmd/kube-scheduler server.go:204-215); the sim analog: abandon
        # mid-cycle work, shut down cleanly, and rebuild state from the
        # store if leadership ever comes back
        if self.sched is not None:
            self.sched.abandon_inflight()
            self.sched.close()  # clean shutdown: events flush
            self.sched = None
            self.autoscaler = self.desched = self.drift = None


class _Soak:
    def __init__(self, *, seed: int, n_nodes: int, batch_size: int,
                 lease_duration: float, tick: float,
                 write_429_rate: float, conflict_rate: float,
                 drift_every: int, max_iterations: int):
        self.fault = FaultSchedule(
            seed, write_429_rate=write_429_rate, conflict_rate=conflict_rate,
            retry_after=0.0,
        )
        self.raw = ObjectStore(fault_injector=self.fault)
        self.store = RetryingStore(self.raw, jitter_seed=seed,
                                   sleep=lambda _s: None)
        self.clock = _FakeClock()
        self.batch_size = batch_size
        self.n_nodes = n_nodes
        self.lease_duration = lease_duration
        self.tick = tick
        self.drift_every = drift_every
        self.max_iterations = max_iterations
        self.iteration = 0
        self.crashes: List[str] = []
        self.recoveries = 0
        self.max_recovery_iterations = 0
        self._crash_iter: Optional[int] = None
        self.drift_divergent = 0
        self.drift_unrepaired = 0
        self.run_controllers = False
        self._gen = 0
        self._log_pos = 0  # raw._log read cursor (replacement recreation)
        self._replaced: Counter = Counter()
        self.replicas = [self._spawn("a"), self._spawn("b")]

    # --- replica lifecycle ----------------------------------------------------

    def _spawn(self, base: str) -> _Replica:
        self._gen += 1
        return _Replica(self, f"sched-{base}#{self._gen}")

    def _sched_factory(self, store, **kw):
        from ..scheduler import TPUScheduler

        s = TPUScheduler(store, clock=self.clock, **kw)
        # headroom for autoscaled nodes + replacement pods: tier growth
        # mid-run would recompile every program per recovery epoch
        s.presize(4 * self.n_nodes, 512)
        return s

    def _ensure_leader_state(self, rep: _Replica) -> None:
        if rep.sched is not None:
            return
        res = cold_start(
            self.store, readyz=rep.readyz, clock=self.clock,
            scheduler_factory=self._sched_factory,
            batch_size=self.batch_size,
            pod_initial_backoff=0.05, pod_max_backoff=0.2, batch_wait=0,
            fence=rep.elector.check_fence,
        )
        rep.sched = res.scheduler
        if res.drift is not None:
            self.drift_divergent += res.drift.total
            self.drift_unrepaired += sum(res.drift.unrepaired.values())
        from ..autoscaler.controller import ClusterAutoscaler
        from ..descheduler.controller import DeschedulerController

        rep.autoscaler = ClusterAutoscaler(
            self.store, rep.sched, clock=self.clock,
            scale_down_utilization_threshold=0.0)  # soak never shrinks
        rep.desched = DeschedulerController(self.store, rep.sched,
                                            clock=self.clock)
        rep.drift = DriftDetector(rep.sched, clock=self.clock)
        self.recoveries += 1
        if self._crash_iter is not None:
            self.max_recovery_iterations = max(
                self.max_recovery_iterations,
                self.iteration - self._crash_iter)
            self._crash_iter = None

    def _kill(self, rep: _Replica, crash: ProcessCrash) -> None:
        self.crashes.append(crash.point)
        self._crash_iter = self.iteration
        sched, rep.sched = rep.sched, None
        if sched is not None:
            # process death: the watch detaches, NOTHING flushes — retained
            # events and every in-memory table die with the process
            sched.close(flush_events=False)
        idx = self.replicas.index(rep)
        base = "a" if idx == 0 else "b"
        self.replicas[idx] = self._spawn(base)
        klog.V(1).info_s("Replica killed", point=crash.point,
                         identity=rep.identity, iteration=self.iteration)

    # --- driver ---------------------------------------------------------------

    def leader(self) -> Optional[_Replica]:
        for rep in self.replicas:
            if rep.elector.is_leader():
                return rep
        return None

    def step(self) -> None:
        self.iteration += 1
        for rep in list(self.replicas):
            try:
                rep.elector.try_acquire_or_renew()
            except ProcessCrash as crash:
                self._kill(rep, crash)
        rep = self.leader()
        if rep is not None:
            try:
                self._ensure_leader_state(rep)
                rep.sched.schedule_cycle()
                if self.run_controllers:
                    rep.autoscaler.sync_once()
                    rep.desched.sync_once()
                if self.drift_every and \
                        self.iteration % self.drift_every == 0:
                    report = rep.drift.check_and_repair()
                    if report is not None:
                        self.drift_divergent += report.total
                        self.drift_unrepaired += sum(
                            report.unrepaired.values())
                if self.iteration % 20 == 0:
                    # unschedulableQ parks otherwise wait the 60s flush;
                    # fixed cadence keeps the re-drive deterministic
                    unbound = [p for p in self.raw.list("Pod")[0]
                               if not p.spec.node_name]
                    if unbound:
                        rep.sched.queue.activate(unbound)
            except ProcessCrash as crash:
                self._kill(rep, crash)
        self.clock.advance(self.tick)
        self._recreate_evicted()

    def _recreate_evicted(self) -> None:
        """ReplicaSet stand-in: every DELETED workload pod gets exactly one
        replacement incarnation (same spec + labels, deterministic name) so
        descheduler/autoscaler evictions don't shrink the workload and the
        exactly-once-per-incarnation accounting stays meaningful."""
        log = self.raw._log
        while self._log_pos < len(log):
            ev = log[self._log_pos]
            self._log_pos += 1
            if ev.type != DELETED or ev.kind != "Pod":
                continue
            pod = ev.obj
            if pod.metadata.labels.get(SOAK_LABEL) != "true":
                continue
            self._replaced[pod.metadata.name] += 1
            clone = copy.deepcopy(pod)
            clone.metadata.name = f"{pod.metadata.name}-r{self._replaced[pod.metadata.name]}"
            clone.metadata.uid = clone.metadata.name
            clone.metadata.resource_version = None
            clone.spec.node_name = ""
            clone.status.nominated_node_name = None
            self.store.create("Pod", clone)

    def run_until(self, pred, cap: int) -> bool:
        """Drive steps until ``pred()`` or the per-phase cap; False = cap."""
        for _ in range(cap):
            if pred():
                return True
            if self.iteration >= self.max_iterations:
                return False
            self.step()
        return pred()


def run_failover_soak(
    n_plain: int = 16,
    n_gangs: int = 2,
    gang_size: int = 4,
    overflow_gang_size: int = 6,
    n_nodes: int = 8,
    seed: int = 7,
    batch_size: int = 8,
    *,
    group_max_size: int = 8,
    kill_order=KILL_ORDER,
    lease_duration: float = 0.6,
    tick: float = 0.05,
    write_429_rate: float = 0.02,
    conflict_rate: float = 0.02,
    drift_every: int = 40,
    phase_cap: int = 400,
    max_iterations: int = 6000,
) -> FailoverResult:
    """The failover acceptance workload.  Per phase: create that kill
    point's trigger supply, arm the point, run until it fires, run until a
    successor is leader + Ready, then move on; finally converge everything.
    Defaults are the fast battery's size — tests/test_recovery.py's slow
    marker scales it to the 500-pod acceptance shape."""
    from ..api import objects as v1
    from ..gang import POD_GROUP_LABEL
    from ..testutil import make_node, make_pod

    t0 = time.monotonic()
    soak = _Soak(seed=seed, n_nodes=n_nodes, batch_size=batch_size,
                 lease_duration=lease_duration, tick=tick,
                 write_429_rate=write_429_rate, conflict_rate=conflict_rate,
                 drift_every=drift_every, max_iterations=max_iterations)
    store, raw, fault = soak.store, soak.raw, soak.fault

    for i in range(n_nodes):
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "4", "pods": "32"}).obj())
    # the scale-up phase's capacity: one NodeGroup with headroom
    from ..autoscaler.api import NodeGroup

    group = NodeGroup(
        metadata=v1.ObjectMeta(name="pool"),
        min_size=0, max_size=group_max_size,
        capacity={"cpu": "4", "pods": "32"},
        cost_per_node=1.0,
    )
    store.create("NodeGroup", group)

    def mk_pod(name: str, cpu: str, labels: Dict[str, str]):
        b = (make_pod().name(name).uid(name).namespace("default")
             .req({"cpu": cpu}).label(SOAK_LABEL, "true"))
        for k, v in labels.items():
            b = b.label(k, v)
        store.create("Pod", b.obj())

    def mk_gang(gname: str, size: int, cpu: str, timeout: float = 5.0):
        store.create("PodGroup", v1.PodGroup(
            metadata=v1.ObjectMeta(name=gname, namespace="default"),
            min_member=size, schedule_timeout_seconds=timeout))
        for i in range(size):
            mk_pod(f"{gname}-{i}", cpu, {POD_GROUP_LABEL: gname})

    def crashed(point):
        return lambda: f"crash:{point}" in fault.injected

    def leader_ready():
        rep = soak.leader()
        return (rep is not None and rep.sched is not None
                and rep.readyz.ready)

    with crash_schedule(fault):
        for point in kill_order:
            # phase stimuli: keep the point's trigger supplied
            if point == "crash.permit_held":
                for g in range(n_gangs):
                    mk_gang(f"gang{g}", gang_size, "1")
            elif point == "crash.after_assume":
                for i in range(n_plain // 2):
                    mk_pod(f"plain-a{i}", "1", {})
            elif point == "crash.mid_bind":
                for i in range(n_plain - n_plain // 2):
                    mk_pod(f"plain-b{i}", "1", {})
            elif point == "crash.mid_scaleup":
                # overflow gang: cannot fully place on current capacity —
                # parks unschedulable, the autoscaler must scale up
                mk_gang("overflow", overflow_gang_size, "3")
                soak.run_controllers = True
            elif point == "crash.mid_plan_apply":
                node = raw.get("Node", "", "n0")
                node.metadata.annotations[DRAIN_ANNOTATION] = "true"
                store.update("Node", node)
            fault.arm_crash(point, at_hit=2 if point == "crash.mid_bind"
                            else 1)
            fired = soak.run_until(crashed(point), phase_cap)
            if not fired:
                klog.error_s(None, "Failover soak: crash point never fired",
                             point=point, iteration=soak.iteration)
                break
            soak.run_until(leader_ready, phase_cap)
        # convergence: controllers keep running (the drain must finish its
        # re-plans); stop only when every live pod is bound
        def all_bound():
            pods, _ = raw.list("Pod")
            return bool(pods) and all(p.spec.node_name for p in pods)

        soak.run_until(all_bound, max_iterations)

    # --- final accounting -----------------------------------------------------
    pods, _ = raw.list("Pod")
    bound = sum(1 for p in pods if p.spec.node_name)
    unbound = [p.metadata.name for p in pods if not p.spec.node_name]
    # exactly-once per INCARNATION, from the store's own event history:
    # count unbound→bound transitions keyed by (name, incarnation), where
    # a DELETE closes the incarnation — so a deleted-then-recreated name
    # (legitimate churn) is two incarnations with one bind each, while a
    # second bind or a node change within one incarnation is a duplicate
    node_of: Dict[str, Optional[str]] = {}
    incarnation: Counter = Counter()
    binds: Counter = Counter()
    duplicates = 0
    for ev in raw._log:
        if ev.kind != "Pod":
            continue
        name = ev.obj.metadata.name
        if ev.type == DELETED:
            node_of.pop(name, None)
            incarnation[name] += 1
            continue
        nn = ev.obj.spec.node_name or None
        prev = node_of.get(name)
        if nn is not None and prev is None:
            binds[(name, incarnation[name])] += 1
        elif nn is not None and prev is not None and nn != prev:
            duplicates += 1  # re-bound to a different node without delete
        node_of[name] = nn
    duplicates += sum(c - 1 for c in binds.values() if c > 1)
    # gang all-or-nothing at the end: every group fully bound or fully not
    partial: List[str] = []
    for pg in raw.list("PodGroup")[0]:
        members = [p for p in pods
                   if p.metadata.labels.get(POD_GROUP_LABEL) == pg.name
                   and p.namespace == pg.namespace]
        n_bound = sum(1 for p in members if p.spec.node_name)
        if 0 < n_bound < pg.min_member:
            partial.append(pg.key())
    events_lost = 0
    for r in soak.replicas:
        if r.sched is not None:
            events_lost += r.sched.recorder.flush()
            r.sched.close()
    result = FailoverResult(
        pods=len(pods), bound=bound, unbound=unbound,
        duplicate_binds=duplicates, crashes=list(soak.crashes),
        recoveries=soak.recoveries,
        max_recovery_iterations=soak.max_recovery_iterations,
        gangs_partial=partial,
        drift_divergent=soak.drift_divergent,
        drift_unrepaired=soak.drift_unrepaired,
        events_lost=events_lost,
        injected=fault.injected_counts(),
        store_rv=raw.current_rv(),
        iterations=soak.iteration,
        wall_seconds=time.monotonic() - t0,
    )
    klog.V(1).info_s(
        "Failover soak complete", pods=result.pods, bound=result.bound,
        crashes=result.crashes, recoveries=result.recoveries,
        max_recovery_iterations=result.max_recovery_iterations,
        duplicates=result.duplicate_binds, iterations=result.iterations)
    return result
