"""Crash-restart resilience: cold-start reconstruction, drift repair, failover.

The reference control plane survives process death because scheduler state
is SOFT — informers relist (SURVEY §5 checkpoint/resume), the assume cache
expires (pkg/scheduler/internal/cache), leader election hands over.  This
tree carries hard device-adjacent state (DeviceSnapshot mirrors,
AffinityIndex count tables, gang Permit holds, nominated reservations,
half-applied controller plans) that a successor must REBUILD from the
store, then prove equal to a from-scratch encode.

Layout:
  - drift.py    — canonical_state/diff oracle + DriftDetector (periodic and
    on-recovery live-vs-from-scratch diff, repair on divergence,
    scheduler_state_drift_total)
  - rebuild.py  — cold_start: fresh-replica state reconstruction with
    readiness gating (component_base.healthz.Readyz) and a post-rebuild
    drift verification
  - failover.py — two-replica leader-election soak killing the leader at
    every registered crash point (chaos.faults.CRASH_POINTS) across a
    pod/gang churn; deterministic-replay discipline like chaos/soak.py

The kill switches live in chaos/faults.py (maybe_crash at the real call
sites); this package is the recovery side.
"""

from .drift import DriftDetector, DriftReport, canonical_state, diff_canonical  # noqa: F401
from .rebuild import (  # noqa: F401
    RecoveryResult,
    cold_start,
    cold_start_from_wal,
)

__all__ = [
    "DriftDetector",
    "DriftReport",
    "RecoveryResult",
    "canonical_state",
    "cold_start",
    "cold_start_from_wal",
    "diff_canonical",
]
