"""Cold-start state reconstruction: a fresh scheduler replica rebuilds every
piece of in-memory state from the store, then proves it.

What upstream gets for free (SURVEY §5: the informer ListAndWatch restart
loop is a checkpoint/resume; the scheduler cache is rebuilt by replay and
assume-cache entries simply expire), this tree must do explicitly because
it carries hard state:

  - ClusterEncoder/DeviceSnapshot mirrors: rebuilt from a store relist by
    replaying every bound pod through ``Cache.update_snapshot`` →
    ``encoder.sync`` (the exact steady-state path, so recovered ==
    from-scratch bit-for-bit at the canonical keys);
  - AffinityIndex count tables: restored via the existing ``rebuild()``
    repair path;
  - gang phase/permit state: re-derived from PodGroup objects + live
    membership.  A dead leader's Permit holds are pure memory — no waiter
    was ever bound in the store — so the holds "expire" instantly into an
    atomic gang requeue (the unbound members re-enter the queue whole);
    never a half-bound gang.  Phases that claim more than the store shows
    are rewritten;
  - nominated-preemption reservations: STALE by definition (the evictions
    already happened; the dead process's claim map is gone) — cleared from
    pod status so the preemptor re-runs a clean attempt;
  - half-applied descheduler/autoscaler plans: fail-stop by design — the
    controllers re-plan from live state every sync, and scale-ups resume
    exactly-once through deterministic node names (autoscaler/api.py).
    Recovery constructs fresh controllers and touches nothing.

Readiness: progress lands in a ``component_base.healthz.Readyz`` so a
recovering replica reports NotReady (with per-component rebuild progress)
until the final drift verification passes — it never takes traffic
mid-rebuild.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional

from ..api import objects as v1
from ..component_base import logging as klog
from ..metrics import scheduler_metrics as m
from .drift import DriftDetector, DriftReport

# Readyz component names, in rebuild order
READYZ_COMPONENTS = ("relist", "replay", "encode", "gangs", "nominations",
                     "verify")


@dataclass
class RecoveryResult:
    scheduler: object
    nodes: int = 0
    bound_pods: int = 0
    pending_pods: int = 0
    gang_phase_repairs: int = 0
    nominations_dropped: int = 0
    drift: Optional[DriftReport] = None
    outcome: str = "clean"  # clean | repaired | degraded
    seconds: float = 0.0
    # gangs whose store state was partially bound at recovery time (bound
    # members exist but below minMember) — they must complete, not unwind
    partial_gangs: List[str] = field(default_factory=list)


def cold_start(store, *, readyz=None, clock=time.monotonic,
               scheduler_factory=None, verify=True,
               **sched_kwargs) -> RecoveryResult:
    """Build a scheduler replica from nothing but the store and prove its
    state.  ``sched_kwargs`` pass through to the scheduler constructor
    (batch_size, fence, clock, ...); ``scheduler_factory`` overrides the
    class for tests.  ``verify=False`` skips the final drift check (the
    failover soak runs its own periodic detector)."""
    from ..scheduler import TPUScheduler

    factory = scheduler_factory or TPUScheduler
    t0 = clock()
    if readyz is not None:
        # one atomic assignment: no scrape can see the empty-(=ready)
        # window a reset-then-begin sequence would open
        readyz.begin_all(READYZ_COMPONENTS)
    # 1. relist: the authoritative recount (the constructor's watch replay
    # below is the informer path; the relist pins the counts the report
    # and the gang/nomination passes work from)
    nodes, _ = store.list("Node")
    pods, _ = store.list("Pod")
    pgs, _ = store.list("PodGroup")
    bound = [p for p in pods if p.spec.node_name]
    pending = [p for p in pods if not p.spec.node_name]
    if readyz is not None:
        readyz.complete("relist")
    # 2. replay: constructing the scheduler replays the store's history
    # through the watch hook — bound pods land in the cache, pending pods
    # in the queue, PodGroups in the gang directory (ListAndWatch resume)
    sched = factory(store, **sched_kwargs)
    if readyz is not None:
        readyz.complete("replay")
    # 3. encode: bound pods through Cache.update_snapshot → encoder.sync
    # (the steady-state path), then the AffinityIndex repair rebuild
    changed = sched.cache.update_snapshot(sched.snapshot)
    sched.encoder.sync(sched.snapshot, changed)
    sched.encoder.aff.rebuild(sched.snapshot)
    if readyz is not None:
        readyz.complete("encode")
    # 4. gangs: re-derive phase from live membership; a fresh process holds
    # no permits, so phases claiming otherwise are rewritten and the
    # unbound members (already queued by replay) retry as one gang
    repairs = 0
    partial: List[str] = []
    for i, pg in enumerate(pgs):
        key = pg.key()
        g = sched.gangs._state(key)
        n_bound = len(g.bound)
        if n_bound >= pg.min_member:
            phase = v1.POD_GROUP_SCHEDULED
        elif n_bound > 0:
            # partially bound in the STORE (a crash mid-flush): the gang
            # must complete — members already bound stay, the rest
            # reschedule; phase goes back to Scheduling
            phase = v1.POD_GROUP_SCHEDULING
            partial.append(key)
        else:
            phase = v1.POD_GROUP_PENDING
        if pg.phase != phase:
            repairs += 1
            sched.gangs._set_phase(g, phase)
        if readyz is not None:
            readyz.progress("gangs", i + 1, len(pgs) or 1)
    if readyz is not None:
        readyz.complete("gangs")
    # 5. nominations: the dead process's claim map is gone and its victims
    # were already evicted — a stale nominatedNodeName would make the
    # successor reserve capacity for a claim nobody holds
    dropped = 0
    for p in pending:
        if getattr(p.status, "nominated_node_name", None):
            p.status.nominated_node_name = None
            dropped += 1
            try:
                store.update("Pod", p)
            except Exception as e:
                # best-effort: a failed clear leaves only a cosmetic field
                # (this replica's nominator starts empty regardless)
                klog.V(2).info_s("stale nomination clear failed",
                                 pod=p.key(),
                                 error=f"{type(e).__name__}: {e}")
    if readyz is not None:
        readyz.complete("nominations")
    # 6. verify: the rebuilt state must equal a from-scratch store encode;
    # divergence here means the rebuild itself is wrong — repair and stay
    # NotReady if it survives
    drift = None
    outcome = "clean"
    if verify:
        drift = DriftDetector(sched).check_and_repair()
        if drift is not None and not drift.clean:
            outcome = "repaired" if drift.converged else "degraded"
    m.cold_starts.inc((outcome,))
    if readyz is not None and outcome != "degraded":
        readyz.complete("verify")
    # a degraded replica keeps "verify" incomplete: /readyz stays NotReady
    seconds = clock() - t0
    klog.V(1).info_s(
        "Cold-start reconstruction complete", outcome=outcome,
        nodes=len(nodes), bound=len(bound), pending=len(pending),
        gang_phase_repairs=repairs, nominations_dropped=dropped,
        seconds=round(seconds, 4))
    return RecoveryResult(
        scheduler=sched, nodes=len(nodes), bound_pods=len(bound),
        pending_pods=len(pending), gang_phase_repairs=repairs,
        nominations_dropped=dropped, drift=drift, outcome=outcome,
        seconds=seconds, partial_gangs=partial)


def cold_start_from_wal(wal_path: str, *, scheme=None, readyz=None,
                        attach_wal=True, wal_fsync_every: int = 1,
                        **kwargs):
    """REAL process death recovery: PR-8's cold_start assumed a surviving
    store to relist from; this path has only the write-ahead log.  The
    store is reconstructed first (sim/wal.replay_on_boot — torn tail
    checksum-truncated, watch history re-emitted), then the standard
    cold-start reconstruction runs on it unchanged, so every PR-8 proof
    (exactly-once binds, drift verification, gang phase repair) holds from
    a bare file.

    ``attach_wal`` reopens the (truncated) log on the replayed store so the
    successor's own writes keep appending where the dead process stopped;
    ``wal_fsync_every`` sets its cadence and defaults to 1 (every append) —
    a successor must never SILENTLY run a looser durability contract than
    the deployment that just died proved it needs; callers relax it
    explicitly.  Returns (RecoveryResult, ReplayResult)."""
    from ..sim.wal import WriteAheadLog, replay_on_boot

    replay = replay_on_boot(wal_path, scheme=scheme)
    if attach_wal:
        replay.store.wal = WriteAheadLog(wal_path, scheme=scheme,
                                         fsync_every=wal_fsync_every)
    result = cold_start(replay.store, readyz=readyz, **kwargs)
    klog.V(1).info_s("Cold start from WAL", path=wal_path,
                     records=replay.records_applied,
                     truncated_tail=replay.truncated_tail,
                     outcome=result.outcome)
    return result, replay
