"""State-drift detection and repair: live scheduler state vs a from-scratch
store rebuild.

The scheduler's device-adjacent state (cache NodeInfos, ClusterEncoder
mirrors, AffinityIndex count tables) is derived incrementally from the
watch stream; a missed event, an in-place corruption, or a recovery bug
leaves it silently diverged from what a fresh replica would build.  The
detector re-derives everything from the store (plus the live scheduler's
own assumed pods — legitimate scheduler-local state a fresh build cannot
know) into a scratch Cache/ClusterEncoder and diffs CANONICAL forms: keyed
by node name / pod uid / affinity-term signature with dictionary ids and
row numbers decoded away, so two encoders that interned strings or
assigned rows in different orders still compare exactly — and any value
difference is a real divergence, bit-for-bit at the canonical key.

Repair = re-derive: reconcile the cache's bound-pod membership from store
truth (assumes untouched), re-add every node, rebuild the snapshot, drop
ghost encoder rows, full re-encode, and restore the affinity tables via
the existing ``AffinityIndex.rebuild`` repair path.  Divergence counts
emit ``scheduler_state_drift_total{component}`` BEFORE repair, so a soak
asserting "zero unrepaired divergence" still sees every incident.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..component_base import logging as klog
from ..metrics import scheduler_metrics as m
from ..state import units
from ..state.cache import Cache, Snapshot
from ..state.dictionary import MISSING
from ..state.encoding import ClusterEncoder

# canonical-state components, in report order
COMPONENTS = ("cache_pods", "encoder_nodes", "encoder_pods", "affinity")


def _canon_vec(vec: np.ndarray, extended_index: Dict[str, int]) -> tuple:
    """i32[R] resource vector → (base-dim tuple, sorted nonzero extended
    (name, value) pairs) — extended-dim SLOT order differs between encoders
    that met extended resources in different orders."""
    base = tuple(int(v) for v in vec[: units.NUM_BASE_DIMS])
    ext = tuple(sorted(
        (name, int(vec[idx]))
        for name, idx in extended_index.items() if int(vec[idx]) != 0
    ))
    return (base, ext)


def _canon_labels(enc: ClusterEncoder, keys: np.ndarray,
                  vals: np.ndarray) -> tuple:
    out = []
    for k, v in zip(keys.tolist(), vals.tolist()):
        if k != MISSING:
            out.append((enc.dic.string(k), enc.dic.string(v)))
    return tuple(sorted(out))


def canonical_state(scheduler) -> Dict[str, dict]:
    """The live scheduler's rebuildable state in canonical form; also used
    on scratch schedulers, so recovered-vs-from-scratch parity is one dict
    comparison (tests/test_recovery.py pins it exactly).

    Runs the scheduler's own steady-state snapshot refresh first (the same
    ``update_snapshot`` → ``encoder.sync`` every dispatch runs): the
    encoder is DELIBERATELY stale between a bind phase and the next
    dispatch, and that staleness is pipeline slack, not drift."""
    snapshot = getattr(scheduler, "snapshot", None)
    if snapshot is not None:
        changed = scheduler.cache.update_snapshot(snapshot)
        scheduler.encoder.sync(snapshot, changed)
    enc = scheduler.encoder
    cache = scheduler.cache
    nodes: Dict[str, tuple] = {}
    for name, row in enc.node_rows.items():
        if not bool(enc.node_valid[row]):
            continue
        taints = tuple(sorted(
            (enc.dic.string(tk), enc.dic.string(tv), int(te))
            for tk, tv, te in zip(enc.taint_keys[row].tolist(),
                                  enc.taint_vals[row].tolist(),
                                  enc.taint_effects[row].tolist())
            if tk != MISSING
        ))
        nodes[name] = (
            _canon_vec(enc.allocatable[row], enc.extended_index),
            _canon_vec(enc.requested[row], enc.extended_index),
            tuple(int(v) for v in enc.non_zero_requested[row]),
            bool(enc.unschedulable[row]),
            bool(enc.node_ready[row]),
            _canon_labels(enc, enc.node_label_keys[row],
                          enc.node_label_vals[row]),
            taints,
        )
    pods: Dict[str, tuple] = {}
    row_name = enc.row_to_name()
    for uid, row in enc.pod_rows.items():
        if not bool(enc.pod_valid[row]):
            continue
        pods[uid] = (
            row_name.get(int(enc.pod_node[row])),
            _canon_vec(enc.pod_request[row], enc.extended_index),
            int(enc.pod_priority[row]),
            enc.dic.string(int(enc.pod_ns[row])),
            _canon_labels(enc, enc.pod_label_keys[row],
                          enc.pod_label_vals[row]),
        )
    aff: Dict[tuple, tuple] = {}
    idx = enc.aff
    for sig, row in idx._sig_row.items():
        if idx._row_total[row] <= 0:
            continue
        slot = int(idx.aff_slot[row])
        # invert the compact-domain map so counts key on label VALUES
        inv = {i: v for v, i in enc.topo_value_maps[slot].items()}
        counts = tuple(sorted(
            (inv.get(d, f"#{d}"), float(c))
            for d, c in enumerate(idx.aff_counts[row].tolist()) if c != 0.0
        ))
        # sig already carries (kind, weight, term signature) — pure strings
        aff[sig] = counts
    cache_pods = {
        uid: st.pod.spec.node_name
        for uid, st in cache._pod_states.items()
    }
    return {"cache_pods": cache_pods, "encoder_nodes": nodes,
            "encoder_pods": pods, "affinity": aff}


def diff_canonical(live: Dict[str, dict],
                   scratch: Dict[str, dict]) -> Dict[str, int]:
    """component → number of divergent keys (missing either side, or value
    mismatch); empty dict == no drift."""
    out: Dict[str, int] = {}
    for comp in COMPONENTS:
        a, b = live.get(comp, {}), scratch.get(comp, {})
        n = sum(1 for k in set(a) | set(b) if a.get(k) != b.get(k))
        if n:
            out[comp] = n
    return out


@dataclass
class DriftReport:
    divergent: Dict[str, int] = field(default_factory=dict)  # pre-repair
    unrepaired: Dict[str, int] = field(default_factory=dict)  # post-repair
    repaired: bool = False  # a repair pass ran
    # a small sample of divergent keys per component, for the log line
    samples: Dict[str, List[str]] = field(default_factory=dict)

    @property
    def total(self) -> int:
        return sum(self.divergent.values())

    @property
    def clean(self) -> bool:
        return not self.divergent

    @property
    def converged(self) -> bool:
        """No divergence survived (either none found, or repair erased it)."""
        return not self.unrepaired


def _scratch_build(store, assumed_pods) -> Tuple[Cache, Snapshot,
                                                 ClusterEncoder]:
    """From-scratch rebuild of cache + snapshot + encoder from the store,
    overlaid with the live scheduler's assumed pods (copies carrying their
    assumed node) — what a fresh replica plus the in-flight assumes would
    build."""
    cache = Cache()
    nodes, _ = store.list("Node")
    for n in nodes:
        cache.add_node(n)
    pods, _ = store.list("Pod")
    seen = set()
    for p in pods:
        if p.spec.node_name:
            cache.add_pod(p)
            seen.add(p.uid)
    for p in assumed_pods:
        if p.uid not in seen and p.spec.node_name:
            cache.add_pod(p)
    snap = Snapshot()
    changed = cache.update_snapshot(snap)
    enc = ClusterEncoder()
    enc.sync(snap, changed)
    return cache, snap, enc


class _ScratchView:
    """Duck-typed scheduler facade so canonical_state serves both sides."""

    def __init__(self, cache: Cache, encoder: ClusterEncoder):
        self.cache = cache
        self.encoder = encoder


class DriftDetector:
    """Periodic (and on-recovery) diff of the live scheduler state against a
    from-scratch store rebuild, with repair on divergence.

    Precondition: the scheduler must be QUIESCENT (no in-flight pipelined
    batches) — ``check`` flushes the pipeline like the controller loops do
    and returns None when it will not drain.  Gang Permit holds are fine:
    their assumes overlay the scratch build.
    """

    def __init__(self, scheduler, min_interval: float = 0.0, clock=None):
        self.scheduler = scheduler
        self.min_interval = min_interval
        self.clock = clock or getattr(scheduler, "clock", time.monotonic)
        self._last_check = float("-inf")

    def maybe_check(self, repair: bool = True) -> Optional[DriftReport]:
        """Rate-limited entry for a controller-loop cadence."""
        now = self.clock()
        if now - self._last_check < self.min_interval:
            return None
        report = self.check_and_repair() if repair else self.check()
        if report is not None:
            self._last_check = now
        return report

    def _quiescent(self) -> bool:
        for _ in range(4):
            if not getattr(self.scheduler, "_inflight_q", None):
                return True
            self.scheduler.schedule_cycle()
        return not getattr(self.scheduler, "_inflight_q", None)

    def _diff_now(self) -> Dict[str, int]:
        sched = self.scheduler
        assumed = [sched.cache._pod_states[uid].pod
                   for uid in sched.cache._assumed_pods
                   if uid in sched.cache._pod_states]
        cache, _snap, enc = _scratch_build(sched.store, assumed)
        live = canonical_state(sched)
        scratch = canonical_state(_ScratchView(cache, enc))
        return diff_canonical(live, scratch)

    def check(self) -> Optional[DriftReport]:
        """Detect only; None when the pipeline will not drain."""
        if not self._quiescent():
            return None
        divergent = self._diff_now()
        for comp, n in divergent.items():
            m.state_drift.inc((comp,), by=n)
        if divergent:
            klog.V(1).info_s("Scheduler state drift detected",
                             components=dict(divergent))
        return DriftReport(divergent=divergent, unrepaired=dict(divergent))

    def check_and_repair(self) -> Optional[DriftReport]:
        report = self.check()
        if report is None or report.clean:
            return report
        self.repair()
        report.repaired = True
        report.unrepaired = self._diff_now()
        if report.unrepaired:
            klog.error_s(None, "Scheduler state drift SURVIVED repair",
                         components=dict(report.unrepaired))
        else:
            klog.V(1).info_s("Scheduler state drift repaired",
                             components=dict(report.divergent))
        return report

    def repair(self) -> None:
        """Re-derive the live scheduler's rebuildable state from the store.

        Assumed pods are preserved untouched (they are truth the store does
        not know yet); everything else — cache bound-pod membership, node
        objects, encoder rows, affinity tables — is rebuilt from a relist,
        the same path cold_start takes."""
        sched = self.scheduler
        cache = sched.cache
        store_pods = {p.uid: p for p in sched.store.list("Pod")[0]
                      if p.spec.node_name}
        # bound-pod membership: drop cached pods the store no longer has
        # (assumes excluded), adopt store pods the cache missed or misplaced
        for uid in list(cache._pod_states):
            if uid in cache._assumed_pods:
                continue
            if uid not in store_pods:
                cache.remove_pod(cache._pod_states[uid].pod)
        for uid, p in store_pods.items():
            st = cache._pod_states.get(uid)
            if st is None:
                cache.add_pod(p)
            elif uid not in cache._assumed_pods and \
                    st.pod.spec.node_name != p.spec.node_name:
                cache.update_pod(st.pod, p)
        # nodes: re-add every store node (bumps generations → full
        # re-encode below), drop cache nodes the store no longer has
        store_nodes = {n.metadata.name: n for n in sched.store.list("Node")[0]}
        for n in store_nodes.values():
            cache.add_node(n)
        for name in list(cache._nodes):
            if name not in store_nodes:
                cache.remove_node(name)
        # fresh snapshot + full re-encode; ghost encoder rows dropped first
        sched.snapshot = Snapshot()
        changed = cache.update_snapshot(sched.snapshot)
        enc = sched.encoder
        for name in list(enc.node_rows):
            if name not in sched.snapshot.node_info_map:
                enc.remove_node(name)
        live_uids = {pi.pod.uid
                     for info in sched.snapshot.node_info_list
                     for pi in info.pods}
        for uid in list(enc.pod_rows):
            if uid not in live_uids:
                enc._remove_pod_row(uid)
        enc.sync(sched.snapshot, changed)
        # affinity tables through the existing repair path (parity oracle)
        enc.aff.rebuild(sched.snapshot)
