"""resource.k8s.io API objects: the DRA kind family.

Reference: staging/src/k8s.io/api/resource/v1alpha2 —
  - DeviceClass: admin-curated selector over device attributes (the
    structured-parameters "class" every request names);
  - ResourceSlice: a driver's per-node device inventory publication
    (named devices + attributes: slice, host, chip index, memory);
  - ResourceClaim: a user's request for devices, carrying the allocation
    result (node + named devices) once the scheduler decides;
  - ResourceClaimTemplate: per-pod claim stamping source (the claim
    controller creates one ResourceClaim per referencing pod).

Device identity is ``"<pool>/<device-name>"`` — pool is the ResourceSlice
name, which for TPU inventories is the slice the chips belong to, so an
allocated device string pins (slice, chip) exactly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional

from ..api.objects import ObjectMeta

# claim lifecycle (status.state): Pending → Allocated (devices + node
# written by the scheduler's PreBind) → Reserved (consumed by a running
# pod).  Deallocation returns the claim to Pending with an empty result.
CLAIM_PENDING = "Pending"
CLAIM_ALLOCATED = "Allocated"
CLAIM_RESERVED = "Reserved"

# well-known device attribute keys published by the TPU driver
ATTR_SLICE = "slice"
ATTR_HOST = "host"
ATTR_CHIP_INDEX = "chipIndex"
ATTR_MEMORY = "memoryGiB"


@dataclass
class Device:
    """One named device in a ResourceSlice (resource.k8s.io BasicDevice)."""

    name: str = ""
    attributes: Dict[str, str] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Mapping) -> "Device":
        return cls(
            name=d.get("name", ""),
            attributes={k: str(v) for k, v in (d.get("attributes") or {}).items()},
        )


@dataclass
class DeviceClass:
    """Selector over device attributes; requests name a class, the
    allocator admits only devices whose attributes match every selector
    entry (CEL structured parameters collapsed to equality matching)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selectors: Dict[str, str] = field(default_factory=dict)

    kind = "DeviceClass"

    @property
    def name(self) -> str:
        return self.metadata.name

    def key(self) -> str:
        return self.metadata.name

    def matches(self, device: Device) -> bool:
        return all(
            device.attributes.get(k) == v for k, v in self.selectors.items()
        )

    @classmethod
    def from_dict(cls, d: Mapping) -> "DeviceClass":
        spec = d.get("spec") or {}
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            selectors={
                k: str(v) for k, v in (spec.get("selectors") or {}).items()
            },
        )


@dataclass
class ResourceSlice:
    """A node's published device inventory.  ``pool`` is the TPU slice the
    devices belong to (upstream's pool concept specialized: one pool per
    slice, sliced across its member hosts' ResourceSlices)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    node_name: str = ""
    pool: str = ""
    driver: str = "tpu.kubernetes.io"
    devices: List[Device] = field(default_factory=list)

    kind = "ResourceSlice"

    @property
    def name(self) -> str:
        return self.metadata.name

    def key(self) -> str:
        return self.metadata.name

    @classmethod
    def from_dict(cls, d: Mapping) -> "ResourceSlice":
        spec = d.get("spec") or {}
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            node_name=spec.get("nodeName", ""),
            pool=(spec.get("pool") or {}).get("name", ""),
            driver=spec.get("driver", "tpu.kubernetes.io"),
            devices=[Device.from_dict(x) for x in spec.get("devices") or []],
        )


@dataclass
class DeviceRequest:
    """spec.devices.requests[0] collapsed: one request per claim (the
    exactly-one-request shape every TPU workload uses)."""

    name: str = "devices"
    device_class_name: str = ""
    count: int = 1

    @classmethod
    def from_dict(cls, d: Mapping) -> "DeviceRequest":
        return cls(
            name=d.get("name", "devices"),
            device_class_name=d.get("deviceClassName", ""),
            count=int(d.get("count", 1)),
        )


@dataclass
class ResourceClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    request: DeviceRequest = field(default_factory=DeviceRequest)
    # status.allocation — written atomically by PreBind, cleared on
    # deallocation; devices are "<pool>/<device-name>" strings
    state: str = CLAIM_PENDING
    allocated_node: str = ""
    allocated_devices: List[str] = field(default_factory=list)
    reserved_for: str = ""  # consuming pod uid (status.reservedFor)

    kind = "ResourceClaim"

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @classmethod
    def from_dict(cls, d: Mapping) -> "ResourceClaim":
        spec = d.get("spec") or {}
        reqs = (spec.get("devices") or {}).get("requests") or []
        status = d.get("status") or {}
        alloc = status.get("allocation") or {}
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            request=(DeviceRequest.from_dict(reqs[0]) if reqs
                     else DeviceRequest()),
            state=status.get("state", CLAIM_PENDING),
            allocated_node=alloc.get("nodeName", ""),
            allocated_devices=[str(x) for x in alloc.get("devices") or []],
            reserved_for=status.get("reservedFor", ""),
        )


@dataclass
class ResourceClaimTemplate:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    request: DeviceRequest = field(default_factory=DeviceRequest)

    kind = "ResourceClaimTemplate"

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @classmethod
    def from_dict(cls, d: Mapping) -> "ResourceClaimTemplate":
        spec = (d.get("spec") or {}).get("spec") or {}
        reqs = (spec.get("devices") or {}).get("requests") or []
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            request=(DeviceRequest.from_dict(reqs[0]) if reqs
                     else DeviceRequest()),
        )


def stamped_claim_name(pod_name: str, podclaim_name: str) -> str:
    """Deterministic name for a template-stamped claim: idempotent across
    controller restarts (the reference uses generateName + an owner-ref
    lookup; a deterministic name gives the same exactly-once property
    without a list scan)."""
    return f"{pod_name}-{podclaim_name}"


def pod_claim_names(pod) -> List[Optional[str]]:
    """ResourceClaim object names a pod references, in spec order.
    Template references resolve to the stamped name; a malformed entry
    (neither claim nor template) yields None so callers can fail the pod
    rather than silently skip it."""
    out: List[Optional[str]] = []
    for pc in getattr(pod.spec, "resource_claims", []) or []:
        if pc.resource_claim_name:
            out.append(pc.resource_claim_name)
        elif pc.resource_claim_template_name:
            out.append(stamped_claim_name(pod.metadata.name, pc.name))
        else:
            out.append(None)
    return out
