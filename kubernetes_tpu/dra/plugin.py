"""DynamicResources: ResourceClaim scheduling as a batched tensor program.

Reference: pkg/scheduler/framework/plugins/dynamicresources/ — PreFilter
resolves the pod's claims, Filter rejects nodes that cannot satisfy them,
Reserve allocates in the in-memory assume cache, PreBind writes the
allocation into the claim's status, Unreserve deallocates.

Device design: per-node chip inventory lives in two encoder planes
(``claim_capacity``/``claim_allocated``, projected by dra/index.py), so
Filter is one broadcast compare and Score one arithmetic plane over the
shared DeviceSnapshot — no per-claim host work inside the solve.  The
host side stays authoritative for NAMES: Reserve picks concrete devices
("pool/chip") in the DraIndex assume cache, PreBind persists them with
exactly-once rollback, and the whatif engine releases a victim's chips in
its forks through the same planes (fork.ForkPayload.vic_claim_chips).
"""

from __future__ import annotations

import time
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..chaos.faults import CRASH_MID_CLAIM_COMMIT, maybe_crash
from ..component_base import logging as klog
from ..framework.events import ActionType, ClusterEvent, EventResource
from ..framework.interface import Plugin, Status
from ..metrics import scheduler_metrics as m
from ..sim.store import StaleResourceVersion
from .api import CLAIM_RESERVED, ResourceClaim
from .index import DraIndex, deallocated, pod_has_claims

# store-write retry bound for the claim-status CAS loop (a conflict means
# re-read + re-stamp; anything still conflicting after this is a live
# writer fighting us and the binding cycle should fail and requeue)
_CAS_RETRIES = 8


class DraAux(NamedTuple):
    demand: jnp.ndarray  # i32[B] pending chips the pod's claims need
    pinned: jnp.ndarray  # i32[B] node row an allocated claim pins to; -1 free
    blocked: jnp.ndarray  # bool[B] unresolvable claims (missing/foreign)
    free: jnp.ndarray  # i32[N] free chips (capacity − allocated), scan-carried


class DynamicResourcesPlugin(Plugin):
    name = "DynamicResources"
    dynamic = True

    def __init__(self, index: Optional[DraIndex] = None):
        self.index = index
        # pod uid → [(claim, named devices)] picked at Reserve, consumed at
        # PreBind/Unreserve — the _decisions idiom VolumeBinding pinned
        self._decisions: Dict[str, List[Tuple[ResourceClaim, List[str]]]] = {}

    def events_to_register(self):
        return [
            ClusterEvent(EventResource.RESOURCE_CLAIM, ActionType.ALL),
            ClusterEvent(EventResource.RESOURCE_SLICE, ActionType.ALL),
            ClusterEvent(EventResource.DEVICE_CLASS, ActionType.ALL),
            ClusterEvent(EventResource.NODE, ActionType.ADD),
        ]

    # --- PreFilter (host): resolve claims → per-pod demand/pin/block ---------

    def host_prepare(self, batch, snapshot, encoder, namespace_labels=None):
        if self.index is None:
            return None
        if not any(pod_has_claims(p) for p in batch.pods):
            # claim-free batch (the common case): no aux at all — the traced
            # hooks emit pass-through planes and identity-class dedup stays
            # available (a non-None host aux routes to the full path)
            return None
        b = batch.size
        demand = np.zeros(b, dtype=np.int32)
        pinned = np.full(b, -1, dtype=np.int32)
        blocked = np.zeros(b, dtype=bool)
        rows = encoder.node_rows
        for i, pod in enumerate(batch.pods):
            if not pod_has_claims(pod):
                continue
            dem, pin_node, ok = self.index.resolve(pod)
            if not ok:
                blocked[i] = True
                continue
            demand[i] = dem
            if pin_node is not None:
                row = rows.get(pin_node)
                if row is None:
                    blocked[i] = True  # allocated to a node we can't see
                else:
                    pinned[i] = row
        return {"demand": demand, "pinned": pinned, "blocked": blocked}

    def prepare(self, batch, snap, dyn, host_aux=None):
        if host_aux is None:
            return None
        return DraAux(
            demand=jnp.asarray(host_aux["demand"]),
            pinned=jnp.asarray(host_aux["pinned"]),
            blocked=jnp.asarray(host_aux["blocked"]),
            free=(snap.claim_capacity - snap.claim_allocated).astype(jnp.int32),
        )

    # --- Filter ---------------------------------------------------------------

    def filter(self, batch, snap, dyn, aux: DraAux = None):
        if aux is None:
            return jnp.ones((batch.valid.shape[0], snap.num_nodes), bool)
        cols = jnp.arange(snap.num_nodes)
        fits = aux.free[None, :] >= aux.demand[:, None]
        pin_ok = (aux.pinned[:, None] < 0) | (cols[None, :] == aux.pinned[:, None])
        return fits & pin_ok & ~aux.blocked[:, None]

    def filter_row(self, batch, snap, dyn, aux: DraAux, i):
        if aux is None:
            return jnp.ones(snap.num_nodes, bool)
        cols = jnp.arange(snap.num_nodes)
        fits = aux.free >= aux.demand[i]
        pin_ok = (aux.pinned[i] < 0) | (cols == aux.pinned[i])
        return fits & pin_ok & ~aux.blocked[i]

    # --- Score: tight-pack claims onto already-busy inventory -----------------

    def _score_plane(self, aux: DraAux, demand, snap):
        """Post-placement chip utilization ×100 — claims pack onto the
        fullest satisfying inventory so whole slices stay free for gangs.
        Nodes without inventory (or demand-free pods) score 0."""
        cap = snap.claim_capacity.astype(jnp.float32)
        used_after = cap - aux.free.astype(jnp.float32) + demand
        raw = jnp.floor(used_after * 100.0 / jnp.maximum(cap, 1.0))
        raw = jnp.clip(raw, 0.0, 100.0)
        return jnp.where((demand > 0) & (snap.claim_capacity > 0), raw, 0.0)

    def score(self, batch, snap, dyn, aux: DraAux, mask=None):
        if aux is None:
            return jnp.zeros((batch.valid.shape[0], snap.num_nodes))
        return self._score_plane(aux, aux.demand[:, None].astype(jnp.float32), snap)

    def score_row(self, batch, snap, dyn, aux: DraAux, i, mask_row=None):
        if aux is None:
            return jnp.zeros(snap.num_nodes)
        return self._score_plane(aux, aux.demand[i].astype(jnp.float32), snap)

    def normalize(self, scores, mask):
        return jnp.where(mask, scores, 0.0)  # already 0..MAX_NODE_SCORE

    # --- in-scan / per-round updates (the device assume) ----------------------

    def update(self, aux: DraAux, i, node_row, batch, snap):
        if aux is None:
            return None
        return aux._replace(free=aux.free.at[node_row].add(-aux.demand[i]))

    def update_batch(self, aux: DraAux, commit, choice, u, batch, snap):
        if aux is None:
            return None
        taken = jnp.einsum("bn,b->n", u, aux.demand.astype(jnp.float32))
        return aux._replace(free=aux.free - taken.astype(jnp.int32))

    def update_batch_classes(self, aux: DraAux, u_c, batch, rep_batch, snap,
                             class_of):
        """Exact at class granularity: demand is a pure function of the pod
        SPEC (claim counts), so the rep row's free-plane fold equals the
        full path's.  In practice claim-carrying batches never reach dedup
        (the pod-indexed host aux routes them to the full path); defining
        the hook keeps the dedup router's hook-presence gate satisfied for
        claim-FREE batches, where aux is None and this never runs."""
        if aux is None:
            return None
        taken = jnp.einsum("cn,c->n", u_c, aux.demand.astype(jnp.float32))
        return aux._replace(free=aux.free - taken.astype(jnp.int32))

    # --- Reserve / Unreserve / PreBind (host binding cycle) -------------------

    def reserve(self, state, pod, node_name: str) -> Status:
        """Pick named devices for every pending claim in the DraIndex assume
        cache — all-or-nothing (index.reserve rolls back partial assumes)."""
        if self.index is None or not pod_has_claims(pod):
            return Status.success()
        decisions, reason = self.index.reserve(pod, node_name)
        if reason is not None:
            m.dra_claims_allocated.inc(("conflict",))
            return Status.unschedulable(reason, plugin=self.name)
        if decisions:
            self._decisions[pod.uid] = decisions
        return Status.success()

    def unreserve(self, state, pod, node_name: str) -> None:
        if self.index is None:
            return
        self._decisions.pop(pod.uid, None)
        self.index.unreserve(pod)

    def pre_bind(self, state, pod, node_name: str) -> Status:
        """Persist each claim's allocation (named devices + reservedFor)
        with CAS; a terminal failure mid-pod deallocates the claims already
        written THIS cycle before failing — so a pod's claims land in the
        store all-or-nothing (exactly-once: a crash between writes leaves
        claims the claim controller's repair arm deallocates, and a retry
        of a fully-written pod sees its own allocation and completes)."""
        decisions = self._decisions.pop(pod.uid, [])
        if self.index is None or not decisions:
            return Status.success()
        store = self.index.store
        t0 = time.monotonic()
        written: List[ResourceClaim] = []
        try:
            for claim, devices in decisions:
                ok, fresh, why = self._commit_claim(
                    store, claim, devices, pod, node_name)
                if not ok:
                    self._rollback(store, written)
                    m.dra_claims_allocated.inc(("error",))
                    return Status.error(
                        f"claim {claim.metadata.name}: {why}",
                        plugin=self.name)
                self.index.apply_claim(fresh)
                written.append(fresh)
                m.dra_claims_allocated.inc(("allocated",))
                # kill-point: some of the pod's claims committed, pod never
                # bound — recovery must deallocate them exactly once
                maybe_crash(CRASH_MID_CLAIM_COMMIT)
        finally:
            m.dra_allocation_duration.observe(time.monotonic() - t0)
        self.index.forget_pod(pod)
        return Status.success()

    def _commit_claim(self, store, claim: ResourceClaim, devices: List[str],
                      pod, node_name: str):
        """(ok, fresh claim, reason) — CAS loop with fresh re-reads, so a
        conflict storm (chaos InjectedConflict) retries against the claim
        that actually won, never double-writes."""
        last = "no attempt"
        for _ in range(_CAS_RETRIES):
            fresh = store.get("ResourceClaim", claim.namespace,
                              claim.metadata.name)
            if fresh is None:
                return False, None, "claim deleted mid-bind"
            if fresh.allocated_node:
                # someone's allocation landed — ours (a resent write whose
                # first attempt succeeded, or crash-recovery completing) is
                # success; anyone else's is a lost race
                if (fresh.allocated_node == node_name
                        and fresh.reserved_for == pod.uid):
                    return True, fresh, ""
                return False, None, (
                    f"allocated to {fresh.allocated_node} "
                    f"for {fresh.reserved_for or 'nobody'}")
            if fresh.reserved_for and fresh.reserved_for != pod.uid:
                return False, None, f"reserved for {fresh.reserved_for}"
            fresh.state = CLAIM_RESERVED
            fresh.allocated_node = node_name
            fresh.allocated_devices = list(devices)
            fresh.reserved_for = pod.uid
            try:
                store.update("ResourceClaim", fresh,
                             expected_rv=fresh.metadata.resource_version)
                return True, fresh, ""
            except StaleResourceVersion as e:
                last = str(e)  # injected or real conflict: re-read, retry
            except Exception as e:  # terminal store fault (429/500 unretried)
                klog.V(1).info_s("Claim allocation write failed",
                                 claim=claim.key(), node=node_name,
                                 error=str(e))
                return False, None, str(e)
        return False, None, f"CAS retries exhausted: {last}"

    def _rollback(self, store, written: List[ResourceClaim]) -> None:
        """Deallocate the claims THIS cycle already wrote (reverse order).
        Best-effort CAS: a claim whose rollback write keeps failing stays
        reserved for a pod that will never bind — the claim controller's
        repair arm converges it, preserving exactly-once."""
        for claim in reversed(written):
            for _ in range(_CAS_RETRIES):
                fresh = store.get("ResourceClaim", claim.namespace,
                                  claim.metadata.name)
                if fresh is None or fresh.reserved_for != claim.reserved_for:
                    break  # gone or re-owned: nothing of ours to undo
                bare = deallocated(fresh)
                try:
                    store.update("ResourceClaim", bare,
                                 expected_rv=fresh.metadata.resource_version)
                    self.index.apply_claim(bare)
                    m.dra_claims_allocated.inc(("rollback",))
                    break
                except StaleResourceVersion:
                    continue
                except Exception as e:
                    # terminal rollback failure: the claim controller's
                    # repair arm owns convergence from here
                    klog.V(1).info_s("Claim rollback write failed",
                                     claim=claim.key(), error=str(e))
                    break
