"""DRA index: store-fed device inventory + claim allocation ledger.

The scheduling-path analog of the reference DynamicResources plugin's
claim/slice listers plus its in-flight assume cache
(pkg/scheduler/framework/plugins/dynamicresources): it tracks

  - DeviceClass selectors and per-node ResourceSlice inventories,
  - claim allocation state from the store (authoritative), and
  - in-flight Reserve assumptions not yet written back (released by
    Unreserve, superseded by the PreBind store write),

and projects the per-node chip totals into the encoder's
``claim_capacity``/``claim_allocated`` planes (state/encoding.py) so
Filter/Score run device-resident.  Event-driven with a store fallback:
watch drops under chaos never desynchronize the ledger because PreBind
applies its own successful writes directly (``apply_claim``), and watch
replays are idempotent keyed diffs.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

from ..sim.store import DELETED
from .api import (
    CLAIM_PENDING,
    DeviceClass,
    ResourceClaim,
    ResourceSlice,
    pod_claim_names,
)


def pod_has_claims(pod) -> bool:
    return bool(getattr(pod.spec, "resource_claims", None))


class DraIndex:
    def __init__(self, store=None):
        self.store = store
        # one lock for ledger + dirty set: writers are the watch thread,
        # the bind phase (reserve/apply), and the dispatch-time flush
        self._lock = threading.RLock()
        self._classes: Dict[str, DeviceClass] = {}
        self._slices: Dict[str, ResourceSlice] = {}  # slice name → obj
        self._node_slices: Dict[str, Set[str]] = {}  # node → slice names
        self._claims: Dict[str, ResourceClaim] = {}  # ns/name → claim
        # store-backed allocations: node → {"pool/device"}; claim key →
        # (node, devices) for the reverse diff on claim update/delete
        self._alloc: Dict[str, Set[str]] = {}
        self._claim_alloc: Dict[str, Tuple[str, List[str]]] = {}
        # in-flight Reserve assumptions: claim key → (node, devices)
        self._assumed: Dict[str, Tuple[str, List[str]]] = {}
        self._assumed_by_pod: Dict[str, List[str]] = {}  # pod uid → keys
        self._dirty: Set[str] = set()  # node names pending an encoder write
        self._primed = False

    # --- store feed ----------------------------------------------------------

    def prime(self) -> None:
        """Initial list (informer-style): called lazily on first flush so
        construction order vs. store population doesn't matter."""
        if self.store is None or self._primed:
            return
        self._primed = True
        for obj in self.store.list("DeviceClass")[0]:
            self.apply_class(obj)
        for obj in self.store.list("ResourceSlice")[0]:
            self.apply_slice(obj)
        for obj in self.store.list("ResourceClaim")[0]:
            self.apply_claim(obj)

    def on_event(self, ev_type: str, obj) -> None:
        kind = getattr(obj, "kind", "")
        with self._lock:  # reentrant: one lock span per delivered event
            if kind == "DeviceClass":
                if ev_type == DELETED:
                    self._classes.pop(obj.metadata.name, None)
                else:
                    self.apply_class(obj)
            elif kind == "ResourceSlice":
                if ev_type == DELETED:
                    self.remove_slice(obj.metadata.name)
                else:
                    self.apply_slice(obj)
            elif kind == "ResourceClaim":
                if ev_type == DELETED:
                    self.remove_claim(obj.key())
                else:
                    self.apply_claim(obj)

    def apply_class(self, dc: DeviceClass) -> None:
        with self._lock:
            self._classes[dc.metadata.name] = dc

    def apply_slice(self, sl: ResourceSlice) -> None:
        with self._lock:
            prev = self._slices.get(sl.metadata.name)
            if prev is not None and prev.node_name != sl.node_name:
                self._node_slices.get(prev.node_name, set()).discard(
                    sl.metadata.name)
                self._dirty.add(prev.node_name)
            self._slices[sl.metadata.name] = sl
            self._node_slices.setdefault(sl.node_name, set()).add(
                sl.metadata.name)
            self._dirty.add(sl.node_name)

    def remove_slice(self, name: str) -> None:
        with self._lock:
            sl = self._slices.pop(name, None)
            if sl is None:
                return
            self._node_slices.get(sl.node_name, set()).discard(name)
            self._dirty.add(sl.node_name)

    def apply_claim(self, claim: ResourceClaim) -> None:
        """Idempotent keyed diff — safe for watch replays AND for PreBind's
        direct apply of its own store write (the path that keeps the ledger
        exact when chaos drops the watch event)."""
        with self._lock:
            key = claim.key()
            self._drop_alloc(key)
            self._claims[key] = claim
            if claim.allocated_devices and claim.allocated_node:
                node = claim.allocated_node
                self._claim_alloc[key] = (node, list(claim.allocated_devices))
                self._alloc.setdefault(node, set()).update(
                    claim.allocated_devices)
                self._dirty.add(node)
            # the authoritative allocation supersedes any in-flight assume
            if key in self._assumed:
                anode, _ = self._assumed.pop(key)
                self._dirty.add(anode)

    def remove_claim(self, key: str) -> None:
        with self._lock:
            self._drop_alloc(key)
            self._claims.pop(key, None)
            if key in self._assumed:
                anode, _ = self._assumed.pop(key)
                self._dirty.add(anode)

    def _drop_alloc(self, key: str) -> None:
        prev = self._claim_alloc.pop(key, None)
        if prev is None:
            return
        node, devices = prev
        held = self._alloc.get(node)
        if held is not None:
            held.difference_update(devices)
        self._dirty.add(node)

    # --- encoder projection --------------------------------------------------

    def note_node(self, name: str) -> None:
        """A node (re)appeared or its encoder row churned: re-project its
        planes on the next flush (encode_node never touches them, and
        remove_node zeroes a freed row)."""
        with self._lock:
            if name in self._node_slices:
                self._dirty.add(name)

    def node_capacity(self, name: str) -> int:
        with self._lock:
            return sum(len(self._slices[s].devices)
                       for s in self._node_slices.get(name, ()))

    def node_allocated(self, name: str) -> int:
        with self._lock:
            held = set(self._alloc.get(name, ()))
            for anode, devs in self._assumed.values():
                if anode == name:
                    held.update(devs)
            return len(held)

    def flush_to_encoder(self, encoder) -> None:
        """Write dirty nodes' (capacity, allocated) into the encoder claim
        planes.  Nodes without a row yet stay dirty and retry next flush."""
        with self._lock:
            self.prime()
            if not self._dirty:
                return
            pending, self._dirty = self._dirty, set()
            for name in pending:
                cap = sum(len(self._slices[s].devices)
                          for s in self._node_slices.get(name, ()))
                held = set(self._alloc.get(name, ()))
                for anode, devs in self._assumed.values():
                    if anode == name:
                        held.update(devs)
                if not encoder.set_claim_row(name, cap, len(held)):
                    self._dirty.add(name)

    # --- claim resolution (host_prepare) -------------------------------------

    def claim_of(self, namespace: str, name: str) -> Optional[ResourceClaim]:
        with self._lock:
            hit = self._claims.get(f"{namespace}/{name}")
        if hit is None and self.store is not None:
            hit = self.store.get("ResourceClaim", namespace, name)
            if hit is not None:
                with self._lock:
                    self.apply_claim(hit)
        return hit

    def resolve(self, pod) -> Tuple[int, Optional[str], bool]:
        """(pending chip demand, pinned node name | None, resolvable).

        Unresolvable (missing claim — template not stamped yet, claim
        reserved by another pod, claims pinned to two different nodes)
        means UnschedulableAndUnresolvable until a claim event requeues."""
        demand = 0
        pinned: Optional[str] = None
        for cname in pod_claim_names(pod):
            if cname is None:
                return 0, None, False
            claim = self.claim_of(pod.namespace, cname)
            if claim is None:
                return 0, None, False
            if claim.reserved_for and claim.reserved_for != pod.uid:
                return 0, None, False
            if claim.allocated_node:
                if pinned is not None and pinned != claim.allocated_node:
                    return 0, None, False
                pinned = claim.allocated_node
            else:
                demand += claim.request.count
        return demand, pinned, True

    def pod_claim_demand(self, pod) -> int:
        """Pending (not-yet-allocated) chip demand — the gang anchor-slice
        resolver: allocated claims already count in ``claim_allocated``, so
        adding them here would double-count against free."""
        demand, _pinned, ok = self.resolve(pod)
        return demand if ok else 0

    def pod_chips(self, pod) -> int:
        """Chips a (bound) pod holds on its node — released by a whatif
        victim fork exactly as a real eviction's deallocation would."""
        total = 0
        node = pod.spec.node_name
        if not node:
            return 0
        for cname in pod_claim_names(pod):
            if cname is None:
                continue
            with self._lock:
                claim = self._claims.get(f"{pod.namespace}/{cname}")
            if claim is not None and claim.allocated_node == node:
                total += len(claim.allocated_devices)
        return total

    # --- named-device selection (Reserve / Unreserve) ------------------------

    def _free_devices(self, node: str, dc: Optional[DeviceClass]) -> List[str]:
        held = set(self._alloc.get(node, ()))
        for anode, devs in self._assumed.values():
            if anode == node:
                held.update(devs)
        out = []
        for sname in sorted(self._node_slices.get(node, ())):
            sl = self._slices[sname]
            for dev in sl.devices:
                if dc is not None and not dc.matches(dev):
                    continue
                full = f"{sl.pool}/{dev.name}"
                if full not in held:
                    out.append(full)
        return out

    def reserve(self, pod, node_name: str):
        """All-or-nothing named-device assume for every claim of ``pod``
        (the AssumePodVolumes discipline): a failure on a later claim rolls
        back the earlier claims' assumes before returning.

        Returns (decisions, None) on success — [(claim, devices)] for the
        claims this pod newly allocates — or (None, reason)."""
        decisions: List[Tuple[ResourceClaim, List[str]]] = []
        taken: List[str] = []
        with self._lock:
            def fail(reason: str):
                for key in taken:
                    anode, _ = self._assumed.pop(key)
                    self._dirty.add(anode)
                by_pod = self._assumed_by_pod.get(pod.uid)
                if by_pod:
                    self._assumed_by_pod[pod.uid] = [
                        k for k in by_pod if k not in taken]
                return None, reason

            for cname in pod_claim_names(pod):
                if cname is None:
                    return fail("malformed resourceClaims entry")
                key = f"{pod.namespace}/{cname}"
                claim = self._claims.get(key)
                if claim is None and self.store is not None:
                    claim = self.store.get(
                        "ResourceClaim", pod.namespace, cname)
                if claim is None:
                    return fail(f"ResourceClaim {cname} not found")
                if claim.reserved_for and claim.reserved_for != pod.uid:
                    return fail(
                        f"claim {cname} reserved for another pod")
                if claim.allocated_node:
                    if claim.allocated_node != node_name:
                        return fail(
                            f"claim {cname} already allocated to "
                            f"{claim.allocated_node}")
                    continue  # idempotent: allocation already persisted
                if key in self._assumed:
                    return fail(f"claim {cname} assumed by another pod")
                dc = self._classes.get(claim.request.device_class_name)
                if dc is None and claim.request.device_class_name:
                    return fail(
                        f"DeviceClass {claim.request.device_class_name} "
                        f"not found")
                free = self._free_devices(node_name, dc)
                if len(free) < claim.request.count:
                    return fail(
                        f"node {node_name}: {len(free)} free devices, "
                        f"claim {cname} needs {claim.request.count}")
                devices = free[:claim.request.count]
                self._assumed[key] = (node_name, devices)
                self._assumed_by_pod.setdefault(pod.uid, []).append(key)
                taken.append(key)
                self._dirty.add(node_name)
                decisions.append((claim, devices))
        return decisions, None

    def unreserve(self, pod) -> None:
        with self._lock:
            for key in self._assumed_by_pod.pop(pod.uid, []):
                hit = self._assumed.pop(key, None)
                if hit is not None:
                    self._dirty.add(hit[0])

    def forget_pod(self, pod) -> None:
        """Drop assume bookkeeping after a successful PreBind (apply_claim
        already superseded the entries; this clears the per-pod list)."""
        with self._lock:
            self._assumed_by_pod.pop(pod.uid, None)

    # --- introspection -------------------------------------------------------

    def allocated_claims(self) -> List[ResourceClaim]:
        with self._lock:
            return [c for c in self._claims.values() if c.allocated_devices]


def deallocated(claim: ResourceClaim) -> ResourceClaim:
    """A copy of ``claim`` with the allocation result cleared (the rollback
    write and the claim controller's repair arm share this shape)."""
    import copy

    out = copy.copy(claim)
    out.state = CLAIM_PENDING
    out.allocated_node = ""
    out.allocated_devices = []
    out.reserved_for = ""
    return out
