"""Dynamic Resource Allocation: named TPU-device claims.

Reference: pkg/scheduler/framework/plugins/dynamicresources and the
resource.k8s.io API group (ResourceClaim / ResourceSlice structured
parameters).  Pods stop requesting devices as a fungible counted resource
and instead reference ResourceClaims that the scheduler resolves to
SPECIFIC named devices (a concrete chip on a concrete host in a concrete
slice) out of per-node ResourceSlice inventories.
"""

from .api import (  # noqa: F401
    CLAIM_ALLOCATED,
    CLAIM_PENDING,
    CLAIM_RESERVED,
    Device,
    DeviceClass,
    DeviceRequest,
    ResourceClaim,
    ResourceClaimTemplate,
    ResourceSlice,
)
from .controller import ResourceClaimController  # noqa: F401
from .index import DraIndex  # noqa: F401
from .plugin import DynamicResourcesPlugin  # noqa: F401
