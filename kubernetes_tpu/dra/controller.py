"""ResourceClaim controller: template stamping + allocation repair.

Reference: pkg/controller/resourceclaim/controller.go — one ResourceClaim
stamped per pod referencing a ResourceClaimTemplate, and stale
reservations cleaned up when the consuming pod is gone.

Exactly-once discipline: stamped names are deterministic
(api.stamped_claim_name), so a re-run after a crash finds the claim it
already created instead of stamping a duplicate; the repair arm
deallocates a claim only when its reserved-for pod can never consume it
(missing, or bound to a DIFFERENT node) — a live unbound pod keeps its
claim untouched, because its PreBind may be mid-flight (the crash window
CRASH_MID_CLAIM_COMMIT leaves exactly this state, and either the retried
binding completes the allocation or this arm returns it to Pending)."""

from __future__ import annotations

import dataclasses

from ..api.objects import ObjectMeta
from ..sim.store import ObjectStore, StaleResourceVersion
from .api import ResourceClaim, stamped_claim_name
from .index import deallocated


class ResourceClaimController:
    def __init__(self, store: ObjectStore, index=None):
        self.store = store
        self.index = index  # optional: a scheduler's DraIndex to keep warm

    def sync_once(self) -> bool:
        changed = False
        pods, _ = self.store.list("Pod")
        claims, _ = self.store.list("ResourceClaim")
        templates = {
            t.key(): t for t in self.store.list("ResourceClaimTemplate")[0]
        }
        claim_keys = {c.key() for c in claims}
        pods_by_uid = {p.uid: p for p in pods}

        # --- stamp claims from templates ------------------------------------
        for pod in pods:
            for pc in getattr(pod.spec, "resource_claims", None) or []:
                if not pc.resource_claim_template_name:
                    continue
                name = stamped_claim_name(pod.metadata.name, pc.name)
                key = f"{pod.namespace}/{name}"
                if key in claim_keys:
                    continue
                tpl = templates.get(
                    f"{pod.namespace}/{pc.resource_claim_template_name}")
                if tpl is None:
                    continue  # template not created yet: next sync
                claim = ResourceClaim(
                    metadata=ObjectMeta(name=name, namespace=pod.namespace),
                    request=dataclasses.replace(tpl.request),
                )
                try:
                    self.store.create("ResourceClaim", claim)
                except ValueError:
                    pass  # a concurrent stamper won: same deterministic name
                claim_keys.add(key)
                if self.index is not None:
                    self.index.apply_claim(claim)
                changed = True

        # --- repair stale reservations --------------------------------------
        for claim in claims:
            if not claim.reserved_for and not claim.allocated_node:
                continue
            pod = pods_by_uid.get(claim.reserved_for) \
                if claim.reserved_for else None
            if pod is not None and (
                    not pod.spec.node_name
                    or pod.spec.node_name == claim.allocated_node):
                continue  # consumer live (bound here or PreBind mid-flight)
            if self._deallocate(claim):
                changed = True
        return changed

    def _deallocate(self, claim: ResourceClaim) -> bool:
        for _ in range(8):
            fresh = self.store.get(
                "ResourceClaim", claim.namespace, claim.metadata.name)
            if fresh is None or (
                    fresh.reserved_for != claim.reserved_for
                    or fresh.allocated_node != claim.allocated_node):
                return False  # re-owned or already repaired: exactly once
            bare = deallocated(fresh)
            try:
                self.store.update(
                    "ResourceClaim", bare,
                    expected_rv=fresh.metadata.resource_version)
            except StaleResourceVersion:
                continue
            if self.index is not None:
                self.index.apply_claim(bare)
            return True
        return False
