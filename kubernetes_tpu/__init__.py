"""kubernetes_tpu — a TPU-native cluster-scheduling framework.

A from-scratch reimplementation of the capability surface of Kubernetes'
kube-scheduler (reference: kubernetes/kubernetes, surveyed in SURVEY.md),
designed TPU-first: the host side (Python) owns API objects, watch/event
ingest, the scheduling queue, profiles/config, preemption, and binding; the
compute side lifts the Scheduling Framework's PreFilter/Filter/Score phases
into batched JAX/XLA programs over dense ``[pods, nodes]`` tensors, with the
hot domain-table ops as one-hot MXU contractions (``ops/``), a parallel
auction assignment engine plus an exact greedy-scan oracle
(``framework/runtime.py``), and ``jax.sharding`` meshes + ICI collectives
for scale (``parallel/``).

Layout (host control plane mirrors reference layers from SURVEY.md §1):
  api/            — object model (v1.Pod, v1.Node, selectors, quantities)
  state/          — dictionary encoding, struct-of-arrays snapshots, cache
  framework/      — batched plugin API + runtime (extension points, events,
                    greedy-scan and auction batch assignment)
  plugins/        — vectorized default plugin set (reference:
                    pkg/scheduler/framework/plugins)
  queueing/       — 3-queue PriorityQueue with event-driven requeue
  ops/            — device kernels: domain segment-sum/gather as einsum
                    contractions (XLA gathers serialize on TPU; measured in
                    tests/test_ops.py)
  parallel/       — device mesh, node-axis sharding, ICI collectives
  config/         — KubeSchedulerConfiguration-compatible componentconfig
  sim/            — in-process apiserver/store + hollow-node simulation
  metrics/        — prometheus-name-compatible metrics
  perf/           — scheduler_perf-style benchmark harness
  controllers/    — control loops (ReplicaSet, Deployment, Job, GC,
                    NodeLifecycle, …)
  client/         — reflector/informer, workqueue, leader election (with
                    fencing tokens), events (bounded-loss recorder)
  component_base/ — feature gates, healthz, readyz, configz, tracing
  chaos/          — seeded fault injection + deterministic crash points
  recovery/       — cold-start reconstruction, drift repair, failover soak
"""

__version__ = "0.2.0"
