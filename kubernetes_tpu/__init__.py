"""kubernetes_tpu — a TPU-native cluster-scheduling framework.

A from-scratch reimplementation of the capability surface of Kubernetes'
kube-scheduler (reference: kubernetes/kubernetes, surveyed in SURVEY.md), designed
TPU-first: the host side (Python, with C++ hot paths) owns API objects, watch/event
ingest, the scheduling queue, profiles/config, preemption, and binding; the compute
side lifts the Scheduling Framework's PreFilter/Filter/Score phases into batched
JAX/XLA programs over dense ``[pods, nodes]`` tensors, with Pallas kernels for top-k
and batch assignment, and ``jax.sharding`` meshes + ICI collectives for scale.

Layout (host control plane mirrors reference layers from SURVEY.md §1):
  api/        — object model (v1.Pod, v1.Node, selectors, quantities)
  state/      — dictionary encoding, struct-of-arrays snapshots, scheduler cache
  framework/  — batched plugin API + runtime (extension points, CycleState, events)
  plugins/    — vectorized default plugin set (reference: pkg/scheduler/framework/plugins)
  queueing/   — 3-queue PriorityQueue with event-driven requeue
  ops/        — device kernels: top-k, assignment, segment-sums (Pallas)
  parallel/   — device mesh, node-axis sharding, ICI collectives
  config/     — KubeSchedulerConfiguration-compatible componentconfig
  sim/        — in-process apiserver/store + hollow-node cluster simulation
  metrics/    — prometheus-name-compatible metrics
  perf/       — scheduler_perf-style benchmark harness
  models/     — the flagship jittable scheduling program (score + assign)
"""

__version__ = "0.1.0"
