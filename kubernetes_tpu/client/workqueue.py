"""Rate-limited work queue (reference: client-go util/workqueue).

Dedup semantics: an item added while queued coalesces; an item added while
being processed is re-queued after Done (the "dirty" set).  Rate limiting is
per-item exponential (5ms·2^failures, capped) like DefaultControllerRateLimiter.
"""

from __future__ import annotations

import heapq
import itertools
import time
from typing import Callable, Dict, Hashable, List, Optional, Set, Tuple


class RateLimitingQueue:
    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._base = base_delay
        self._max = max_delay
        self._queue: List[Hashable] = []
        self._queued: Set[Hashable] = set()
        self._processing: Set[Hashable] = set()
        self._dirty: Set[Hashable] = set()
        self._failures: Dict[Hashable, int] = {}
        self._delayed: List[Tuple[float, int, Hashable]] = []
        self._seq = itertools.count()

    def add(self, item: Hashable) -> None:
        if item in self._processing:
            self._dirty.add(item)
            return
        if item in self._queued:
            return
        self._queued.add(item)
        self._queue.append(item)

    def add_after(self, item: Hashable, delay: float) -> None:
        heapq.heappush(self._delayed, (self._clock() + delay, next(self._seq), item))

    def add_rate_limited(self, item: Hashable) -> None:
        n = self._failures.get(item, 0)
        self._failures[item] = n + 1
        self.add_after(item, min(self._base * (2 ** n), self._max))

    def forget(self, item: Hashable) -> None:
        self._failures.pop(item, None)

    def _drain_delayed(self):
        now = self._clock()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, item = heapq.heappop(self._delayed)
            self.add(item)

    def get(self) -> Optional[Hashable]:
        self._drain_delayed()
        if not self._queue:
            return None
        item = self._queue.pop(0)
        self._queued.discard(item)
        self._processing.add(item)
        return item

    def done(self, item: Hashable) -> None:
        self._processing.discard(item)
        if item in self._dirty:
            self._dirty.discard(item)
            self.add(item)

    def __len__(self) -> int:
        self._drain_delayed()
        return len(self._queue)
