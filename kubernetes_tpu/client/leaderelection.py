"""Leader election on Lease objects.

Reference: client-go tools/leaderelection/leaderelection.go:112-150 — acquire a
Lease by CAS on holderIdentity/renewTime; renew every RetryPeriod; a candidate
steals the lease when renewTime is older than LeaseDuration.  The scheduler
exits when it loses the lease (cmd/kube-scheduler/app/server.go:204-215) —
active/passive replication for the control plane (SURVEY §5 failure detection).

Failure semantics (leaderelection.go:269-287 renew → release):
  - every write CASes on the resourceVersion the lease was READ at, so two
    candidates racing for an expired lease cannot both win (the reference's
    Update conflict path);
  - a renewal that fails — transient store error, CAS conflict, or a
    usurped holderIdentity — RELEASES leadership (on_stopped_leading fires,
    the holder stops acting) and the next tick re-enters the acquire path:
    renewal-failure → release → reacquire, never a crash and never two
    concurrent leaders.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.objects import ObjectMeta
from ..component_base import logging as klog
from ..metrics import scheduler_metrics as m
from ..sim.store import ObjectStore, StaleResourceVersion


@dataclass
class Lease:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    renew_time: float = 0.0
    # incremented on every holder CHANGE (coordination.k8s.io/v1
    # leaseTransitions) — never on a self-renewal.  Doubles as the fencing
    # token: a holder captures it at acquire time and refuses shared-state
    # writes once the stored value moved on (a successor acquired, or
    # chaos.steal_lease usurped) — the classic fencing-token construction.
    lease_transitions: int = 0

    kind = "Lease"


class LeaseLock:
    def __init__(self, store: ObjectStore, namespace: str, name: str):
        self.store = store
        self.namespace = namespace
        self.name = name

    def get(self) -> Optional[Lease]:
        return self.store.get("Lease", self.namespace, self.name)

    def create(self, lease: Lease) -> None:
        lease.metadata.namespace = self.namespace
        lease.metadata.name = self.name
        self.store.create("Lease", lease)

    def update(self, lease: Lease, expected_rv=None) -> None:
        """CAS write: ``expected_rv`` (the rv the lease was read at) makes
        concurrent acquire/renew attempts serialize through the store's
        conflict check instead of last-writer-wins."""
        self.store.update("Lease", lease, expected_rv=expected_rv)


class LeaderElector:
    def __init__(
        self,
        lock: LeaseLock,
        identity: str,
        lease_duration: float = 15.0,
        clock: Callable[[], float] = time.monotonic,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ):
        self.lock = lock
        self.identity = identity
        self.lease_duration = lease_duration
        self.clock = clock
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = False
        self.renew_failures = 0  # consecutive failed acquire/renew ticks
        # fencing token: the lease's transition count captured when THIS
        # identity last acquired/renewed; -1 while not leading
        self.fence_token = -1

    def is_leader(self) -> bool:
        return self._leading

    def check_fence(self) -> bool:
        """Fencing-token check for shared-state writes (the bind fence).

        Reads the LIVE lease and verifies this identity still holds it at
        the SAME transition count as when leadership was captured.  Any
        failure to prove that — lease gone, holder changed, transitions
        bumped (steal_lease), or a store fault mid-read — returns False:
        an unprovable fence is a failed fence, exactly like a failed
        renewal releases leadership."""
        if not self._leading:
            return False
        try:
            lease = self.lock.get()
        except Exception as e:
            klog.V(2).info_s("fence check store read failed",
                             identity=self.identity,
                             error=f"{type(e).__name__}: {e}")
            return False
        return (lease is not None
                and lease.holder_identity == self.identity
                and lease.lease_transitions == self.fence_token)

    def try_acquire_or_renew(self) -> bool:
        """One tick of the acquire/renew loop; returns current leadership.

        Any store failure (transient error, lost CAS race, create collision)
        counts as a renewal failure: leadership is released this tick and
        the acquire path re-runs on the next — the caller's retry cadence is
        the RetryPeriod loop."""
        now = self.clock()
        try:
            leading = self._tick(now)
        except StaleResourceVersion:
            # lost the CAS race: someone else renewed/stole between our read
            # and write — they hold the lease, we certainly don't
            leading = False
        except ValueError:
            # create raced another candidate's create (AlreadyExists)
            leading = False
        # ktpu-analysis: ignore[exception-hygiene] -- the failure IS surfaced: renew_failures increments below and _set_leading(False) flips the leader_election_master_status metric; a log line per failed tick would spam under chaos storms
        except Exception:
            # transient control-plane failure (chaos 429/500, network):
            # we cannot prove the lease is ours — release, reacquire later
            leading = False
        if leading:
            self.renew_failures = 0
        else:
            self.renew_failures += 1
        self._set_leading(leading)
        return leading

    def _tick(self, now: float) -> bool:
        import copy

        from ..chaos.faults import maybe_crash

        lease = self.lock.get()
        if lease is None:
            lease = Lease(
                holder_identity=self.identity,
                lease_duration_seconds=self.lease_duration,
                renew_time=now,
            )
            self.lock.create(lease)
            self.fence_token = lease.lease_transitions
            return True
        # mutate a private copy: in-process stores hand out the LIVE object,
        # and a write that fails (CAS conflict, injected fault) must not
        # leave our half-written holder/renewTime visible to other readers
        rv = lease.metadata.resource_version
        lease = copy.copy(lease)
        lease.metadata = copy.copy(lease.metadata)
        expired = now - lease.renew_time > lease.lease_duration_seconds
        if lease.holder_identity == self.identity:
            lease.renew_time = now
            self.lock.update(lease, expected_rv=rv)
            self.fence_token = lease.lease_transitions
            # process death right after a successful renewal: the worst
            # takeover latency — successors must wait out a FRESH full
            # lease_duration before stealing (recovery-time upper bound)
            maybe_crash("crash.post_lease_renew")
            return True
        if expired:
            lease.holder_identity = self.identity
            lease.renew_time = now
            # holder change = lease transition (fences out the old holder)
            lease.lease_transitions += 1
            self.lock.update(lease, expected_rv=rv)
            self.fence_token = lease.lease_transitions
            return True
        return False

    def _set_leading(self, leading: bool):
        if not leading:
            # a released (or never-held) leadership has no valid fence; the
            # token resets BEFORE on_stopped_leading so the callback's
            # stop-work path (scheduler.abandon_inflight) already sees a
            # fenced-out elector
            self.fence_token = -1
        if leading and not self._leading and self.on_started_leading:
            self.on_started_leading()
        if not leading and self._leading and self.on_stopped_leading:
            self.on_stopped_leading()
        if leading != self._leading:
            m.leader_election_status.set(1.0 if leading else 0.0,
                                         (self.identity,))
        self._leading = leading
