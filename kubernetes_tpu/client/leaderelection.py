"""Leader election on Lease objects.

Reference: client-go tools/leaderelection/leaderelection.go:112-150 — acquire a
Lease by CAS on holderIdentity/renewTime; renew every RetryPeriod; a candidate
steals the lease when renewTime is older than LeaseDuration.  The scheduler
exits when it loses the lease (cmd/kube-scheduler/app/server.go:204-215) —
active/passive replication for the control plane (SURVEY §5 failure detection).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.objects import ObjectMeta
from ..sim.store import ObjectStore


@dataclass
class Lease:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    renew_time: float = 0.0

    kind = "Lease"


class LeaseLock:
    def __init__(self, store: ObjectStore, namespace: str, name: str):
        self.store = store
        self.namespace = namespace
        self.name = name

    def get(self) -> Optional[Lease]:
        return self.store.get("Lease", self.namespace, self.name)

    def create(self, lease: Lease) -> None:
        lease.metadata.namespace = self.namespace
        lease.metadata.name = self.name
        self.store.create("Lease", lease)

    def update(self, lease: Lease) -> None:
        self.store.update("Lease", lease)


class LeaderElector:
    def __init__(
        self,
        lock: LeaseLock,
        identity: str,
        lease_duration: float = 15.0,
        clock: Callable[[], float] = time.monotonic,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ):
        self.lock = lock
        self.identity = identity
        self.lease_duration = lease_duration
        self.clock = clock
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = False

    def is_leader(self) -> bool:
        return self._leading

    def try_acquire_or_renew(self) -> bool:
        """One tick of the acquire/renew loop; returns current leadership."""
        now = self.clock()
        lease = self.lock.get()
        if lease is None:
            lease = Lease(
                holder_identity=self.identity,
                lease_duration_seconds=self.lease_duration,
                renew_time=now,
            )
            self.lock.create(lease)
            self._set_leading(True)
            return True
        expired = now - lease.renew_time > lease.lease_duration_seconds
        if lease.holder_identity == self.identity:
            lease.renew_time = now
            self.lock.update(lease)
            self._set_leading(True)
            return True
        if expired:
            lease.holder_identity = self.identity
            lease.renew_time = now
            self.lock.update(lease)
            self._set_leading(True)
            return True
        self._set_leading(False)
        return False

    def _set_leading(self, leading: bool):
        if leading and not self._leading and self.on_started_leading:
            self.on_started_leading()
        if not leading and self._leading and self.on_stopped_leading:
            self.on_stopped_leading()
        self._leading = leading
