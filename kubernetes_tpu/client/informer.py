"""Reflector + SharedInformer over the sim store.

Reference: client-go tools/cache — Reflector.ListAndWatch (reflector.go:49,254):
LIST returns a consistent snapshot + resourceVersion; WATCH resumes from that rv;
on restart the reflector relists (the stateless-recovery property SURVEY §5
"checkpoint/resume" relies on).  SharedInformer fans one watch out to many
handlers with add/update/delete callbacks and a synced() barrier.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..sim.store import ADDED, DELETED, MODIFIED, ObjectStore, WatchEvent


class Reflector:
    """ListAndWatch one kind into a local store dict."""

    def __init__(self, store: ObjectStore, kind: str):
        self.store = store
        self.kind = kind
        self.items: Dict[Tuple[str, str], object] = {}
        self.last_rv = 0
        self._handlers: List[Callable[[str, object, Optional[object]], None]] = []
        self._unwatch = None
        self._synced = False

    def add_handler(self, fn: Callable[[str, object, Optional[object]], None]):
        """fn(event_type, obj, old_obj)."""
        self._handlers.append(fn)

    def _key(self, obj) -> Tuple[str, str]:
        ns = (
            "" if self.kind in ObjectStore.CLUSTER_SCOPED
            else getattr(obj.metadata, "namespace", "")
        )
        return (ns, obj.metadata.name)

    def run(self):
        """LIST (snapshot + rv), deliver synthetic ADDs, then WATCH from rv."""
        objs, rv = self.store.list(self.kind)
        for o in objs:
            self.items[self._key(o)] = o
            for h in self._handlers:
                h(ADDED, o, None)
        self.last_rv = rv
        try:
            # HTTP stores stream watch BOOKMARKs (rv-only progress marks);
            # consuming them keeps the relist-after-disconnect point fresh
            # even when no object events flow.  In-process stores don't
            # take the kwarg — they have no stream to keep alive.
            self._unwatch = self.store.watch(
                self._on_event, since_rv=rv, on_bookmark=self._on_bookmark)
        except TypeError:
            self._unwatch = self.store.watch(self._on_event, since_rv=rv)
        self._synced = True

    def _on_bookmark(self, rv: int):
        self.last_rv = max(self.last_rv, rv)

    def stop(self):
        if self._unwatch:
            self._unwatch()
            self._unwatch = None

    def has_synced(self) -> bool:
        return self._synced

    def _on_event(self, ev: WatchEvent):
        if ev.kind != self.kind:
            return
        self.last_rv = ev.resource_version
        key = self._key(ev.obj)
        old = self.items.get(key)
        if ev.type == DELETED:
            self.items.pop(key, None)
        else:
            self.items[key] = ev.obj
        for h in self._handlers:
            h(ev.type, ev.obj, old)


class SharedInformer:
    """One reflector, many handlers; exposes a lister over the local cache."""

    def __init__(self, store: ObjectStore, kind: str):
        self.reflector = Reflector(store, kind)

    def add_event_handler(self, on_add=None, on_update=None, on_delete=None):
        def h(ev_type, obj, old):
            if ev_type == ADDED and on_add:
                on_add(obj)
            elif ev_type == MODIFIED and on_update:
                on_update(old, obj)
            elif ev_type == DELETED and on_delete:
                on_delete(obj)

        self.reflector.add_handler(h)

    def run(self):
        self.reflector.run()

    def has_synced(self) -> bool:
        return self.reflector.has_synced()

    def list(self) -> List[object]:
        return list(self.reflector.items.values())

    def get(self, namespace: str, name: str) -> Optional[object]:
        return self.reflector.items.get((namespace, name))


class InformerFactory:
    """SharedInformerFactory: one informer per kind, started together."""

    def __init__(self, store: ObjectStore):
        self.store = store
        self._informers: Dict[str, SharedInformer] = {}

    def informer(self, kind: str) -> SharedInformer:
        if kind not in self._informers:
            self._informers[kind] = SharedInformer(self.store, kind)
        return self._informers[kind]

    def start(self):
        for inf in self._informers.values():
            if not inf.has_synced():
                inf.run()

    def wait_for_cache_sync(self) -> bool:
        return all(i.has_synced() for i in self._informers.values())
