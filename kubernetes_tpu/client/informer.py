"""Reflector + SharedInformer over the sim store.

Reference: client-go tools/cache — Reflector.ListAndWatch (reflector.go:49,254):
LIST returns a consistent snapshot + resourceVersion; WATCH resumes from that rv;
on watch failure the reflector backs off and RELISTS (reflector.go:312 —
watchErrorHandler + the ListAndWatch restart loop), which is the
stateless-recovery property SURVEY §5 "checkpoint/resume" relies on.
SharedInformer fans one watch out to many handlers with add/update/delete
callbacks and a synced() barrier.

Failure handling (the chaos-harness spine):
  - a WATCH that errors, is dropped (chaos watch-stream cut), or ends
    (HTTP timeoutSeconds) routes to ``_on_watch_error`` → full relist with
    jittered exponential backoff, then resubscribe from the fresh rv;
  - an in-band ``ERROR`` WatchEvent (the watch protocol's stream-failure
    marker) relists the same way;
  - the relist DIFFS the fresh snapshot against the local cache and emits
    synthetic ADDED/MODIFIED/DELETED so handlers converge without replaying
    the whole world (DeltaFIFO Replace semantics).  Caveat: in-process
    stores share object identity, so a mutation-in-place during the drop
    window carries no rv change to diff on — the cache is still correct
    (same object), only the notification is elided.
"""

from __future__ import annotations

import inspect
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis import lockcheck
from ..chaos.retry import backoff_delay
from ..component_base import logging as klog
from ..metrics import scheduler_metrics as m
from ..sim.store import ADDED, DELETED, ERROR, MODIFIED, ObjectStore, WatchEvent


class FailoverEndpoints:
    """Store-shaped facade over an ordered set of replica endpoints
    (leader + replication followers, sim/replication.py): a reflector
    pointed at this object survives a replica death by rotating to the
    next endpoint on the next call.

    Rotation triggers ONLY on ConnectionError (which chaos WatchDropped
    subclasses) and OSError — the failure modes that mean "this replica is
    gone", not "this request is wrong".  Everything else passes through
    untouched; above all ``TooOldResourceVersion`` (410): the follower's
    shorter ring legitimately answers 410 below its horizon, and the
    reflector's relist-on-410 against the SAME endpoint is the correct
    recovery — rotating would just hide the compaction.  Each endpoint
    gets one try per call; when all of them refuse, the last error
    propagates (the reflector's backoff loop owns the retry cadence).

    rv-interchangeability is what makes this sound: every endpoint serves
    the same WAL-ordered history, so an rv learned from one replica is
    meaningful at every other (lists rv-gate, bookmarks never overclaim
    the watermark), and a mid-walk rotation cannot teleport the reflector
    into a different timeline."""

    def __init__(self, endpoints: List[object], on_failover=None):
        if not endpoints:
            raise ValueError("FailoverEndpoints needs at least one endpoint")
        self.endpoints = list(endpoints)
        self.on_failover = on_failover
        self.failovers = 0
        self._idx = 0
        self._lock = lockcheck.maybe_wrap(
            threading.Lock(), "FailoverEndpoints._lock")

    @property
    def current(self):
        with self._lock:
            return self.endpoints[self._idx]

    def _call(self, method: str, *args, **kwargs):
        return self._call_fn(method,
                             lambda ep: getattr(ep, method)(*args, **kwargs))

    def _call_fn(self, method: str, fn):
        last_exc: Optional[Exception] = None
        for _ in range(len(self.endpoints)):
            with self._lock:
                idx = self._idx
                ep = self.endpoints[idx]
            try:
                return fn(ep)
            except (ConnectionError, OSError) as e:
                last_exc = e
                with self._lock:
                    if self._idx == idx:  # first failure wins the rotate
                        self._idx = (self._idx + 1) % len(self.endpoints)
                        self.failovers += 1
                klog.V(1).info_s("endpoint failover", method=method,
                                 error=f"{type(e).__name__}: {e}",
                                 failovers=self.failovers)
                if self.on_failover is not None:
                    self.on_failover(ep, e)
        raise last_exc  # every endpoint refused

    def list(self, kind: str):
        return self._call("list", kind)

    def list_page(self, kind: str, limit: int = 0, continue_=None,
                  resource_version=None):
        return self._call("list_page", kind, limit=limit,
                          continue_=continue_,
                          resource_version=resource_version)

    def get(self, kind: str, namespace: str, name: str):
        return self._call("get", kind, namespace, name)

    def watch(self, handler, since_rv: int = 0, **kwargs):
        # the reflector detected stream kwargs on OUR signature (VAR_KEYWORD
        # accepts them all); each endpoint gets only what its own watch
        # takes — mixed fleets (plain store + watch-cache replica) work
        def do(ep):
            try:
                params = inspect.signature(ep.watch).parameters
                var_kw = any(p.kind is inspect.Parameter.VAR_KEYWORD
                             for p in params.values())
            except (TypeError, ValueError):
                params, var_kw = {}, False
            kw = kwargs if var_kw else {
                k: v for k, v in kwargs.items() if k in params}
            return ep.watch(handler, since_rv=since_rv, **kw)

        return self._call_fn("watch", do)


class Reflector:
    """ListAndWatch one kind into a local store dict."""

    def __init__(self, store: ObjectStore, kind: str,
                 relist_backoff_initial: float = 0.05,
                 relist_backoff_max: float = 5.0,
                 sleep=time.sleep, jitter_seed: int = 0,
                 relist_page_size: int = 0,
                 rewatch_on_error: bool = False):
        self.store = store
        self.kind = kind
        self.items: Dict[Tuple[str, str], object] = {}
        self.last_rv = 0
        self._handlers: List[Callable[[str, object, Optional[object]], None]] = []
        self._unwatch = None
        self._synced = False
        self._stopped = False
        self.relists = 0  # successful relists (also informer_relists_total)
        self._backoff_initial = relist_backoff_initial
        self._backoff_max = relist_backoff_max
        self._sleep = sleep
        self._jitter = random.Random(jitter_seed)
        # paginated relists: when > 0 and the store serves rv-consistent
        # pages (sim/watchcache.list_page; HTTPApiClient.list_page), every
        # relist walks limit/continue pages at ONE rv instead of one
        # whole-world LIST — informer_relists_total{kind="paged"} counts
        # them.  An expired continue token (410) surfaces as an exception
        # and the retry loop starts a fresh walk.
        self.relist_page_size = relist_page_size
        # watch-cache resume: on a broken stream, try re-watching from
        # last_rv FIRST (the cache's ring replays the gap — including the
        # very event whose fan-out dropped us) and fall back to a full
        # relist only when the server answers 410 (rv compacted away).
        # Off by default: against a plain store the chaos batteries pin
        # relist-on-drop semantics.
        self.rewatch_on_error = rewatch_on_error
        # True while last_rv's freshness came from a BOOKMARK rather than a
        # delivered event — a resync that starts from such an rv is a
        # relist the bookmark saved (informer_relists_total{kind="bookmark"})
        self._bookmark_fresh = False
        # serializes relists: a drop callback and a stream-end callback from
        # two transports must not diff against the same cache concurrently
        self._relist_lock = lockcheck.maybe_wrap(
            threading.Lock(), f"Reflector[{kind}]._relist_lock")

    def add_handler(self, fn: Callable[[str, object, Optional[object]], None]):
        """fn(event_type, obj, old_obj)."""
        self._handlers.append(fn)

    def _key(self, obj) -> Tuple[str, str]:
        ns = (
            "" if self.kind in ObjectStore.CLUSTER_SCOPED
            else getattr(obj.metadata, "namespace", "")
        )
        return (ns, obj.metadata.name)

    def run(self):
        """LIST (snapshot + rv), deliver synthetic ADDs, then WATCH from rv.

        Holds ``_relist_lock`` around the diff+subscribe, same as the
        error-driven relist path: a watch drop delivered while run()'s
        synthetic ADDs are still flowing would otherwise diff the same
        cache concurrently (found by the lock-discipline static check —
        run() was the one unlocked caller of _apply_relist)."""
        self._stopped = False
        objs, rv = self._list_snapshot()
        with self._relist_lock:
            self._apply_relist(objs, rv)
        self._synced = True

    def _list_snapshot(self, count_paged: bool = False):
        """One consistent (objects, rv) snapshot — paginated when
        ``relist_page_size`` is set and the store serves rv-pinned pages
        (the watch cache / HTTP chunked-list contract), whole-world LIST
        otherwise.  Paged walks keep per-call memory and store work bounded
        at thousands of watchers; the continue token pins every page to the
        first page's rv, so the snapshot cannot tear across writes.

        ``count_paged`` marks this walk as a RELIST for the metric: the
        error-driven relist path sets it (each paged relist then counts
        once under {kind} and once under the "paged" mechanism tag); the
        initial run() sync is not a relist and never counts."""
        list_page = getattr(self.store, "list_page", None)
        if not self.relist_page_size or list_page is None:
            return self.store.list(self.kind)
        objs: List[object] = []
        token = None
        while True:
            page, rv, token = list_page(self.kind,
                                        limit=self.relist_page_size,
                                        continue_=token)
            objs.extend(page)
            if not token:
                break
        if count_paged:
            m.informer_relists.inc(("paged",))
        return objs, rv

    def _apply_relist(self, objs, rv: int):
        """Diff a fresh snapshot against the cache, deliver the synthetic
        events, resubscribe (DeltaFIFO Replace: handlers see only what
        actually changed across the outage window).

        Each key commits to the cache AFTER its handlers ran, so a handler
        that raises leaves the remaining keys undelivered AND uncommitted —
        a later relist rediffs and redelivers them (at-least-once, same as
        the reference's requeue-on-handler-error; handlers here dedup by
        uid).  The handler exception itself propagates, matching live watch
        delivery — it is a handler bug, not a stream failure, and must not
        spin the relist retry loop."""
        new_items = {self._key(o): o for o in objs}
        for key, obj in new_items.items():
            old = self.items.get(key)
            if old is None:
                for h in self._handlers:
                    h(ADDED, obj, None)
                self.items[key] = obj
            elif old is not obj and (
                    old.metadata.resource_version
                    != obj.metadata.resource_version):
                for h in self._handlers:
                    h(MODIFIED, obj, old)
                self.items[key] = obj
        for key, old in list(self.items.items()):
            if key not in new_items:
                for h in self._handlers:
                    h(DELETED, old, old)
                self.items.pop(key, None)
        self.last_rv = rv
        self._subscribe(rv)

    def _subscribe(self, rv: int):
        """WATCH from rv, passing the optional stream kwargs the store's
        watch actually accepts.  Capability detection is by signature, NOT
        by probing with a TypeError-catching call: a TypeError raised
        INSIDE a watch implementation that already registered its callback
        would otherwise double-subscribe the handler (ADVICE round 5)."""
        watch = self.store.watch
        kwargs = {}
        try:
            params = inspect.signature(watch).parameters
            var_kw = any(p.kind is inspect.Parameter.VAR_KEYWORD
                         for p in params.values())
        except (TypeError, ValueError):  # builtins without introspection
            params, var_kw = {}, False
        if "on_bookmark" in params or var_kw:
            # HTTP stores stream watch BOOKMARKs (rv-only progress marks);
            # consuming them keeps the relist-after-disconnect point fresh
            # even when no object events flow
            kwargs["on_bookmark"] = self._on_bookmark
        if "on_error" in params or var_kw:
            kwargs["on_error"] = self._on_watch_error
        self._unwatch = watch(self._on_event, since_rv=rv, **kwargs)

    def _on_bookmark(self, rv: int):
        if rv > self.last_rv:
            # ktpu-analysis: ignore[lock-discipline] -- bookmark delivery is serialized by the store's emit path; the monotonic max() makes a lost race harmless (rv only advances)
            self.last_rv = rv
            # ktpu-analysis: ignore[lock-discipline] -- same single-streamed delivery as last_rv above; the flag only routes metric accounting, a lost race miscounts one series by one
            self._bookmark_fresh = True

    def _on_watch_error(self, exc: Optional[Exception] = None):
        """The watch stream ended.  ``exc`` None means a CLEAN end (the
        HTTP server's timeoutSeconds elapsed): rv continuity is intact, so
        re-watch from last_rv — no O(N) relist, no relist-metric noise.
        Any exception (drop, in-band ERROR, transport failure) means the
        continuity is broken: full relist + resubscribe, with jittered
        exponential backoff between failed attempts.  The FIRST attempt
        runs immediately — the in-process store delivers drops on the
        writer's thread (after releasing its lock — a drop callback that
        ran UNDER the store lock inverted lock order against this relist
        path, found by the runtime lockcheck), so a gratuitous first sleep
        would still stall that writer."""
        if self._stopped:
            return
        # ktpu-analysis: ignore[lock-discipline] -- clears the handle of the stream that ALREADY ended (this callback came from it); taking _relist_lock here would stall the store's writer thread behind a relist in backoff
        self._unwatch = None
        with self._relist_lock:
            if self._stopped:
                return
            if exc is None:
                try:
                    self._subscribe(self.last_rv)
                    self._note_bookmark_resync()
                    self._unwatch_if_stopped()
                    return
                except Exception as e:  # resubscribe failed → full relist
                    klog.V(2).info_s("Re-watch failed; relisting",
                                     kind=self.kind,
                                     error=f"{type(e).__name__}: {e}")
            elif self.rewatch_on_error and self.last_rv > 0:
                # watch-cache resume: the broken stream's gap is replayed
                # from the cache's ring (since_rv semantics recover the
                # very event whose fan-out dropped us) — only a 410
                # (TooOldResourceVersion over HTTP or in-process: events
                # compacted past last_rv) falls through to the full relist
                try:
                    self._subscribe(self.last_rv)
                    self._note_bookmark_resync()
                    self._unwatch_if_stopped()
                    return
                except Exception as e:
                    klog.V(2).info_s("Resume-from-rv failed; relisting",
                                     kind=self.kind, last_rv=self.last_rv,
                                     error=f"{type(e).__name__}: {e}")
            attempt = 0
            while not self._stopped:
                if attempt > 0:
                    self._sleep(backoff_delay(
                        attempt - 1, self._backoff_initial,
                        self._backoff_max, self._jitter))
                # only the LIST retries here — apply/deliver exceptions are
                # handler bugs and propagate (see _apply_relist)
                try:
                    objs, rv = self._list_snapshot(count_paged=True)
                except Exception as e:
                    klog.V(2).info_s("Relist LIST failed; backing off",
                                     kind=self.kind, attempt=attempt,
                                     error=f"{type(e).__name__}: {e}")
                    attempt += 1
                    continue
                self._apply_relist(objs, rv)
                self.relists += 1
                m.informer_relists.inc((self.kind,))
                self._unwatch_if_stopped()
                return

    def _note_bookmark_resync(self):
        """A resync just started from an rv a BOOKMARK advanced: that
        freshness is a relist the bookmark saved — counted as
        informer_relists_total{kind="bookmark"} (the series the watch-cache
        soak asserts grows while true relists stay flat).  Runs under
        _relist_lock (both resubscribe paths hold it)."""
        if self._bookmark_fresh:
            self._bookmark_fresh = False
            m.informer_relists.inc(("bookmark",))

    def _unwatch_if_stopped(self):
        """Close the race where stop() ran while a relist/rewatch was in
        flight: the fresh subscription would otherwise outlive the
        'stopped' reflector forever (the store holds a strong reference)."""
        if self._stopped and self._unwatch:
            self._unwatch()
            self._unwatch = None

    def stop(self):
        self._stopped = True
        if self._unwatch:
            self._unwatch()
            # ktpu-analysis: ignore[lock-discipline] -- stop() must not block behind a relist sleeping in backoff; the stopped flag + _unwatch_if_stopped close the in-flight-resubscribe race instead
            self._unwatch = None

    def has_synced(self) -> bool:
        return self._synced

    def _on_event(self, ev: WatchEvent):
        if ev.type == ERROR:
            # in-band stream-failure marker (watch protocol ERROR event,
            # e.g. 410 Gone): the rv continuity is broken — full relist
            # (the exception argument routes past the clean-end rewatch)
            self._on_watch_error(ConnectionError("in-band watch ERROR event"))
            return
        if ev.kind != self.kind:
            return
        # Live watch delivery is single-streamed (the store emits events in
        # rv order outside its lock) and every relist path first drops the
        # subscription under _relist_lock, so these writes never interleave
        # with a relist's diff — taking the lock here would serialize every
        # store write behind relist backoff sleeps.
        # ktpu-analysis: ignore[lock-discipline] -- single-streamed watch delivery; relists unsubscribe first (see comment)
        self.last_rv = ev.resource_version
        # ktpu-analysis: ignore[lock-discipline] -- single-streamed watch delivery; relists unsubscribe first (see comment)
        self._bookmark_fresh = False
        key = self._key(ev.obj)
        old = self.items.get(key)
        if ev.type == DELETED:
            # ktpu-analysis: ignore[lock-discipline] -- single-streamed watch delivery; relists unsubscribe first (see comment)
            self.items.pop(key, None)
        else:
            # ktpu-analysis: ignore[lock-discipline] -- single-streamed watch delivery; relists unsubscribe first (see comment)
            self.items[key] = ev.obj
        for h in self._handlers:
            h(ev.type, ev.obj, old)


class SharedInformer:
    """One reflector, many handlers; exposes a lister over the local cache."""

    def __init__(self, store: ObjectStore, kind: str):
        self.reflector = Reflector(store, kind)

    def add_event_handler(self, on_add=None, on_update=None, on_delete=None):
        def h(ev_type, obj, old):
            if ev_type == ADDED and on_add:
                on_add(obj)
            elif ev_type == MODIFIED and on_update:
                on_update(old, obj)
            elif ev_type == DELETED and on_delete:
                on_delete(obj)

        self.reflector.add_handler(h)

    def run(self):
        self.reflector.run()

    def has_synced(self) -> bool:
        return self.reflector.has_synced()

    def list(self) -> List[object]:
        return list(self.reflector.items.values())

    def get(self, namespace: str, name: str) -> Optional[object]:
        return self.reflector.items.get((namespace, name))


class InformerFactory:
    """SharedInformerFactory: one informer per kind, started together."""

    def __init__(self, store: ObjectStore):
        self.store = store
        self._informers: Dict[str, SharedInformer] = {}

    def informer(self, kind: str) -> SharedInformer:
        if kind not in self._informers:
            self._informers[kind] = SharedInformer(self.store, kind)
        return self._informers[kind]

    def start(self):
        for inf in self._informers.values():
            if not inf.has_synced():
                inf.run()

    def wait_for_cache_sync(self) -> bool:
        return all(i.has_synced() for i in self._informers.values())

    def stop(self):
        for inf in self._informers.values():
            inf.reflector.stop()
