"""Client machinery (reference L3: staging/src/k8s.io/client-go)."""

from .informer import Reflector, SharedInformer, InformerFactory  # noqa: F401
from .workqueue import RateLimitingQueue  # noqa: F401
from .leaderelection import LeaderElector, LeaseLock  # noqa: F401
from .events import EventRecorder, Event  # noqa: F401
