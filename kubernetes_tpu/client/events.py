"""Event recorder (reference: client-go tools/record + tools/events).

The scheduler emits FailedScheduling/Scheduled events (scheduler.go:386,488);
events are aggregated by (object, reason) with a count, like the reference's
correlator.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from ..api.objects import ObjectMeta
from ..component_base import logging as klog
from ..sim.store import ObjectStore


@dataclass
class Event:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_object: str = ""  # "Kind/namespace/name"
    reason: str = ""
    message: str = ""
    type: str = "Normal"  # or Warning
    count: int = 1
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0

    kind = "Event"


class EventRecorder:
    def __init__(self, store: ObjectStore, source: str = "tpu-scheduler",
                 clock=time.time):
        self.store = store
        self.source = source
        self.clock = clock
        self._index: Dict[Tuple[str, str], Event] = {}

    def eventf(self, obj, event_type: str, reason: str, message: str) -> Event:
        ref = f"{getattr(obj, 'kind', type(obj).__name__)}/{obj.metadata.namespace}/{obj.metadata.name}"
        key = (ref, reason)
        now = self.clock()
        ev = self._index.get(key)
        if ev is not None:
            ev.count += 1
            ev.last_timestamp = now
            ev.message = message
            self._write(self.store.update, ev)
            return ev
        ev = Event(
            involved_object=ref, reason=reason, message=message, type=event_type,
            first_timestamp=now, last_timestamp=now,
        )
        ev.metadata.namespace = obj.metadata.namespace or "default"
        ev.metadata.name = f"{obj.metadata.name}.{int(now * 1e6):x}"
        self._index[key] = ev
        self._write(self.store.create, ev)
        return ev

    @staticmethod
    def _write(op, ev) -> None:
        """Best-effort store write: events are observability, never
        load-bearing — the reference's recorder drops events rather than
        fail the caller (client-go tools/record broadcaster semantics), so
        a flaky control plane must not turn a Scheduled notification into
        a binding-cycle crash.  The local aggregate keeps counting."""
        try:
            op("Event", ev)
        except Exception as e:
            # still best-effort (never fail the caller), but a dropped
            # event is visible at debug verbosity instead of vanishing
            klog.V(2).info_s("event recorder dropped store write",
                             reason=ev.reason, obj=ev.involved_object,
                             err=f"{type(e).__name__}: {e}")

    def events_for(self, obj) -> List[Event]:
        ref = f"{getattr(obj, 'kind', type(obj).__name__)}/{obj.metadata.namespace}/{obj.metadata.name}"
        return [e for (r, _), e in self._index.items() if r == ref]
