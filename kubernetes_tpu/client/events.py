"""Event recorder (reference: client-go tools/record + tools/events).

The scheduler emits FailedScheduling/Scheduled events (scheduler.go:386,488);
events are aggregated by (object, reason) with a count, like the reference's
correlator.

Durability contract: event writes stay best-effort (a flaky control plane
must never turn a Scheduled notification into a binding-cycle crash), but
the loss is BOUNDED instead of silent — a failed store write parks the
event in a retained-retry buffer (cap ``RETAIN_CAP``) that ``flush()``
drains on shutdown (TPUScheduler.close) or whenever the caller asks.  An
event is only counted into ``events_dropped_total`` when it is truly lost:
evicted from a full buffer, or still failing at flush time — so a soak can
assert the loss bound (zero after a clean-shutdown flush against a healthy
store).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from ..api.objects import ObjectMeta
from ..component_base import logging as klog
from ..metrics import scheduler_metrics as m
from ..sim.store import ObjectStore

# retained failed writes beyond this evict oldest-first (each eviction IS a
# drop and counts); keeps a long outage from growing the buffer unboundedly
RETAIN_CAP = 256


@dataclass
class Event:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_object: str = ""  # "Kind/namespace/name"
    reason: str = ""
    message: str = ""
    type: str = "Normal"  # or Warning
    count: int = 1
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0

    kind = "Event"


class EventRecorder:
    def __init__(self, store: ObjectStore, source: str = "tpu-scheduler",
                 clock=time.time):
        self.store = store
        self.source = source
        self.clock = clock
        self._index: Dict[Tuple[str, str], Event] = {}
        # failed store writes retained for flush(): (op name, event).
        # Single-writer by contract (the scheduler thread), like _index.
        self._pending: List[Tuple[str, Event]] = []
        self.dropped = 0  # truly lost events (mirror of the counter)

    def eventf(self, obj, event_type: str, reason: str, message: str) -> Event:
        ref = f"{getattr(obj, 'kind', type(obj).__name__)}/{obj.metadata.namespace}/{obj.metadata.name}"
        key = (ref, reason)
        now = self.clock()
        ev = self._index.get(key)
        if ev is not None:
            ev.count += 1
            ev.last_timestamp = now
            ev.message = message
            self._write("update", ev)
            return ev
        ev = Event(
            involved_object=ref, reason=reason, message=message, type=event_type,
            first_timestamp=now, last_timestamp=now,
        )
        ev.metadata.namespace = obj.metadata.namespace or "default"
        ev.metadata.name = f"{obj.metadata.name}.{int(now * 1e6):x}"
        self._index[key] = ev
        self._write("create", ev)
        return ev

    def _write(self, op: str, ev: Event) -> None:
        """Best-effort store write: events are observability, never
        load-bearing — the reference's recorder drops events rather than
        fail the caller (client-go tools/record broadcaster semantics), so
        a flaky control plane must not turn a Scheduled notification into
        a binding-cycle crash.  A failed write is RETAINED for flush();
        only buffer eviction (and flush-time failure) counts as dropped."""
        try:
            (self.store.create if op == "create" else self.store.update)(
                "Event", ev)
        except Exception as e:
            klog.V(2).info_s("event recorder retained failed store write",
                             reason=ev.reason, obj=ev.involved_object,
                             err=f"{type(e).__name__}: {e}")
            self._pending.append((op, ev))
            while len(self._pending) > RETAIN_CAP:
                old_op, old_ev = self._pending.pop(0)
                self._drop(old_ev, "retain buffer full")

    def _drop(self, ev: Event, why: str) -> None:
        self.dropped += 1
        m.events_dropped.inc()
        klog.V(2).info_s("event dropped", reason=ev.reason,
                         obj=ev.involved_object, why=why)

    def flush(self) -> int:
        """Retry every retained failed write once (the shutdown hook —
        TPUScheduler.close calls this); events that STILL fail are counted
        dropped.  Returns the number of events lost by this flush, so the
        chaos/failover soaks can assert the loss bound."""
        pending, self._pending = self._pending, []
        lost = 0
        for op, ev in pending:
            try:
                if op == "create":
                    # the original create may have half-raced a retry: an
                    # existing object downgrades to an update
                    if self.store.get("Event", ev.metadata.namespace,
                                      ev.metadata.name) is None:
                        self.store.create("Event", ev)
                    else:
                        self.store.update("Event", ev)
                else:
                    self.store.update("Event", ev)
            except Exception as e:
                self._drop(ev, f"flush retry failed: {type(e).__name__}: {e}")
                lost += 1
        return lost

    @property
    def pending_writes(self) -> int:
        """Retained-but-not-yet-lost failed writes (the bounded backlog)."""
        return len(self._pending)

    def events_for(self, obj) -> List[Event]:
        ref = f"{getattr(obj, 'kind', type(obj).__name__)}/{obj.metadata.namespace}/{obj.metadata.name}"
        return [e for (r, _), e in self._index.items() if r == ref]
