"""TPUScheduler: the end-to-end scheduling loop.

Reference: pkg/scheduler/scheduler.go (scheduleOne :496, assume :424, bind :446)
+ pkg/scheduler/eventhandlers.go (addAllEventHandlers :251).  Differences by
design:

  - Batched cycles: instead of one pod per cycle, a whole batch is popped from
    the queue and scheduled by ONE device program (greedy lax.scan with exact
    sequential-assume semantics — framework/runtime.py), removing both the
    one-pod outer loop and the 16-goroutine node fan-out.
  - No adaptive node sampling (scheduler.go:852-872): all nodes are scored
    densely on device; percentageOfNodesToScore is accepted but ignored.
  - Pipelined binding (``pipeline=True``): the reference splits assume
    (synchronous cache write, scheduler.go:571) from the binding cycle (a
    detached goroutine, scheduler.go:623) so store latency never blocks the
    next scheduling cycle.  The device analog: batch N's decisions are
    fetched after its device window, its pods are assumed in the
    cache, batch N+1 is dispatched against a snapshot containing those
    assumes, and only THEN batch N's reserve/permit/bind host work runs —
    overlapping the device window.  A failed bind forgets the assume and
    requeues exactly as the reference's binding-cycle error path
    (scheduler.go:676-689).  Synchronous mode (default) runs both halves
    back-to-back — same results, no overlap.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import plugins as P
from .api import objects as v1
from .framework import events as fwk_events
from .framework.events import ActionType, ClusterEvent, EventResource
from .framework.interface import PluginWithWeight
from .framework.podbatch import PodBatchCompiler
from .framework.runtime import BatchedFramework, initial_dynamic_state
from .component_base import logging as klog
from .metrics import scheduler_metrics as m
from .preemption import Evaluator, candidate_mask_device
from .queueing import PriorityQueue
from .queueing.priority_queue import QueuedPodInfo
from .sim.store import ADDED, DELETED, MODIFIED, ObjectStore, WatchEvent
from .state.cache import Cache, Snapshot
from .state.encoding import ClusterEncoder
from .state.units import pow2_round_up as _pow2

DEFAULT_SCHEDULER_NAME = "default-scheduler"  # apis/config v1.Pod default


def default_plugins(domain_cap: int, listers=None,
                    dra_index=None) -> List[PluginWithWeight]:
    """Default plugin set + weights (apis/config/v1beta3/default_plugins.go:32-51)."""
    from .plugins.volumes import (
        NodeVolumeLimitsPlugin,
        VolumeBindingPlugin,
        VolumeRestrictionsPlugin,
        VolumeZonePlugin,
    )

    from .dra import DynamicResourcesPlugin
    from .gang import CoschedulingPlugin

    PW = PluginWithWeight
    return [
        PW(CoschedulingPlugin(), 1),
        PW(P.NodeUnschedulablePlugin(), 0),
        PW(P.NodeNamePlugin(), 0),
        PW(P.TaintTolerationPlugin(), 3),
        PW(P.NodeAffinityPlugin(), 2),
        PW(P.NodePortsPlugin(), 0),
        PW(P.FitPlugin(), 1),
        PW(VolumeRestrictionsPlugin(), 0),
        PW(NodeVolumeLimitsPlugin(listers), 0),
        PW(VolumeBindingPlugin(listers), 0),
        PW(VolumeZonePlugin(listers), 0),
        PW(DynamicResourcesPlugin(dra_index), 1),
        PW(P.PodTopologySpreadPlugin(domain_cap=domain_cap), 2),
        PW(P.InterPodAffinityPlugin(domain_cap=domain_cap), 2),
        PW(P.BalancedAllocationPlugin(), 1),
        PW(P.ImageLocalityPlugin(), 1),
    ]


class _TransientBindError(Exception):
    """A store/transport fault during the binding cycle (NOT a plugin
    rejection): already rolled back; retriable on a timer via the backoff
    queue — no cluster event is needed to unblock the pod."""


# _run_reserve_and_bind outcome: a holds_on_wait Permit plugin (gang
# Coscheduling) left the pod pending — assume + reserve kept, bind deferred
_PERMIT_WAIT = object()


@dataclass
class _WaitingBind:
    """A binding cycle held open at Permit (gang all-or-nothing hold): the
    pod stays assumed in the cache on ``node_name`` with ``reserved``
    plugins intact; _flush_waiting_binds finishes or rolls it back."""

    qi: QueuedPodInfo
    node_name: str
    fw: object
    reserved: List
    since: float
    # attempt-span context the held binding cycle came from: the
    # permit_wait span emitted at flush time links into that tree
    ctx: object = None


@dataclass
class CycleStats:
    attempted: int = 0
    scheduled: int = 0
    unschedulable: int = 0
    batch_seconds: float = 0.0
    in_flight: int = 0  # pods dispatched to device, decision not yet bound
    # gang members assumed + holding a Permit wait (bind deferred until the
    # gang completes or the wait deadline fires) at cycle end
    waiting: int = 0


def _unpack_diag(bits: np.ndarray, n_filters: int) -> np.ndarray:
    """int32[B] bitmask → bool[B, K] diagnosis bits (see diagnostics() in
    _build_jitted: bit k = filter plugin k leaves the pod a feasible node)."""
    return (
        (bits[:, None].astype(np.int64) >> np.arange(n_filters)[None, :]) & 1
    ).astype(bool)


def _host_aux_take(fw, host_auxes, rows):
    """Row-gather the pod-indexed host auxes for the identity-class rep
    view: a plugin owning a pod-indexed host aux exposes ``host_aux_take``
    (Coscheduling's anchor vector); auxes without the hook pass through —
    the dedup gate only admits None or class-uniform values for them."""
    host_auxes = host_auxes or {}
    out = {}
    for pw in fw.plugins:
        name = pw.plugin.name
        if name not in host_auxes:
            continue
        aux = host_auxes[name]
        fn = getattr(pw.plugin, "host_aux_take", None)
        out[name] = aux if aux is None or fn is None else fn(aux, rows)
    return out


def _num_feasible_nodes(n_all: int) -> int:
    """numFeasibleNodesToFind (scheduler.go:852-872, default
    percentageOfNodesToScore=0): ≤100 nodes are never sampled; above that
    the adaptive percentage 50 − n/125 applies (floor 5%, floor 100
    nodes).  The fused device path scores ALL nodes regardless (the
    documented no-sampling deviation) — this bound only caps the candidate
    list shipped to EXTENDERS per callout, which is exactly the subset the
    reference's extenders ever see: feasibleNodes there IS the sampled
    set, so sending the full tier was paying ~2× the reference's protocol
    bytes per pod for a fidelity the reference doesn't have."""
    if n_all <= 100:
        return n_all
    pct = min(max(50 - n_all // 125, 5), 100)
    return max(100, n_all * pct // 100)


def _pods_block_deep(pods: Sequence[v1.Pod]) -> bool:
    """True when any pod carries state the deep pipeline cannot chain
    between batches: host-port sets and volume bindings live in host-side
    structures updated at assume/bind time.  Topology-spread tables chain
    via the plugins' chain_prev hooks, and — since round 6 — pod
    (anti)affinity state does too (InterPodAffinityPlugin.chain_prev folds
    in-flight placements into the count tables AND carries the in-flight
    batch's own terms for the symmetric block/score effects), so the
    coupled-affinity suites no longer force depth 1.  Resource requests,
    node selectors/affinity, taints and images chain exactly.

    Preemption-CAPABLE pods (priority > 0, policy not Never) also block
    WHEN LIKELY TO PREEMPT: beyond the victim-visibility problem (in-flight
    placements have no snapshot pod entries for the dry-run to evict), a
    same-process A/B (tools/preempt_ab.py, round 5) measured chaining
    preemptor waves at 231/87 pods/s vs 266/265 blocked — extra in-flight
    staleness makes their preemption claims collide, refusing nominated
    fast binds into backoff churn.  The refinement lives in
    TPUScheduler._infos_block_deep: a preemption-capable pod that has never
    failed AND fits the current snapshot somewhere (e.g. MixedChurn's
    priority-10 churn pods on a half-empty cluster) does not block — if it
    does fail anyway, its bind phase defers preemption to the retry, which
    THEN blocks (see _bind_phase)."""
    for p in pods:
        if _pod_blocks_static(p):
            return True
        if (p.spec.priority or 0) > 0 and p.spec.preemption_policy != "Never":
            return True
    return False


def _pod_blocks_static(p: v1.Pod) -> bool:
    """The statically non-chainable constraints, shared by _pods_block_deep
    and TPUScheduler._infos_block_deep so the two predicates cannot drift:
    host ports and volumes.  Topology-spread AND pod-(anti)affinity
    constraints are CHAINABLE (the fused program folds in-flight placements
    into this batch's tables via the plugins' chain_prev hooks); an
    affinity-carrying in-flight batch additionally requires the NEXT batch
    to have affinity content — gated in schedule_cycle, not here."""
    from .gang import POD_GROUP_LABEL
    from .state.node_info import _pod_host_ports

    if _pod_host_ports(p):
        return True
    if getattr(p.spec, "volumes", None):
        return True
    # gang members carry Permit-hold state (assumes that may roll back on a
    # group timeout) the deep chain can neither see nor unwind
    if POD_GROUP_LABEL in p.metadata.labels:
        return True
    return False


def _pod_has_affinity(p: v1.Pod) -> bool:
    """Any ACTUAL pod-(anti)affinity term present — must agree exactly with
    PodBatch.has_affinity (derived from valid term rows, group_present): a
    present-but-EMPTY affinity stanza compiles zero terms, and a mismatch
    here would admit an anti-affinity prev batch to the chain tail while
    _dispatch_batch ships a group-free carry (silently dropping its terms)."""
    aff = p.spec.affinity
    if aff is None:
        return False
    pa, paa = aff.pod_affinity, aff.pod_anti_affinity
    return bool(pa and (pa.required or pa.preferred)) or bool(
        paa and (paa.required or paa.preferred))


@dataclass
class _InFlight:
    """One dispatched batch awaiting fetch/bind (the pipelined binding cycle)."""

    infos: List[QueuedPodInfo]
    batch: object
    dsnap: object
    dyn: object
    auxes: object
    node_row_dev: object  # device array, fetched (blocking) at _complete
    algo_lat: object  # np.ndarray once known, or None → filled at fetch
    t0: float
    cycle: int
    node_names: Optional[List[Optional[str]]] = None  # resolved at _complete
    # row→name map captured at DISPATCH (later encoder.syncs may reuse rows
    # of deleted nodes — a deep-pipelined batch completes after the next
    # dispatch's sync); _complete and the bind-phase preemption path both
    # resolve rows through THIS map
    name_of: Optional[Dict[int, str]] = None
    # True when this batch carries constraints the deep pipeline can't
    # chain (pod (anti)affinity tables, host ports, volumes, preemption
    # capability — spread tables DO chain via chain_prev) — the NEXT batch
    # must then complete this one before dispatching
    interacts: bool = True
    # scheduler's node-delete generation at dispatch: a later delete can
    # free an encoder row the next sync reuses, so deep chaining is only
    # allowed while the generation is unchanged
    node_del_gen: int = -1
    # background fetch of node_row (started at dispatch): the device→host
    # round trip (~100ms on the tunnel) overlaps the next batch's window
    # instead of riding _complete's critical path
    fetch_thread: object = None
    fetched: object = None  # np.ndarray once the thread lands it
    fetched_at: float = 0.0  # clock() when the decision became available
    diag_np: object = None  # prefetched diagnosis bits (bool[B, K])
    profile: str = DEFAULT_SCHEDULER_NAME
    # the framework the batch was dispatched with: _fws may be rebuilt (domain
    # growth) between dispatch and the deferred bind, so the record owns it
    fw: object = None
    diag_dev: object = None  # bool[B, K] per-filter-plugin any-feasible bits
    # speculative preemption candidate mask, dispatched AT DISPATCH TIME when
    # the profile's recent failure rate predicts the bind phase will need it
    # (a failure-heavy cycle otherwise serializes cand dispatch + fetch after
    # the decision fetch — 2 extra full-priced tunnel rounds)
    cand_dev: object = None
    cand_np: object = None  # prefetched by the background thread
    # True when this batch was dispatched deep-chained on in-flight prevs:
    # a failing preemptor in it defers preemption to its retry (the chained
    # deltas hide state the dry-run could neither see nor evict)
    chained: bool = False
    # priority-level table captured at dispatch for the segment-sum
    # candidate mask (the lazy bind-phase call must see the SAME pod set
    # the record's dsnap was built from, not a later sync's)
    cand_levels: object = None
    # batch carries pod-(anti)affinity terms: chainable only under a batch
    # that also builds an InterPodAffinity aux (see schedule_cycle's gate)
    has_aff: bool = False
    # assignment engine this batch ran ("batch" | "scan" | "extender") and
    # the engine round count fetched with the decisions — feeds
    # scheduler_assignment_rounds_total at bind time
    engine: str = "batch"
    rounds_np: object = None
    # async extender walk (see _dispatch_batch): an exception the
    # background round walk died with — re-raised at _complete so the
    # batch routes through the cycle failure handler (requeue, not lost)
    walk_error: object = None
    # span tracing (component_base/trace.py): the attempt root span, its
    # context (the EXPLICIT cross-thread handoff — bg-fetch and the async
    # extender walk parent their spans to it), and the clock stamp where
    # host dispatch work ended (the dispatch/device phase boundary for the
    # per-pod attempt records).  span is None when the tracer is disabled.
    span: object = None
    span_ctx: object = None
    dispatch_end: float = 0.0
    # the legacy utiltrace step trace, carried so log_if_long can cover the
    # WHOLE attempt (dispatch→complete→bind) instead of only the
    # synchronous dispatch slice — the ISSUE-14 bugfix: under
    # pipeline/async_extenders the old dispatch-scoped total was misleading
    trace: object = None
    # XLA backend-compile count at dispatch (utils/compilemon): the micro-
    # bucket policy must not feed compile-stalled attempts into its p99 —
    # a cold shape's first-ever dispatch would otherwise drive the bucket
    # to the floor on one poisoned sample
    compiles0: int = -1


@dataclass
class _SyncAhead:
    """The overlapped snapshot/sync handoff (see _spawn_sync_ahead): one
    background build of the NEXT dispatch's snapshot + deferred-scatter
    payload, running during the just-dispatched batch's device window.
    Explicit-handoff discipline like _bg_fetch and the async extender walk:
    the record carries everything across the thread seam, _complete joins
    the thread before any cache assume, and the next dispatch consumes (or
    discards) the payload."""

    thread: object = None
    dsnap: object = None
    upd: object = None
    # dirty-row sets the background to_device_deferred consumed — folded
    # back via encoder.restore_dirty when the payload is discarded/merged
    consumed: object = None
    # node-delete generation AT CAPTURE (read under the sync lock): a later
    # delete can free an encoder row the dispatch-time top-up reuses, and
    # the prepared payload would then scatter the DEAD node's rows over the
    # new owner — mismatch forces the synchronous fallback
    node_del_gen: int = -1
    dic_len: int = -1
    error: object = None
    # off-critical-path wall of the background sync, carried on the record
    # so the spawned thread never touches self.phase_wall (which the main
    # thread mutates concurrently during the overlap window) — folded into
    # phase_wall["sync_overlap"] by _join_sync_ahead, after the join
    wall: float = 0.0


class TPUScheduler:
    def __init__(
        self,
        store: ObjectStore,
        plugins_factory=default_plugins,
        batch_size: int = 64,
        clock=time.monotonic,
        namespace_labels: Optional[Dict[str, Dict[str, str]]] = None,
        rng_key=None,
        extenders: Optional[List] = None,
        assign_mode: str = "auto",
        coupled_fraction_threshold: float = 0.25,
        pipeline: bool = False,
        profiles: Optional[Dict[str, object]] = None,
        pod_initial_backoff: float = 1.0,
        pod_max_backoff: float = 10.0,
        batch_wait: float = 0.5,
        serialize_extender_callouts: str = "auto",
        async_extenders: object = "auto",
        pipeline_depth: int = 3,
        nominated_fast_bind: bool = True,
        chain_affinity: object = "auto",
        fence=None,
        sharding: object = "auto",
        tracer=None,
        overlap_sync: object = "auto",
        latency_target_ms: Optional[float] = None,
    ):
        """``profiles`` maps schedulerName → plugins factory (domain_cap →
        [PluginWithWeight]); each profile gets its own framework + compiled
        programs while sharing one queue/cache/encoder — profile.Map
        (profile/profile.go:45) with frameworkForPod dispatch
        (scheduler.go:719).  Default: one profile, ``plugins_factory``."""
        if assign_mode not in ("auto", "scan", "batch"):
            raise ValueError(f"unknown assign_mode {assign_mode!r}")
        # pipeline=True defers batch N's reserve/bind host work until after
        # batch N+1 is dispatched (assume feeds the snapshot in between) —
        # the device analog of the reference's async binding goroutine
        # (scheduler.go:623).  Default off: tests and interactive callers get
        # the synchronous contract (schedule_cycle returns with pods bound).
        self.pipeline = pipeline
        # Deep-chain depth (pipeline=True only): how many batches may be in
        # flight at once, the newest D-1 chained on device.  At depth 2 the
        # completing batch's program is only one dispatch old and the fetch
        # join waits a full tunnel round (~130ms/cycle measured at B=512);
        # at depth 3 completions are two dispatches old and join for free.
        # Capped at 3: the fused program carries two PrevBatch delta slots.
        if not 1 <= pipeline_depth <= 3:
            raise ValueError(f"pipeline_depth must be 1..3, got {pipeline_depth}")
        self.pipeline_depth = pipeline_depth
        # Deep-chain (anti)affinity batches (InterPodAffinityPlugin.chain_prev
        # + PrevBatch term-group carry)?  The chain's cross-batch einsums are
        # near-free MXU work on an accelerator but REAL added compute on the
        # CPU backend, where there is no dispatch latency to hide — measured
        # on a 1-core CPU container: the scaled anti suite LOST ~2× chained.
        # "auto" = chain whenever the backend isn't plain CPU; parity tests
        # force True so the accelerator path stays proven either way.
        if chain_affinity == "auto":
            chain_affinity = jax.default_backend() != "cpu"
        self.chain_affinity = bool(chain_affinity)
        # did the most recent batch-engine dispatch take the identity-class
        # dedup path?  Steady-state heuristic for _chain_affinity_now: on a
        # CPU backend, affinity deep-chaining is only worth its compute
        # when the chain lands on the [C]-wide rep tables — which the next
        # batch of a templated workload will, iff the last one did.
        self._last_dedup = False
        # per-profile EMA of the batch failure fraction — drives the
        # speculative candidate-mask dispatch (see _dispatch_batch)
        self._fail_ema: Dict[str, float] = {}
        # per-phase wall accumulators, snapshotted by the perf harness per
        # measured window so suite regressions are attributable to a phase
        # (host_prepare / partition / dispatch / fetch / bind / …)
        self.phase_wall: Dict[str, float] = {
            k: 0.0 for k in ("snapshot", "compile", "host_prepare",
                             "partition", "dispatch", "fetch",
                             "extender_wait", "bind",
                             # queue_wait: _await_backoff_wave hold time —
                             # previously unattributed, silently inflating
                             # whatever the caller measured around the
                             # cycle; sync_overlap: the background
                             # snapshot/sync wall (OFF the critical path —
                             # do not sum it into cycle wall)
                             "queue_wait", "sync_overlap")}
        # Off-critical-path snapshot/sync (round 15): at the end of a
        # pipelined cycle a background thread runs cache.update_snapshot +
        # encoder.sync + the deferred scatter-build for the NEXT dispatch,
        # overlapping the just-dispatched batch's device window (the fetch
        # joins release the GIL; on a tunnel-attached TPU the whole round
        # trip).  _complete joins the thread before any cache assume; the
        # dispatch consumes the payload with a generation-gated top-up
        # (see _take_sync_ahead / _deferred_snapshot).  "auto" = on exactly
        # when the pipeline is: a synchronous scheduler would join the
        # thread immediately after spawning it — pure overhead.
        if overlap_sync == "auto":
            overlap_sync = pipeline
        self.overlap_sync = bool(overlap_sync)
        self._sync_ahead: Optional[_SyncAhead] = None
        # guards the lazily-built extender-callout pool: _ext_pool is
        # reached from the main dispatch path AND from the async walk
        # thread (micro-bucket pipelining can run two walks back to back),
        # and an unguarded double-build would leak a 16-worker pool
        self._ext_pool_lock = threading.Lock()
        self._ext_pool_obj = None
        # Micro-bucket pipelined dispatch (round 15): dedup-eligible
        # constraint-free batches split into pow-2 sub-buckets riding the
        # existing deep-pipeline chain, so a pod's attempt latency tracks
        # the SUB-BUCKET round trip while aggregate throughput rides
        # pipeline depth.  latency_target_ms arms the adaptive policy
        # (_pick_bucket): dispatch at the largest PROFILED tier under
        # target, descending ONE unprofiled tier at a time while every
        # profiled tier overruns — at most O(log batch_size) one-off
        # compiles over the process life, the pow-2 tier-growth
        # discipline.  The perf harness instead profiles every tier
        # pre-window via _forced_bucket bursts, so measured windows stay
        # at zero in-window compiles.  None = off: every cycle pads to
        # batch_size, byte-identical to the round-14 path.
        self.latency_target_ms = latency_target_ms
        self._forced_bucket: Optional[int] = None  # warmup override
        # pad tier → EMA of per-batch max attempt latency (the p99 proxy a
        # batch's near-uniform attempts make exact enough, and conservative:
        # max ≥ p99).  Fed by _bind_phase from compile-clean batches only;
        # _bucket_from_latency picks the largest profiled tier under target.
        self._tier_p99: Dict[int, float] = {}
        self._last_wave_wait = 0.0
        # Span tracer (component_base/trace.py): one span tree per
        # dispatched batch — attempt root, queue_wait, dispatch (snapshot/
        # compile/host_prepare/device_enqueue), device_wait or
        # extender_rounds, complete, bind_phase + per-pod bind spans —
        # with the SpanContext handed EXPLICITLY across the pipeline seams
        # (_InFlight.span_ctx → bg-fetch thread → async extender walk →
        # _complete → bind; never a thread-local).  Defaults to the shared
        # NOOP tracer: every emission site is guarded on tracer.enabled, a
        # constant-false attribute read on the hot path (gated < 1%
        # overhead in tools/bench_trace_overhead.py).  Spans bracket the
        # dispatch/fetch boundaries only — never inside jitted code.
        from .component_base.trace import NOOP_TRACER

        self.tracer = tracer or NOOP_TRACER
        # batch-formation hysteresis: when the active queue holds less than
        # half a batch but a backoff wave (e.g. 256 preemptors nominated
        # together) expires within this window, wait for it — the wave then
        # fills ONE device batch instead of trickling into several
        # fragmented cycles that each pay full tunnel pacing (measured:
        # PreemptionBasic retries averaged 78 pods over 107 cycles)
        self.batch_wait = batch_wait
        self._inflight_q: List[_InFlight] = []  # oldest first, depth ≤ 2
        self._node_del_gen = 0  # bumped on node DELETE (deep-pipeline gate)
        # "scan" = exact greedy-sequential lax.scan; "batch" = round-based
        # parallel prefix commits (framework/runtime.py batch_assign); "auto"
        # uses batch unless the coupled fraction exceeds the threshold
        self.assign_mode = assign_mode
        self.coupled_fraction_threshold = coupled_fraction_threshold
        self.store = store
        self.clock = clock
        self.batch_size = batch_size
        self.cache = Cache(clock=clock)
        self.snapshot = Snapshot()
        self.encoder = ClusterEncoder()
        # Node-axis sharding (parallel/mesh.py): the DeviceSnapshot's node
        # tier partitions across a device mesh, the fused cycle program runs
        # over the sharded arrays (GSPMD inserts the cross-shard reductions
        # — row max/min, argmax/top-k merges, domain scatter-adds — so
        # sharded == unsharded bindings bit-for-bit, pinned in
        # tests/test_sharding_runtime.py), and the incremental scatter/sync
        # path updates shards in place without re-replicating the tier.
        # "auto" mirrors chain_affinity's backend gate: on for multi-device
        # accelerators, off on plain CPU where partitioning one core is pure
        # overhead; "on"/True shards over the largest pow-2 device prefix
        # (tests force this on the virtual CPU mesh), an int shards over
        # the first n (n must be a power of two).
        self.mesh = None
        if sharding == "auto":
            # auto never crashes on an odd topology: the mesh requires a
            # power-of-two device count, so shard over the largest pow-2
            # prefix (6 GPUs -> 4) and stay unsharded on 1.
            n_dev = len(jax.devices())
            n_pow2 = 1 << (n_dev.bit_length() - 1)
            sharding = (n_pow2 if n_pow2 > 1
                        and jax.default_backend() != "cpu" else False)
        if sharding is True or sharding == "on":
            # largest pow-2 device prefix (the mesh requires pow-2): "on"
            # means "shard", not "crash on a 6-GPU host"
            all_dev = jax.devices()
            devices = all_dev[: 1 << (len(all_dev).bit_length() - 1)]
        elif isinstance(sharding, int) and not isinstance(sharding, bool) \
                and sharding > 1:
            devices = jax.devices()[: sharding]
        else:
            devices = None
        if devices:
            from .parallel import node_sharded_mesh

            self.mesh = node_sharded_mesh(devices)
            self.encoder.set_mesh(self.mesh)
        self.namespace_labels = namespace_labels or {}
        self.compiler = PodBatchCompiler(self.encoder, self.namespace_labels)
        from .plugins.volumes import StoreVolumeListers

        listers = StoreVolumeListers(store)
        # DRA ledger: device inventory + claim allocations, projected into
        # the encoder's claim planes right after every sync (see
        # _dispatch_batch_traced) and consumed by the DynamicResources
        # plugin's Reserve/PreBind plus the gang anchor-slice resolver
        from .dra import DraIndex

        self.dra = DraIndex(store)
        if plugins_factory is default_plugins:
            self._plugins_factory = lambda d: default_plugins(
                d, listers, dra_index=self.dra)
        else:
            self._plugins_factory = plugins_factory
        # profile map: schedulerName → plugins factory; every profile gets its
        # own BatchedFramework/jitted programs, all sharing this scheduler's
        # queue/cache/encoder (profile.NewMap, QueueSort shared by contract)
        self.profiles: Dict[str, object] = (
            dict(profiles) if profiles else {DEFAULT_SCHEDULER_NAME: self._plugins_factory}
        )
        self._fws: Dict[str, BatchedFramework] = {}
        self._jitted_by: Dict[str, dict] = {}
        self._fw_domain_cap = -1
        self.rng_key = rng_key
        # build event map from the UNION of all profiles' plugin registrations
        # (scheduler.go:347-362 unions the per-profile maps)
        event_map: Dict[ClusterEvent, Set[str]] = {}
        for factory in self.profiles.values():
            for pw in factory(8):
                for ev in pw.plugin.events_to_register():
                    event_map.setdefault(ev, set()).add(pw.plugin.name)
        # gang runtime (kubernetes_tpu/gang/): one directory shared by every
        # profile's Coscheduling plugin instance; its less-fn IS the
        # Coscheduling QueueSort (group cohesion over PrioritySort), and its
        # group key gives the queue gang-atomic activate/requeue
        from .gang import GangDirectory

        self.gangs = GangDirectory(store, clock=clock)
        self.gangs.attach_claim_resolver(self.dra.pod_claim_demand)
        self.queue = PriorityQueue(
            less=self.gangs.less,
            clock=clock, cluster_event_map=event_map,
            pod_initial_backoff=pod_initial_backoff,
            pod_max_backoff=pod_max_backoff,
            group_key=self.gangs.queue_group_key,
        )
        self.preemption = Evaluator()
        self.extenders = list(extenders or [])
        # DOCUMENTED DEVIATION from the reference's strictly sequential
        # per-pod extender callouts (scheduleOne → findNodesThatPassExtenders,
        # scheduler.go:1035): the round-based path fires all unresolved pods'
        # filter/prioritize callouts concurrently at round start.  A
        # STATEFUL extender tracking its own managed resources would see
        # every request before any accept — it could approve placements the
        # sequential cadence would have rejected (the host-side ledger
        # re-check covers framework resource dims only, not extender-internal
        # state).  "auto" serializes callouts for rounds where any interested
        # extender declares managedResources (the exact case where internal
        # state matters); "always"/"never" force either cadence.
        if serialize_extender_callouts not in ("auto", "always", "never"):
            raise ValueError(
                f"unknown serialize_extender_callouts {serialize_extender_callouts!r}")
        self.serialize_extender_callouts = serialize_extender_callouts
        # Fully async extender callouts (round 12): the whole round walk —
        # worker-thread JSON encode/decode, HTTP callouts, host ledger —
        # runs on a background thread, so batch k's callouts overlap batch
        # k-1's binding cycle and the next cycle's pop/snapshot/compile
        # instead of serializing inside the device cycle.  The walk
        # captures its own copies of the encoder mirrors at dispatch, and
        # _complete joins it before any assume — chained == sync bindings
        # (pinned in tests/test_deep_pipeline.py).  "auto" = on exactly
        # when the pipeline is (a synchronous scheduler would join the
        # thread immediately — pure overhead).
        if async_extenders not in ("auto", True, False):
            raise ValueError(f"unknown async_extenders {async_extenders!r}")
        self.async_extenders = (
            self.pipeline if async_extenders == "auto" else bool(async_extenders))
        # bind a plain preemptor to its nominated node within the failing
        # attempt (see _try_nominated_fast_bind); off = always nominate and
        # requeue, the pre-round-5 cadence
        self.nominated_fast_bind = nominated_fast_bind
        # fencing predicate consulted immediately before every store bind
        # write (LeaderElector.check_fence under leader election): False
        # refuses the bind and rolls the cycle back — a replica whose lease
        # moved on can no longer race the new leader's binding cycles.
        # None (the default, single-replica deployments) costs nothing.
        self.fence = fence
        # does the store's bind_pod accept the span-context handoff kwarg?
        # (the informer's signature-probing idiom: ObjectStore and
        # RetryingStore do, remote facades may not — probe once, not per
        # bind).  Only consulted when the tracer is enabled.
        from .utils import takes_kwarg

        self._bind_takes_trace = takes_kwarg(store.bind_pod, "trace_parent")
        from .framework.waiting_pods import WaitingPodsMap

        self.waiting_pods = WaitingPodsMap(clock=clock)
        self.gangs.bind_runtime(self.waiting_pods)
        # uid → _WaitingBind: binding cycles held open at Permit (gang
        # members keep their assume + reserve until the gang completes or
        # the wait deadline fires — flushed every schedule_cycle)
        self._waiting_binds: Dict[str, "_WaitingBind"] = {}
        # nominator: uid → (node_name, request vector, pod) for pods holding a
        # nominated node across cycles (their reservation is added to the
        # dynamic state so other pods don't steal the spot, and preemption
        # dry-runs see them on their nominated node —
        # RunFilterPluginsWithNominatedPods analog)
        self._nominated: Dict[str, Tuple[str, np.ndarray, v1.Pod]] = {}
        # uid → dispatch seq at which the pod was preemption-FAST-BOUND: its
        # nomination entry stands in for the not-yet-snapshotted assume and
        # is purged by the first dispatch whose update_snapshot sees the
        # bind (seq strictly greater — see _bind_phase / _dispatch_batch)
        self._fastbound_noms: Dict[str, int] = {}
        self._dispatch_seq = 0
        from .client.events import EventRecorder

        # Scheduled / FailedScheduling events through the store-backed
        # recorder (scheduler.go:386,488)
        self.recorder = EventRecorder(store)
        from .descheduler.evictions import EvictionAPI

        # preemption victim deletes flow through the shared eviction gate
        # (descheduler/evictions.py) with override_pdb: the dry-run already
        # minimized PDB violations in ranking, and the reference's
        # preemption may violate budgets as a last resort — the gate
        # records the violation and drains the budget instead of refusing
        self.eviction_api = EvictionAPI(store, recorder=self.recorder,
                                        clock=clock)
        self._unwatch = store.watch(self._on_event)

    # --- event handlers (eventhandlers.go:251+) ------------------------------

    _KIND_RESOURCE = {
        "PersistentVolumeClaim": EventResource.PVC,
        "PersistentVolume": EventResource.PV,
        "StorageClass": EventResource.STORAGE_CLASS,
        "CSINode": EventResource.CSI_NODE,
        "Service": EventResource.SERVICE,
        "ResourceClaim": EventResource.RESOURCE_CLAIM,
        "ResourceSlice": EventResource.RESOURCE_SLICE,
        "DeviceClass": EventResource.DEVICE_CLASS,
    }

    # DRA kinds feed the index before the requeue fires (claim-plane dirt
    # must precede the pods the event unblocks)
    _DRA_KINDS = frozenset(("ResourceClaim", "ResourceSlice", "DeviceClass"))

    # kinds that never unblock scheduling (avoid wildcard requeue storms);
    # a ResourceClaimTemplate only matters once the claim controller stamps
    # a claim from it — THAT create requeues
    _IGNORED_KINDS = {"Lease", "Event", "ReplicaSet", "Deployment", "Job",
                      "StatefulSet", "DaemonSet", "HorizontalPodAutoscaler",
                      "ResourceClaimTemplate"}

    def _on_event(self, ev: WatchEvent):
        if ev.kind == "Node":
            self._on_node_event(ev)
        elif ev.kind == "Pod":
            self._on_pod_event(ev)
        elif ev.kind == "PodGroup":
            # gang directory first (quorum counts read it), then requeue
            # members whose Coscheduling rejection this change may resolve
            self.gangs.on_group_event(ev.type, ev.obj)
            action = {ADDED: ActionType.ADD, MODIFIED: ActionType.UPDATE,
                      DELETED: ActionType.DELETE}.get(ev.type, ActionType.ALL)
            self.queue.move_all_to_active_or_backoff(
                ClusterEvent(EventResource.POD_GROUP, action))
        elif ev.kind in self._DRA_KINDS:
            self.dra.on_event(ev.type, ev.obj)
            action = {ADDED: ActionType.ADD, MODIFIED: ActionType.UPDATE,
                      DELETED: ActionType.DELETE}.get(ev.type, ActionType.ALL)
            self.queue.move_all_to_active_or_backoff(
                ClusterEvent(self._KIND_RESOURCE[ev.kind], action))
        elif ev.kind in self._IGNORED_KINDS:
            return
        else:
            resource = self._KIND_RESOURCE.get(ev.kind, EventResource.WILDCARD)
            action = {ADDED: ActionType.ADD, MODIFIED: ActionType.UPDATE,
                      DELETED: ActionType.DELETE}.get(ev.type, ActionType.ALL)
            if resource == EventResource.WILDCARD:
                action = ActionType.ALL
            self.queue.move_all_to_active_or_backoff(ClusterEvent(resource, action))

    def _node_update_action(self, old: Optional[v1.Node], new: v1.Node) -> ActionType:
        if old is None:
            return ActionType.ADD
        action = ActionType(0)
        if old.status.allocatable != new.status.allocatable:
            action |= ActionType.UPDATE_NODE_ALLOCATABLE
        if old.metadata.labels != new.metadata.labels:
            action |= ActionType.UPDATE_NODE_LABEL
        if old.spec.taints != new.spec.taints or old.spec.unschedulable != new.spec.unschedulable:
            action |= ActionType.UPDATE_NODE_TAINT
        return action or ActionType.UPDATE_NODE_CONDITION

    def _on_node_event(self, ev: WatchEvent):
        node: v1.Node = ev.obj
        self.gangs.invalidate_nodes()  # slice-domain plane is stale
        if ev.type == ADDED:
            self.cache.add_node(node)
            # a (re)added node may land on a freed encoder row whose claim
            # planes were zeroed — re-project its inventory next flush
            self.dra.note_node(node.metadata.name)
            self.queue.move_all_to_active_or_backoff(fwk_events.NODE_ADD)
        elif ev.type == MODIFIED:
            old_info = self.cache._nodes.get(node.metadata.name)
            old = old_info.node if old_info else None
            action = self._node_update_action(old, node)
            self.cache.update_node(node)
            self.queue.move_all_to_active_or_backoff(
                ClusterEvent(EventResource.NODE, action)
            )
        elif ev.type == DELETED:
            # deep-pipeline guard: a delete can free an encoder row that the
            # next sync reuses; an in-flight batch's delta rows would then
            # charge the wrong node (see schedule_cycle's deep gate)
            self._node_del_gen += 1
            self.cache.remove_node(node.metadata.name)
            self.queue.move_all_to_active_or_backoff(fwk_events.NODE_DELETE)

    def _on_pod_event(self, ev: WatchEvent):
        pod: v1.Pod = ev.obj
        assigned = bool(pod.spec.node_name)
        # responsibleForPod (eventhandlers.go:285+, scheduler.go:719): only
        # pods naming one of this scheduler's profiles enter the queue;
        # assigned pods always feed the cache (they occupy resources)
        if not assigned and self._profile_of(pod) not in self.profiles:
            return
        if ev.type == DELETED and pod.uid in self._waiting_binds:
            # a gang member deleted while holding its Permit wait: abort the
            # held binding cycle THROUGH the unreserve chain (reserved
            # plugin state — e.g. VolumeBinding's assumed PVs — must roll
            # back, and the Coscheduling group-failure hook fails the
            # gang's remaining waiters fast instead of timing them out)
            self._cancel_waiting_bind(pod.uid)
        self.gangs.on_pod_event(ev.type, pod, assigned)
        if ev.type == ADDED:
            if assigned:
                self.cache.add_pod(pod)
            else:
                self.queue.add(pod)
        elif ev.type == MODIFIED:
            if assigned:
                if pod.uid in self.cache._pod_states and not self.cache.is_assumed(pod):
                    self.cache.update_pod(pod, pod)
                else:
                    self.cache.add_pod(pod)  # also confirms an assumed pod
                # an assigned-pod change can free/consume resources
                self.queue.move_all_to_active_or_backoff(fwk_events.POD_UPDATE)
            else:
                self.queue.update(pod, pod)
        elif ev.type == DELETED:
            self._nominated.pop(pod.uid, None)
            if assigned or pod.uid in self.cache._pod_states:
                self.cache.remove_pod(pod)
                self.queue.move_all_to_active_or_backoff(fwk_events.POD_DELETE)
            else:
                self.queue.delete(pod)

    def presize(self, n_nodes: int, n_pods: int):
        """Pre-grow the encoder's node/pod tiers (see ClusterEncoder.reserve).

        Mid-run tier growth changes DeviceSnapshot shapes, which recompiles
        the whole prepare/assign program suite (~5-30s each) inside the
        measured window — round 2's profile showed this was most of the
        north-star bench's p99.  Callers that know the run's extent (the perf
        harness, a real deployment's node inventory) call this once up front.
        """
        # n_ids: rough dictionary-size bound (node names + labels + pod
        # names/labels) so the numeric side-table never crosses a pow2 size
        # (= a full fused-program recompile) mid-run
        self.encoder.reserve(
            _pow2(n_nodes, 1), _pow2(n_pods, 1),
            n_ids=16 * n_nodes + 8 * n_pods,
        )
        # fixed scatter buckets: steady cycles fit in 256 rows per group;
        # larger bursts (preemption victim storms) overflow to the full
        # upload inside to_device_deferred instead of growing the bucket.
        # Sized from the LIVE extent, not a 5k-cluster constant: a small
        # cluster's bucket is capped at its own tier (and node tiers ≤1024
        # skip scatter entirely — encoding._SMALL_NODE_TIER — so a 500-node
        # run never pays 5000-node dispatch overhead or scatter machinery).
        self.encoder._scatter_bucket.setdefault(
            "node_valid",
            min(_pow2(n_nodes, 32), max(256, _pow2(self.batch_size, 32))))
        self.encoder._scatter_bucket.setdefault(
            "pod_valid",
            min(_pow2(max(n_pods, 1), 32),
                max(256, _pow2(2 * self.batch_size, 32))))

    # --- framework / jit management ------------------------------------------

    def _profile_of(self, pod: v1.Pod) -> str:
        """frameworkForPod (scheduler.go:719): pod's schedulerName, falling
        back to the default profile name when unset."""
        return pod.spec.scheduler_name or DEFAULT_SCHEDULER_NAME

    @property
    def _chain_affinity_now(self) -> bool:
        """May affinity batches deep-chain RIGHT NOW?  chain_affinity is the
        static backend gate (accelerators: yes — the chain einsums hide
        under dispatch latency); on CPU backends the chain is additionally
        allowed while the workload is deduping (the chain work then rides
        the [C]-wide rep tables — see _run_assignment).  A heuristic miss
        costs only performance, never correctness: the chain itself is
        exact (tests/test_deep_pipeline.py)."""
        return self.chain_affinity or self._last_dedup

    def _framework(self, profile: str = None) -> BatchedFramework:
        profile = profile or next(iter(self.profiles))
        d = self.encoder.domain_cap
        if d != self._fw_domain_cap:
            # domain growth invalidates every profile's compiled programs
            self._fws.clear()
            self._jitted_by.clear()
            self._fw_domain_cap = d
        if profile not in self._fws:
            factory = self.profiles[profile]
            fw = BatchedFramework(factory(d))
            # wire every Coscheduling instance to the shared gang directory
            # (profiles each construct their own plugin objects)
            for pw in fw.plugins:
                attach = getattr(pw.plugin, "attach_gang_directory", None)
                if attach is not None:
                    attach(self.gangs)
            self._fws[profile] = fw
            self._jitted_by[profile] = self._build_jitted(fw)
        return self._fws[profile]

    def _build_jitted(self, fw: BatchedFramework) -> dict:
        from .state.encoding import apply_scatter

        # EVERYTHING fused into one program per cycle: the deferred
        # snapshot row-scatter, the nominated-pod reservations, prepare,
        # and the assignment engine.  Each separate device program on the
        # tunnel-attached TPU pays a ~100ms pacing round, so the eager
        # scatter/upload path tripled cycle latency.  The extender path
        # rides its own fused first round (prepare_packed below).
        def reserve_nominated(dsnap, nom_rows, nom_req):
            dyn = initial_dynamic_state(dsnap)
            rows = jnp.clip(nom_rows, 0, dsnap.requested.shape[0] - 1)
            add = jnp.where((nom_rows >= 0)[:, None], nom_req, 0)
            return dyn._replace(
                requested=dyn.requested.at[rows].add(add.astype(dyn.requested.dtype))
            )

        def apply_prev_delta(dyn, prev):
            # Deep pipeline: a still-in-flight previous batch's resource
            # consumption, applied from ITS device-resident decisions
            # (prev.rows = prev node_row, a future) without any host round
            # trip.  Rows <0 (unscheduled/padding) contribute nothing; a
            # shallow cycle passes all -1 so the same compiled program serves
            # both.  Depth 3 passes TWO prev bundles (the two newest
            # in-flight batches), each applied in turn.
            n = dyn.requested.shape[0]
            rows = jnp.clip(prev.rows, 0, n - 1)
            ok = (prev.rows >= 0)[:, None]
            req = dyn.requested.at[rows].add(
                jnp.where(ok, prev.req, 0).astype(dyn.requested.dtype)
            )
            nz = dyn.non_zero.at[rows].add(
                jnp.where(ok, prev.nz, 0).astype(dyn.non_zero.dtype)
            )
            return dyn._replace(requested=req, non_zero=nz)

        n_filters = len(fw.filter_names)

        def pack_diag(bits, node_row, rounds):
            if n_filters <= 31:
                packed_bits = jnp.sum(
                    bits.astype(jnp.int32)
                    << jnp.arange(n_filters, dtype=jnp.int32)[None, :],
                    axis=1,
                )
                rrow = jnp.full_like(packed_bits, jnp.asarray(rounds, jnp.int32))
                return jnp.stack(
                    [node_row.astype(jnp.int32), packed_bits, rrow])
            return bits  # >31 filter plugins: unpacked legacy shape

        def diagnostics(batch, dsnap, dyn, auxes, node_row, rounds):
            # FitError diagnosis bits in the SAME program (XLA CSEs the
            # filter planes) — the eager fallback paid a ~100ms pacing round
            # per plugin per batch.  The preemption candidate mask
            # deliberately does NOT ride here: its freed-resources einsum
            # contracts the full pod tier (O(B·N·R·P) ≈ 200 TFLOP at
            # 5k-node/16k-pod shapes, ~400ms/cycle) and belongs only on
            # batches that actually have unschedulable pods — computed
            # lazily in _candidate_mask.
            #
            # PACKED with node_row into one [2, B] i32: every separate
            # device→host fetch on the tunnel pays its own ~100ms round, so
            # fetching decisions and diagnosis separately doubled the
            # per-cycle fetch cost (measured in the r4 preemption suite).
            return pack_diag(
                fw.diagnose_bits(batch, dsnap, dyn, auxes), node_row, rounds)

        # gang all-or-nothing: a segment-sum pass over per-pod gang ids
        # withdraws every member of a gang with ANY unplaced member, INSIDE
        # the fused program (a standalone device pass would pay its own
        # ~100ms tunnel round per cycle).  gang_seg all(-1) is a no-op, so
        # gang-free cycles share the same compiled executable.
        from .gang import gang_all_or_nothing

        def fused_greedy(batch, dsnap, upd, nom_rows, nom_req, prevs,
                         host_auxes, order, gang_seg, key):
            dsnap = apply_scatter(dsnap, upd)
            dyn = reserve_nominated(dsnap, nom_rows, nom_req)
            for prev in prevs:  # oldest→newest in-flight carry (≤2 bundles)
                dyn = apply_prev_delta(dyn, prev)
            auxes = fw.prepare(batch, dsnap, dyn, host_auxes)
            for prev in prevs:
                auxes = fw.chain_prev(batch, dsnap, auxes, prev)
            res = fw.greedy_assign(batch, dsnap, dyn, auxes, order, key)
            res = res._replace(
                node_row=gang_all_or_nothing(res.node_row, gang_seg))
            return res, auxes, dsnap, dyn, diagnostics(
                batch, dsnap, dyn, auxes, res.node_row, res.rounds)

        def fused_batch(batch, dsnap, upd, nom_rows, nom_req, prevs,
                        host_auxes, order, gang_seg, coupling, key,
                        classes=None):
            dsnap = apply_scatter(dsnap, upd)
            dyn = reserve_nominated(dsnap, nom_rows, nom_req)
            for prev in prevs:
                dyn = apply_prev_delta(dyn, prev)
            # affinity/spread batches under dedup NEVER materialize the
            # pod-level [B, T, N] aux tables — the whole point of the [C, N]
            # path; the gate guarantees no bind-phase consumer (preemption
            # candidate program) will need them.  Plain dedup batches keep
            # the (cheap, mostly-None) full auxes for the candidate mask.
            # static pytree aux flags — plain Python bools at trace time
            coupled = (getattr(batch, "has_affinity", False)
                       or getattr(batch, "has_spread", False))
            if classes is None or not coupled:
                auxes = fw.prepare(batch, dsnap, dyn, host_auxes)
                for prev in prevs:
                    auxes = fw.chain_prev(batch, dsnap, auxes, prev)
            else:
                auxes = None
            if classes is None:
                res = fw.batch_assign(batch, dsnap, dyn, auxes, order,
                                      coupling, key)
                res = res._replace(
                    node_row=gang_all_or_nothing(res.node_row, gang_seg))
                return res, auxes, dsnap, dyn, diagnostics(
                    batch, dsnap, dyn, auxes, res.node_row, res.rounds)
            # identity-class dedup (TPUScheduler._dedup_classes gate): the
            # dense planes and the diagnosis bits compute once per
            # exact-content pod class ([C, N] instead of [B, N]) — at 131k
            # nodes this is the difference between 18s and 0.26s of device
            # compute per cycle, bit-for-bit equal (runtime.py
            # _batch_assign_dedup).  Affinity/spread classes carry rep aux
            # state updated per round via update_batch_classes.
            class_of, rep_rows = classes
            rep_batch = batch.take(rep_rows)
            rep_host = _host_aux_take(fw, host_auxes, rep_rows)
            rep_auxes = fw.prepare(rep_batch, dsnap, dyn, rep_host)
            for prev in prevs:
                rep_auxes = fw.chain_prev(rep_batch, dsnap, rep_auxes, prev)
            res = fw.batch_assign(batch, dsnap, dyn, auxes, order, coupling,
                                  key, classes=(class_of, rep_batch,
                                                rep_auxes))
            res = res._replace(
                node_row=gang_all_or_nothing(res.node_row, gang_seg))
            bits = fw.diagnose_bits(rep_batch, dsnap, dyn, rep_auxes)[class_of]
            return res, auxes, dsnap, dyn, pack_diag(
                bits, res.node_row, res.rounds)

        def cand_mask(batch, dsnap, dyn, auxes, levels):
            from .framework.runtime import live_nodes

            static_ok = live_nodes(dsnap)[None, :] & batch.valid[:, None]
            for pw, aux in zip(fw.plugins, auxes):
                if pw.plugin.name in TPUScheduler._STATIC_PLUGINS and hasattr(
                    pw.plugin, "filter"
                ):
                    static_ok = static_ok & pw.plugin.filter(batch, dsnap, dyn, aux)
            return candidate_mask_device(batch, dsnap, dyn, static_ok, levels)

        def prepare_packed(batch, dsnap, upd, nom_rows, nom_req, host_auxes):
            # the extender path's FIRST round fused into one program:
            # deferred snapshot scatter + nominated reservations + prepare +
            # the packed [B, N] feasibility/score plane — the eager
            # to_device + standalone prepare + first compute_packed cost
            # three separate tunnel rounds per batch
            dsnap = apply_scatter(dsnap, upd)
            dyn = reserve_nominated(dsnap, nom_rows, nom_req)
            auxes = fw.prepare(batch, dsnap, dyn, host_auxes)
            return fw.compute_packed(batch, dsnap, dyn, auxes), auxes, dsnap, dyn

        return {
            "prepare_packed": jax.jit(prepare_packed),
            "greedy": jax.jit(fused_greedy),
            "batch": jax.jit(fused_batch),
            "compute_static": jax.jit(fw.compute_static),
            "compute_row": jax.jit(fw.compute_row),
            # round-based extender path: one dense compute + one batched
            # state update per ROUND (was one compute_row device round per
            # POD — ~100ms tunnel pacing × batch size); the packed form
            # fetches mask+scores in ONE tunnel round
            "compute_packed": jax.jit(fw.compute_packed),
            "apply_commits": jax.jit(fw.apply_commits),
            # whole-batch FitError diagnosis for the extender path (whose
            # round programs carry no packed diag plane): ONE fused
            # program + one [B, K] fetch per failing batch — the previous
            # eager per-plugin loop in _diagnose paid one device program
            # per plugin per failing POD (host-sync dataflow finding)
            "diag_bits": jax.jit(fw.diagnose_bits),
            # one device round per FAILING batch (not fused into every cycle:
            # its freed-resources einsum is ~200 TFLOP at 5k/16k shapes)
            "cand": jax.jit(cand_mask),
        }

    # --- the batched scheduling cycle ----------------------------------------

    def schedule_cycle(self) -> CycleStats:
        """One pipelined step.

        Shallow pipeline (pipeline=True, interacting batches): complete the
        in-flight batch (fetch + assume), dispatch the next batch against the
        assumed snapshot, then run the completed batch's binding cycle while
        the new batch computes on device.

        DEEP pipeline (pipeline=True, constraint-free batches): the next
        batch dispatches BEFORE the in-flight batches' decisions are fetched
        — its program consumes each still-in-flight batch's device-resident
        node_row as a resource delta (apply_prev_delta), so the ~100-200ms
        device round-trip of fetch + chained dispatch overlaps the next
        batch's window entirely.  Depth is ``pipeline_depth`` (default 3: up
        to two batches chained; completions are then two dispatches old and
        their fetch join is free); eligibility requires that no chained
        batch carries state the chain can't carry (pod (anti)affinity, host
        ports, volumes, preemption capability — see _pods_block_deep;
        topology-spread tables ARE chained via the plugins' chain_prev
        hooks, and resources via apply_prev_delta).

        Synchronous mode (pipeline=False) dispatches and completes the same
        batch within the call — identical results, no overlap."""
        inflight = self._inflight_q
        stats = CycleStats()

        def merge(s):
            stats.attempted += s.attempted
            stats.scheduled += s.scheduled
            stats.unschedulable += s.unschedulable
            stats.batch_seconds += s.batch_seconds

        if self.batch_wait > 0:
            self._await_backoff_wave()
        infos = self.queue.pop_batch(
            self.batch_size, group_key=lambda qi: self._profile_of(qi.pod)
        )
        # gang PreFilter quorum gate: a member whose group is below
        # minMember can never form the gang — reject HERE, before any
        # batch-compile or solver work is spent on it
        if infos and self.gangs.active:
            infos = self._gang_prefilter(infos, stats)
        next_interacts = self._infos_block_deep(infos) if infos else True
        # Micro-bucket split (round 15): a dedup-eligible constraint-free
        # batch dispatches only its head sub-bucket; the tail goes straight
        # back to the active queue and rides the next cycles' back-to-back
        # chained dispatches — attempt latency then tracks the SUB-BUCKET
        # device round instead of the whole batch's.
        pad = self._pick_bucket(infos, next_interacts)
        if len(infos) > pad:
            self.queue.put_back(infos[pad:])
            infos = infos[:pad]
        # an affinity-carrying in-flight batch can only be chained under a
        # batch that will itself build an InterPodAffinity aux (otherwise
        # the prev batch's anti/score terms would have no tables to land in)
        next_has_aff = any(_pod_has_affinity(qi.pod) for qi in infos)
        # Deep chain tail: the newest run of in-flight batches this dispatch
        # can chain on device (each must be chainable and predate no
        # node delete — a freed encoder row that THIS dispatch's sync reuses
        # would make the in-flight delta rows charge the wrong node).  Depth
        # D keeps up to D-1; a depth-3 steady state completes batches TWO
        # dispatches old, whose programs have long landed — the fetch join
        # costs ~0 instead of a full tunnel round.  Sub-bucketed cycles cap
        # the tail at 1 (completions then one window old): at depth 3 a
        # pod's decision is ~3 bucket-windows from its pop, at depth 2 ~2 —
        # the device stays saturated either way as long as per-cycle host
        # work fits under one bucket window, so the shallower chain is pure
        # latency win at micro-bucket sizes.
        tail = 0
        if bool(infos) and self.pipeline and not self.extenders \
                and not next_interacts:
            limit = 1 if pad < self.batch_size else self.pipeline_depth - 1
            for fl in reversed(inflight):
                if (tail >= limit or fl.interacts
                        or (fl.has_aff and not next_has_aff)
                        or fl.node_del_gen != self._node_del_gen
                        # a carry whose arrays are another pad tier would
                        # compile a fresh delta-slot pytree variant — break
                        # the chain across tier changes instead
                        or fl.batch.size != pad):
                    break
                tail += 1
        # complete (fetch + assume) everything except the chained tail
        completed: List[Tuple[_InFlight, np.ndarray]] = []
        keep = tail
        while len(inflight) > keep:
            fl = inflight.pop(0)
            try:
                completed.append((fl, self._complete(fl)))
            except Exception as e:
                # a completion fault (async extender walk death, device
                # fetch collapse) costs the batch a requeue, not the loop:
                # nothing was assumed — route through the failure handler
                # exactly like a dispatch-time fault
                if fl.span is not None:
                    fl.span.set(error=f"{type(e).__name__}: {e}").finish()
                if fl.trace is not None:
                    fl.trace.step("Completion failed")
                    fl.trace.log_if_long(0.1)
                self._handle_cycle_failure(fl.infos, e)
                stats.attempted += len(fl.infos)

        nxt = None
        if infos:
            prevs = list(inflight[-tail:]) if tail else None
            try:
                nxt = self._dispatch_batch(infos, prevs=prevs,
                                           interacts=next_interacts,
                                           pad=pad)
            except Exception as e:
                # whole-cycle fault (store outage mid-dispatch, extender
                # transport collapse, device error): route through the
                # failure handler — the batch requeues via the existing pod
                # backoff instead of vanishing, and the scheduler loop keeps
                # running (handleSchedulingFailure, schedule_one.go:921)
                self._handle_cycle_failure(infos, e)
                stats.attempted += len(infos)

        for fl, rows in completed:  # binds overlap nxt's device window
            merge(self._bind_phase(fl, rows))

        if nxt is not None:
            if self.pipeline:
                inflight.append(nxt)
            else:
                try:
                    rows = self._complete(nxt)
                except Exception as e:
                    if nxt.span is not None:
                        nxt.span.set(
                            error=f"{type(e).__name__}: {e}").finish()
                    if nxt.trace is not None:
                        nxt.trace.step("Completion failed")
                        nxt.trace.log_if_long(0.1)
                    self._handle_cycle_failure(nxt.infos, e)
                    stats.attempted += len(nxt.infos)
                else:
                    merge(self._bind_phase(nxt, rows))
        # resolve gang Permit holds: released members bind now (the last
        # sibling's permit this cycle allowed them), expired ones roll the
        # whole gang back and requeue it atomically
        ws = self._flush_waiting_binds()
        stats.scheduled += ws.scheduled
        stats.unschedulable += ws.unschedulable
        stats.waiting = len(self._waiting_binds)
        stats.in_flight = sum(len(fl.infos) for fl in inflight)
        self._observe_pending()
        # overlapped sync for the NEXT dispatch: spawned after every cache
        # write of THIS cycle (assumes, bind confirmations, flushes) so the
        # background capture carries them all, leaving only between-cycle
        # external events + the next completes' assumes to the dispatch-time
        # top-up.  Idle cycles spawn nothing — there is no next dispatch to
        # prepare for.
        if self.overlap_sync and (inflight or stats.attempted):
            self._spawn_sync_ahead()
        return stats

    def _gang_prefilter(self, infos: List[QueuedPodInfo],
                        stats: CycleStats) -> List[QueuedPodInfo]:
        """Host PreFilter pass (Coscheduling quorum): rejected members go
        straight to unschedulableQ with the plugin diagnosis — no solver
        work — and requeue on sibling-pod/PodGroup events."""
        keep: List[QueuedPodInfo] = []
        cycle = self.queue.scheduling_cycle()
        for qi in infos:
            st = self.gangs.prefilter(qi.pod)
            if st is None or st.is_success():
                keep.append(qi)
                continue
            qi.unschedulable_plugins = {st.plugin or "Coscheduling"}
            stats.attempted += 1
            stats.unschedulable += 1
            m.schedule_attempts.inc(("unschedulable",))
            self.queue.add_unschedulable(qi, cycle)
            self.recorder.eventf(
                qi.pod, "Warning", "FailedScheduling", st.message())
        return keep

    def _handle_cycle_failure(self, infos: List[QueuedPodInfo],
                              err: Exception) -> None:
        """Failure handler for a batch whose cycle died before producing
        decisions: every pod requeues through the BACKOFF queue (a transient
        error is retriable on a timer — no cluster event will arrive to
        unpark it from unschedulableQ; attempts was already counted by pop,
        so the exponential per-pod backoff applies), so control-plane
        faults cost a retry, not a lost pod."""
        m.scheduler_retries.inc(("cycle_error",), by=len(infos))
        klog.V(1).info_s("Scheduling cycle failed; requeueing batch",
                         error=f"{type(err).__name__}: {err}",
                         pods=len(infos))
        for qi in infos:
            self._requeue_after_failure(qi)

    def _requeue_after_failure(self, qi: QueuedPodInfo) -> None:
        """Requeue one pod after an error path, guarding against the
        deleted-while-in-flight ghost (its DELETE event was consumed while
        the pod was out of the queue).  A store read that itself fails
        requeues anyway — a spurious retry beats a dropped pod."""
        try:
            exists = self.store.get(
                "Pod", qi.pod.namespace, qi.pod.metadata.name) is not None
        except Exception as e:
            klog.V(2).info_s("Ghost probe failed; requeueing anyway",
                             pod=qi.pod.key(),
                             error=f"{type(e).__name__}: {e}")
            exists = True
        if exists:
            self.queue.requeue_after_error(qi)

    def _await_backoff_wave(self) -> None:
        """Hold the cycle briefly while an imminent backoff wave drains into
        the active queue (see batch_wait in __init__).  Engages only when the
        active queue is under half a batch AND backoff pods outnumber it —
        deep-queue workloads (the steady suites) never enter the loop."""
        # REAL-time deadline (not self.clock): under an injected fake clock
        # time.sleep would never advance a clock-based deadline and the loop
        # would spin forever — the wait budget is wall time either way
        t_wave = time.monotonic()
        real_deadline = t_wave + self.batch_wait
        try:
            while True:
                # flush FIRST (next_backoff_expiry applies the debounced
                # event moves + expired backoffs): a just-failed wave sits
                # in pending moves where pending_count can't see it yet
                nxt = self.queue.next_backoff_expiry()
                a, b, _ = self.queue.pending_count()
                # threshold on the EFFECTIVE dispatch size: with the
                # micro-bucket policy engaged, half a full batch_size of
                # active pods can be many sub-buckets' worth — holding
                # them batch_wait (0.5 s) for a backoff wave would blow
                # the very latency target the policy is holding
                eff = self._bucket_from_latency() \
                    if self.latency_target_ms is not None else self.batch_size
                if b == 0 or nxt is None or a >= eff // 2 or a >= b:
                    return
                now = self.clock()
                if time.monotonic() >= real_deadline \
                        or nxt - now > self.batch_wait:
                    return
                time.sleep(min(0.02, max(nxt - now, 0.001)))
        finally:
            # attribute the hold into the queue_wait bucket (and, via
            # _last_wave_wait, onto the next dispatch's queue_wait span):
            # unattributed it silently inflated whatever the caller timed
            # around the cycle — corrupting exactly the per-phase A/B
            # attribution the latency artifacts gate on
            waited = time.monotonic() - t_wave
            if waited > 0.0005:
                self.phase_wall["queue_wait"] += waited
                self._last_wave_wait += waited

    # --- overlapped snapshot/sync (round 15) ---------------------------------

    def _spawn_sync_ahead(self) -> None:
        """Start the off-critical-path snapshot/sync for the NEXT dispatch.

        The cache diff (update_snapshot: generation walk + clone of changed
        NodeInfos) runs HERE, synchronously — it is the cheap half, and
        capturing it on the spawning thread means the background thread
        never reads the live cache, so the watch-event handlers (the only
        concurrent cache writers) need no lock at all.  The expensive half
        — encoder.sync's per-pod re-encode of every changed node plus the
        deferred scatter-build — runs on the thread, during the just-
        dispatched batch's device window: the main thread's fetch joins
        release the GIL there, so on the CPU backend the python sync work
        genuinely overlaps device compute, and on a tunnel-attached TPU it
        overlaps the ~100ms round trips.  Handoff is the _SyncAhead record;
        _complete joins the thread before any cache assume or encoder read,
        and the next dispatch consumes the payload via _take_sync_ahead."""
        if not self.overlap_sync or self._sync_ahead is not None:
            return
        rec = _SyncAhead()
        changed = self.cache.update_snapshot(self.snapshot)
        rec.node_del_gen = self._node_del_gen
        # parent the sync_overlap span to the newest in-flight attempt —
        # the batch whose device window this work overlaps
        ctx = self._inflight_q[-1].span_ctx if self._inflight_q else None
        tracer = self.tracer

        def _run():
            t_s = self.clock()
            span = (tracer.span("sync_overlap", parent=ctx, start=t_s)
                    if tracer.enabled else None)
            try:
                self.encoder.sync(self.snapshot, changed)
                rec.consumed = self.encoder.capture_dirty()
                # consume_force=False: a force_full_next() set while this
                # thread runs (harness warms) must survive untouched for
                # the dispatch-time build — the reuse gate re-checks it
                rec.dsnap, rec.upd = self.encoder.to_device_deferred(
                    consume_force=False)
                rec.dic_len = len(self.encoder.dic)
                if span is not None:
                    span.set(changed=len(changed),
                             payload="scatter" if rec.upd is not None
                             else "full")
            except Exception as e:  # surfaced at the next dispatch → the
                rec.error = e       # cycle failure handler requeues
                klog.V(1).info_s("Overlapped sync failed; next dispatch "
                                 "will requeue its batch",
                                 error=f"{type(e).__name__}: {e}")
                if span is not None:
                    span.set(error=f"{type(e).__name__}: {e}")
            # off-critical-path wall, attributed so the overlap win is
            # measured, not inferred (do NOT sum this into cycle wall);
            # rides the record — phase_wall belongs to the main thread
            done = self.clock()
            rec.wall = done - t_s
            if span is not None:
                span.finish(end=done)

        rec.thread = threading.Thread(target=_run, daemon=True)
        self._sync_ahead = rec
        rec.thread.start()

    def join_sync_ahead(self) -> None:
        """Barrier for EXTERNAL readers of the scheduler's snapshot/encoder
        (descheduler/autoscaler controllers driven between cycles, tests):
        joins any in-flight background sync without consuming its payload.
        Main-thread internal callers use the same join via _join_sync_ahead
        at every encoder/snapshot touch point."""
        self._join_sync_ahead()

    def _join_sync_ahead(self) -> None:
        rec = self._sync_ahead
        if rec is not None and rec.thread is not None:
            rec.thread.join()
            rec.thread = None
            # fold the background wall in here, after the join: the record
            # hands the measurement off like every other _SyncAhead field
            self.phase_wall["sync_overlap"] += rec.wall
            rec.wall = 0.0

    def _take_sync_ahead(self) -> Optional[_SyncAhead]:
        """Join + consume the pending overlapped sync at dispatch time.
        Returns the record (payload valid, possibly needing a merge —
        _deferred_snapshot decides) or None: no sync ran, it failed (the
        error re-raises into the cycle failure handler), or a node DELETE
        landed after the capture — the generation guard — in which case the
        payload is discarded and the dispatch syncs synchronously."""
        self._join_sync_ahead()
        rec, self._sync_ahead = self._sync_ahead, None
        if rec is None:
            return None
        if rec.error is not None:
            # same contract as an inline sync failure: the dispatch dies
            # and the batch requeues through _handle_cycle_failure
            raise rec.error
        if rec.node_del_gen != self._node_del_gen:
            if rec.upd is not None:
                self.encoder.restore_dirty(rec.consumed)
            m.sync_overlap.inc(("fallback_node_delete",))
            return None
        # tracked until _deferred_snapshot consumes it: a dispatch dying
        # between here and there (compile fault, store outage) must fold
        # the payload's rows back or they never reach the device
        self._unconsumed_prep = rec
        return rec

    def _discard_prep(self) -> None:
        """Failure-path cleanup for a taken-but-unconsumed overlapped-sync
        payload (see _take_sync_ahead)."""
        prep = getattr(self, "_unconsumed_prep", None)
        self._unconsumed_prep = None
        if prep is not None and prep.upd is not None:
            self.encoder.restore_dirty(prep.consumed)

    def _deferred_snapshot(self, prep: Optional[_SyncAhead]):
        """The dispatch-time deferred upload: the overlapped payload is
        adopted verbatim when nothing changed since its capture; otherwise
        its consumed rows fold back into the dirty sets and the scatter
        rebuilds from the live mirrors (values re-gathered, so a top-up
        that re-encoded one of the payload's rows can never ship the stale
        version).  No prep → the plain synchronous build."""
        enc = self.encoder
        self._unconsumed_prep = None  # consumed (or folded back) below
        if prep is None:
            return enc.to_device_deferred()
        if (not enc.has_dirty() and len(enc.dic) == prep.dic_len
                and not getattr(enc, "_force_full_once", False)):
            m.sync_overlap.inc(("reused",))
            return prep.dsnap, prep.upd
        if prep.upd is not None:
            enc.restore_dirty(prep.consumed)
        m.sync_overlap.inc(("merged",))
        return enc.to_device_deferred()

    # --- micro-bucket pipelined dispatch (round 15) --------------------------

    def bucket_tiers(self) -> List[int]:
        """Pow-2 sub-bucket pad tiers below batch_size, largest first, down
        to the floor (batch_size/16, min 16) — the shapes the adaptive
        policy may dispatch.  The perf harness warms each tier pre-window
        (via _forced_bucket) so the policy's warm-tier gate can engage."""
        out: List[int] = []
        t = _pow2(self.batch_size, 1) // 2
        floor = max(16, self.batch_size // 16)
        while t >= floor:
            out.append(t)
            t //= 2
        return out

    def _pick_bucket(self, infos, interacts: bool) -> int:
        """The dispatch pad for this cycle.  Full batch_size unless the
        micro-bucket policy is armed (latency_target_ms) AND the batch is
        chain-eligible (pipelined, extender-free, non-interacting — the
        same gate as deep chaining: sub-buckets only pay off when they can
        ride the chain back-to-back).  _forced_bucket is the harness's
        warmup override."""
        B = self.batch_size
        if self._forced_bucket:
            return max(1, min(self._forced_bucket, B))
        if self.latency_target_ms is None or not infos:
            return B
        if interacts or self.extenders or not self.pipeline:
            # interacting batches dispatch shallow at full size: a small
            # unchained bucket would serialize dispatch against completion
            # and lose throughput with no latency win
            return B
        return self._bucket_from_latency()

    def _bucket_from_latency(self) -> int:
        """Pick the dispatch tier from the measured per-tier profiles: the
        LARGEST profiled tier whose EMA'd batch-max attempt latency fits
        under 90% of the target (largest = highest throughput; the margin
        absorbs cycle jitter so the window p99 holds), and full batch_size
        when nothing is profiled yet.  When every profiled tier overruns
        the target, DESCEND one unprofiled tier below the smallest — its
        first dispatch compiles the shape once and its post-compile
        batches profile it, so a cold production scheduler converges in at
        most O(log batch_size) one-off compiles (the pow-2 tier-growth
        discipline; compile-stalled attempts never poison the profile —
        _InFlight.compiles0).  The perf harness pre-profiles every tier
        with pipelined warm bursts instead, so measured windows descend
        nowhere and stay at zero in-window compiles."""
        B = self.batch_size
        prof = self._tier_p99
        if not prof:
            return B
        tgt = self.latency_target_ms / 1e3
        cand = dict(prof)
        if B not in cand:
            # the full batch is rarely profiled once the policy engages
            # (only ≥half-full batches feed profiles, and sub-bucketing
            # keeps the window off B): predict it from its immediate sub-
            # tier's profile — attempt latency tracks the pad ~linearly —
            # so a generous target can still climb back to full batches
            t = max(cand)
            if 2 * t >= _pow2(B, 1):
                cand[B] = 2.0 * cand[t]
        fit = [t for t, p in cand.items() if p <= 0.9 * tgt]
        if fit:
            return max(fit)
        lower = [t for t in self.bucket_tiers() if t < min(prof)]
        return max(lower) if lower else min(prof)

    def _dispatch_batch(self, infos: List[QueuedPodInfo],
                        prevs: Optional[List[_InFlight]] = None,
                        interacts: Optional[bool] = None,
                        pad: Optional[int] = None) -> _InFlight:
        """Snapshot → compile → ONE device dispatch; decisions fetched
        (blocking) at _complete.  ``prevs`` (deep pipeline) are the still-in-
        flight batches (oldest first, ≤2) whose device-resident decisions
        feed this program as resource deltas; ``interacts`` is the caller's
        already-computed _pods_block_deep result for this batch (recomputed
        when absent); ``pad`` is the compile pad tier (micro-bucket policy —
        defaults to batch_size, the round-14 shape)."""
        from .component_base.trace import Trace

        t0 = self.clock()
        # hot-path step trace; log_if_long now fires at the END of the
        # batch's bind phase (via _InFlight.trace) so the logged total
        # covers dispatch→complete→bind, not just the synchronous dispatch
        # slice a deep pipeline returns from at enqueue (utiltrace in
        # schedulePod, scheduler.go:775-791)
        trace = Trace("Scheduling", pods=len(infos))
        cycle = self.queue.scheduling_cycle()
        # attempt span tree root (see tracer in __init__): children bracket
        # every host phase; the context travels on the _InFlight record
        root = ctx = disp_span = None
        if self.tracer.enabled:
            root = self.tracer.span("attempt", start=t0, cycle=cycle,
                                    pods=len(infos))
            ctx = root.context()
            disp_span = self.tracer.span("dispatch", parent=ctx, start=t0)
            earliest = min(qi.timestamp for qi in infos)
            # active wait = poppable-but-unpopped time (queue pressure);
            # the rest of the window is backoff/unschedulable parking
            act = max((t0 - max(qi.last_activation, qi.timestamp)
                       for qi in infos), default=0.0)
            self.tracer.span(
                "queue_wait", parent=ctx, start=earliest,
                max_wait_ms=round((t0 - earliest) * 1e3, 3),
                max_active_wait_ms=round(act * 1e3, 3),
                # the batch-formation hysteresis hold preceding this pop
                # (_await_backoff_wave) — attributed here, not smeared
                # into the next phase
                backoff_wave_ms=round(self._last_wave_wait * 1e3, 3),
            ).finish(end=t0)
        self._last_wave_wait = 0.0
        try:
            return self._dispatch_batch_traced(
                infos, prevs, interacts, t0, trace, cycle, root, ctx,
                disp_span, pad=pad)
        except Exception as e:
            # a dispatch-time fault must still close the attempt tree (an
            # unfinished root would orphan its already-exported children
            # and strand threshold-exporter buffers) AND dump the legacy
            # step trace — the slow-dispatch diagnostic matters most on
            # exactly the cycles that die
            self._discard_prep()
            if root is not None:
                root.set(error=f"{type(e).__name__}: {e}").finish()
            trace.log_if_long(0.1)
            raise

    def _dispatch_batch_traced(self, infos, prevs, interacts, t0, trace,
                               cycle, root, ctx, disp_span,
                               pad=None) -> _InFlight:
        """_dispatch_batch's body, wrapped by the span/trace failure guard
        above; see _dispatch_batch for the contract."""
        from .utils.compilemon import monitor as _cmon

        self._dispatch_seq += 1
        pad = pad or self.batch_size
        compiles0 = _cmon.snapshot()[0]
        # Overlapped sync (round 15): adopt the background thread's already-
        # applied snapshot/sync, then TOP-UP the residue — between-cycle
        # external events plus this cycle's completion assumes, which post-
        # date the capture by construction.  update_snapshot is generation-
        # gated, so the top-up only re-encodes what actually changed since.
        prep = self._take_sync_ahead() if self.overlap_sync else None
        # O(changed-nodes) refresh, generation-gated (cache.go:197-276 analog)
        changed = self.cache.update_snapshot(self.snapshot)
        self.encoder.sync(self.snapshot, changed)
        # DRA claim planes: project dirty nodes' (capacity, allocated) into
        # the encoder mirrors now, BEFORE the deferred device upload — the
        # upload closure re-checks encoder dirt at call time, so this flush
        # always rides the same scatter/snapshot as the node sync above
        self.dra.flush_to_encoder(self.encoder)
        t_snap_end = self.clock()
        self.phase_wall["snapshot"] += t_snap_end - t0
        if disp_span is not None:
            self.tracer.span("snapshot", parent=disp_span, start=t0,
                             overlapped=prep is not None).finish(
                end=t_snap_end)
        # fast-bound nominations whose assume this refresh now carries: the
        # reservation would double-count from here on — release it.  Marks
        # from the bind phase that ran after the PREVIOUS dispatch carry
        # that dispatch's seq; anything strictly older than this dispatch
        # is covered by the snapshot just built.
        for uid, seq in list(self._fastbound_noms.items()):
            if seq < self._dispatch_seq:
                self._fastbound_noms.pop(uid, None)
                self._nominated.pop(uid, None)
        trace.step("Snapshot update")
        pods = [qi.pod for qi in infos]
        # fixed padding: every cycle compiles to ONE (pad, tier) program
        # per bucket tier instead of one per pow-2 backlog size — partial
        # batches reuse the warm executable (first compile is tens of
        # seconds).  pad == batch_size unless the micro-bucket policy
        # shrank this dispatch onto a warmed sub-bucket tier.
        t_c = self.clock()
        batch = self.compiler.compile(pods, pad_to=pad)
        t_c_end = self.clock()
        self.phase_wall["compile"] += t_c_end - t_c
        if disp_span is not None:
            self.tracer.span("compile", parent=disp_span,
                             start=t_c).finish(end=t_c_end)
        trace.step("Batch compile")
        profile = self._profile_of(infos[0].pod)  # queue groups by profile
        fw = self._framework(profile)
        jt = self._jitted_by[profile]
        # gang context for this batch: the Coscheduling score plane's
        # host_prepare reads the staged pod objects (the compiled PodBatch
        # carries none), and the fused program gets the segment ids for the
        # in-batch all-or-nothing mask
        self.gangs.stage_batch(pods)
        gang_seg = self.gangs.gang_segments(pods, batch.size)
        t_hp = self.clock()
        host_auxes = fw.host_prepare(
            batch, self.snapshot, self.encoder, namespace_labels=self.namespace_labels
        )
        dt_hp = self.clock() - t_hp
        self.phase_wall["host_prepare"] += dt_hp
        if disp_span is not None:
            self.tracer.span("host_prepare", parent=disp_span,
                             start=t_hp).finish(end=t_hp + dt_hp)
        # the reference's per-extension-point histogram (:130): host_prepare
        # is this build's PreFilter/PreScore analog, the fused dispatch its
        # Filter+Score (observed below) — was registered-but-unemitted
        m.framework_extension_point_duration.observe(dt_hp, ("host_prepare",))
        gate_auxes = None
        if self.mesh is not None:
            # pre-place host aux planes with node-axis sharding on their
            # node dim: device_put here is the explicit analog of the
            # snapshot's sharded upload — without it GSPMD would replicate
            # the [B, N] planes onto every shard at dispatch.  The dedup
            # gate reads the Coscheduling anchor, so it keeps the pre-put
            # host arrays: the same read on the placed copy would be a
            # blocking device round every cycle
            from .parallel.mesh import shard_host_auxes

            gate_auxes = host_auxes
            host_auxes = shard_host_auxes(
                host_auxes, self.mesh, self.encoder._n)
        if self.extenders:
            # round-based cycles: each pod's decision lands at its own
            # round, so per-attempt latency must not absorb later pods'
            # rounds.  Snapshot scatter + nominations + prepare + the first
            # round's packed plane ride ONE fused program (prepare_packed).
            dsnap, upd = self._deferred_snapshot(prep)
            nom_rows, nom_req = self._nominated_arrays(
                {qi.pod.uid for qi in infos})
            packed0, auxes, dsnap, dyn = jt["prepare_packed"](
                batch, dsnap, upd, nom_rows, nom_req, host_auxes)
            self.encoder.commit_device(dsnap)
            if not getattr(self, "_ext_round_warmed", False):
                # the standalone round programs (compute_packed for rounds
                # ≥2, apply_commits) only run on MULTI-round batches, which
                # the harness's 1-pod warmups never produce — compile them
                # on the first extender dispatch (pre-window) instead of
                # inside the first contended batch (measured 2.8s mid-window)
                self._ext_round_warmed = True
                jt["compute_packed"](batch, dsnap, dyn, auxes)
                jt["apply_commits"](
                    batch, dsnap, dyn, auxes,
                    np.zeros(batch.size, dtype=bool),
                    np.zeros(batch.size, dtype=np.int32),
                )
                # the failing-batch diagnosis program too: its first use
                # is inside _bind_phase, and a cold compile there is the
                # same mid-window stall this block exists to prevent
                jt["diag_bits"](batch, dsnap, dyn, auxes)
            fl = _InFlight(infos, batch, dsnap, dyn, auxes, None, None,
                           t0, cycle, profile=profile, fw=fw,
                           engine="extender")
            fl.compiles0 = compiles0
            fl.name_of = dict(self.encoder.row_to_name())
            # dispatch/device phase boundary: the fused first round is
            # enqueued; everything after is the extender round walk
            fl.dispatch_end = self.clock()
            fl.trace = trace
            if root is not None:
                fl.span, fl.span_ctx = root, ctx
                root.set(engine="extender")
                disp_span.finish(end=fl.dispatch_end)
            if self.async_extenders:
                # the WHOLE round walk (device-round fetches, callouts,
                # host ledger) moves off the device cycle: _complete joins
                # it before any assume, so the walk overlaps the previous
                # batch's bind phase and the next cycle's pop/snapshot/
                # compile.  The walk's inputs are snapshotted HERE, on the
                # dispatch thread (_capture_walk_state) — the bind phase's
                # store writes pump cache events concurrently, and a
                # mid-iteration mutation of cache._nodes or a torn ledger
                # copy would corrupt the walk.
                import threading

                captured = self._capture_walk_state()

                def _walk(rec=fl, clk=self.clock, tracer=self.tracer):
                    # cross-thread span handoff: the walk's span parents to
                    # the attempt context carried on the record — no
                    # thread-local crosses this seam.  start/end both come
                    # from the SCHEDULER clock (clk), matching every other
                    # scheduler-emitted span's clock domain
                    wspan = (tracer.span("extender_rounds",
                                         parent=rec.span_ctx, start=clk())
                             if tracer.enabled and rec.span_ctx is not None
                             else None)
                    try:
                        out, lat, rounds, _wait = self._assign_with_extenders(
                            fw, jt, batch, dsnap, dyn, auxes, pods, t0,
                            packed0=packed0, nom=(nom_rows, nom_req),
                            captured=captured,
                        )
                        rec.fetched, rec.algo_lat = out, lat
                        rec.rounds_np = rounds
                        if wspan is not None:
                            wspan.set(rounds=int(rounds),
                                      callout_wait_ms=round(_wait * 1e3, 3))
                    except Exception as e:  # surfaced at _complete → the
                        rec.walk_error = e  # cycle failure handler requeues
                        if wspan is not None:
                            wspan.set(error=f"{type(e).__name__}: {e}")
                        klog.V(1).info_s(
                            "Async extender walk failed; batch requeues at "
                            "completion", pods=len(infos),
                            error=f"{type(e).__name__}: {e}")
                    rec.fetched_at = clk()
                    if wspan is not None:
                        wspan.finish(end=rec.fetched_at)

                fl.fetch_thread = threading.Thread(target=_walk, daemon=True)
                fl.fetch_thread.start()
                return fl
            t_d = self.clock()
            node_row, algo_lat, ext_rounds, wait = self._assign_with_extenders(
                fw, jt, batch, dsnap, dyn, auxes, pods, t0, packed0=packed0,
                nom=(nom_rows, nom_req),
            )
            # callout wall is its own bucket (was lumped into dispatch):
            # a suite regression now names the extender protocol, not the
            # device program
            self.phase_wall["extender_wait"] += wait
            ew = self.clock() - t_d - wait
            if ew < 0:
                # the callout wall exceeded the interval it was timed
                # inside — a double-attribution bug, not a rounding blip;
                # count it instead of silently clamping it away
                m.phase_wall_clamped.inc(("dispatch",))
                ew = 0.0
            self.phase_wall["dispatch"] += ew
            fl.node_row_dev = None
            fl.fetched, fl.algo_lat, fl.rounds_np = node_row, algo_lat, ext_rounds
            fl.fetched_at = self.clock()
            if root is not None:
                self.tracer.span(
                    "extender_rounds", parent=ctx, start=t_d,
                    rounds=int(ext_rounds),
                    callout_wait_ms=round(wait * 1e3, 3),
                ).finish(end=fl.fetched_at)
            return fl
        dsnap, upd = self._deferred_snapshot(prep)
        nom_rows, nom_req = self._nominated_arrays({qi.pod.uid for qi in infos})
        deltas = None
        if prevs:
            from .framework.runtime import PrevBatch

            # the four term groups ride the carry only when THIS batch has
            # affinity content (it then surely builds an IPA aux to chain
            # into; plain workloads keep the group-free pytree variant)
            def _groups_of(pb):
                if not (batch.has_affinity and self._chain_affinity_now):
                    return {}
                return {
                    name: getattr(pb, name)
                    for name in ("req_affinity", "req_anti_affinity",
                                 "pref_affinity", "pref_anti_affinity")
                }

            deltas = [
                PrevBatch(
                    rows=p.node_row_dev, req=p.batch.request,
                    nz=p.batch.non_zero, valid=p.batch.valid,
                    label_keys=p.batch.label_keys,
                    label_vals=p.batch.label_vals, ns=p.batch.ns,
                    **_groups_of(p.batch),
                )
                for p in prevs
            ]
        t_d = self.clock()
        part0 = self.phase_wall["partition"]
        (res, auxes, dsnap_out, dyn_out, diag), engine = self._run_assignment(
            jt, batch, dsnap, upd, nom_rows, nom_req, host_auxes,
            deltas=deltas, gang_seg=gang_seg, gate_auxes=gate_auxes, fw=fw,
        )
        # dispatch wall excludes the partition slice timed inside
        dt_disp = (self.clock() - t_d) - (
            self.phase_wall["partition"] - part0)
        self.phase_wall["dispatch"] += dt_disp
        m.framework_extension_point_duration.observe(dt_disp, ("dispatch",))
        self.encoder.commit_device(dsnap_out)  # futures — safe to adopt now
        trace.step("Device dispatch")
        # NOTE: log_if_long moved to the end of this batch's bind phase
        # (the trace rides the _InFlight record) — under pipeline/
        # async_extenders the dispatch returns at enqueue, so logging here
        # reported only the synchronous slice of a multi-cycle attempt
        fl = _InFlight(infos, batch, dsnap_out, dyn_out, auxes, res.node_row,
                       None, t0, cycle, profile=profile, fw=fw, diag_dev=diag,
                       engine=engine, has_aff=bool(batch.has_affinity))
        fl.compiles0 = compiles0
        fl.dispatch_end = self.clock()
        fl.trace = trace
        if root is not None:
            fl.span, fl.span_ctx = root, ctx
            root.set(engine=engine)
            self.tracer.span("device_enqueue", parent=disp_span,
                             start=t_d).finish(end=fl.dispatch_end)
            disp_span.finish(end=fl.dispatch_end)
        # Row→name capture at DISPATCH (not complete): a deep-pipelined
        # batch is completed only after the NEXT dispatch's encoder.sync,
        # which may reuse rows of nodes deleted in between — resolving
        # through the live map then would bind to the wrong node.
        fl.name_of = dict(self.encoder.row_to_name())
        fl.interacts = interacts if interacts is not None else (
            _pods_block_deep(pods)
            or (not self._chain_affinity_now
                and any(_pod_has_affinity(p) for p in pods)))
        fl.node_del_gen = self._node_del_gen
        fl.chained = bool(prevs)
        # Speculative candidate mask: when this profile's recent cycles were
        # failure-heavy and the batch can preempt, dispatch the cand program
        # NOW so its device window + fetch overlap the bind phase instead of
        # serializing inside it (2 tunnel rounds off every failing cycle).
        # A wrong guess costs one overlapped device program, no extra rounds
        # on the critical path.
        # chained batches never run the candidate mask (their bind defers
        # preemption to the retry), so neither the levels table nor the
        # speculative dispatch applies to them
        can_preempt = not prevs and any(
            (p.spec.priority or 0) > 0
            and p.spec.preemption_policy != "Never" for p in pods)
        if can_preempt:
            # levels only matter to the candidate mask; a batch that can
            # never preempt must not pay the O(P log P) np.unique on the
            # dispatch critical path
            fl.cand_levels = self._priority_levels()
        if can_preempt and self._fail_ema.get(profile, 0.0) > 0.25:
            fl.cand_dev = jt["cand"](batch, dsnap_out, dyn_out, auxes,
                                     fl.cand_levels)
        # background fetch: the thread blocks in np.asarray until the
        # program lands, so by _complete time the decisions are host-side
        # and the cycle pays no fetch round trip
        import threading

        n_filters = len(fw.filter_names)
        packed_mode = n_filters <= 31  # matches diagnostics() in _build_jitted

        def _bg_fetch(dev=res.node_row, diag_dev=diag, rec=fl, clk=self.clock):
            # Poll-with-sleep instead of a blocking fetch: a blocking
            # jax fetch holds the GIL for its whole wait, which STALLS the
            # main thread's host pipeline (profiled: trivial dictionary
            # interns averaging 1.7ms under contention).  time.sleep
            # releases the GIL; np.asarray on an already-ready array is
            # ~0.1ms, so the thread's GIL footprint stays negligible.
            try:
                if packed_mode and diag_dev is not None:
                    # packed [3, B] i32 (node_row; diagnosis bitmask; engine
                    # rounds): decisions + diagnosis + the rounds metric
                    # land in ONE device→host round
                    if hasattr(diag_dev, "is_ready"):
                        while not diag_dev.is_ready():
                            time.sleep(0.004)
                    packed = np.asarray(diag_dev)
                    rec.fetched = packed[0]
                    rec.diag_np = _unpack_diag(packed[1], n_filters)
                    rec.rounds_np = int(packed[2, 0])
                    rec.fetched_at = clk()
                    if rec.cand_dev is not None:
                        try:  # speculative cand mask: land it off-path too,
                            # with the same GIL-releasing readiness poll (a
                            # blocking asarray would stall the main thread
                            # for the cand program's whole device window)
                            if hasattr(rec.cand_dev, "is_ready"):
                                while not rec.cand_dev.is_ready():
                                    time.sleep(0.004)
                            rec.cand_np = np.asarray(rec.cand_dev)
                        except Exception:
                            # degraded, not lost: _bind_phase refetches the
                            # cand mask synchronously — count the miss
                            m.scheduler_retries.inc(("bg_cand_fetch_error",))
                            rec.cand_np = None
                    return
                if hasattr(dev, "is_ready"):
                    while not dev.is_ready():
                        time.sleep(0.004)
                rec.fetched = np.asarray(dev)
            except Exception:
                # _complete falls back to a sync fetch; the fallback costs
                # a full blocking device round, so make the rate visible
                m.scheduler_retries.inc(("bg_fetch_error",))
                rec.fetched = None
            rec.fetched_at = clk()
            # prefetch the diagnosis bits too (tiny [B, K] bool): a failing
            # batch's bind phase then pays no extra device round trip.  In
            # packed mode the device array is the raw [2, B] i32 stack —
            # unpack row 1 here; _bind_phase consumes diag_np as bool[B, K]
            # and would otherwise misread the packed ints as diagnosis rows.
            try:
                if diag_dev is None:
                    rec.diag_np = None
                elif packed_mode:
                    raw = np.asarray(diag_dev)
                    rec.diag_np = _unpack_diag(raw[1], n_filters)
                    rec.rounds_np = int(raw[2, 0])
                else:
                    rec.diag_np = np.asarray(diag_dev)
            except Exception:
                # diagnosis prefetch is advisory — _bind_phase refetches
                # per failing batch; count the miss rather than hide it
                m.scheduler_retries.inc(("bg_diag_fetch_error",))
                rec.diag_np = None

        def _bg_run(rec=fl, tracer=self.tracer):
            _bg_fetch()
            # cross-thread span handoff (seam #1): the device-wait span is
            # emitted from the fetch thread, parented to the attempt
            # context the record carries — enqueue → decisions host-side.
            # Only on SUCCESS (rec.fetched landed): a failed bg fetch falls
            # back to _complete's sync fetch, which emits the span itself —
            # emitting here too would double-count the device wait.
            if tracer.enabled and rec.span_ctx is not None \
                    and rec.fetched is not None:
                tracer.span("device_wait", parent=rec.span_ctx,
                            start=rec.dispatch_end).finish(
                    end=rec.fetched_at or rec.dispatch_end)

        fl.fetch_thread = threading.Thread(target=_bg_run, daemon=True)
        fl.fetch_thread.start()
        return fl

    def _complete(self, fl: _InFlight) -> np.ndarray:
        """Fetch the batch's decisions and assume placements in the cache so
        the NEXT dispatch's snapshot accounts for them (assume :571; the bind
        happens later, exactly like the reference's binding goroutine)."""
        # Join the dispatch-time background fetch (the device→host round
        # trip overlapped the next batch's window); fall back to a direct
        # blocking fetch when no thread ran (extender path) or it failed.
        # (Round 3's copy_to_host_async + is_ready polling measured 100-200ms
        # SLOWER than a plain blocking fetch on the current backend —
        # tools/bench_cycle.py — so the fallback is the simple one.)
        t_f = self.clock()
        if fl.fetch_thread is not None:
            fl.fetch_thread.join()
        if fl.walk_error is not None:
            # async extender walk died: attribute the join, then surface to
            # schedule_cycle's completion guard (requeue via the failure
            # handler — nothing was assumed yet)
            self.phase_wall["extender_wait"] += self.clock() - t_f
            raise fl.walk_error
        if fl.fetched is not None:
            node_row = fl.fetched
        else:
            dev = fl.node_row_dev
            jax.block_until_ready(dev)
            node_row = np.asarray(dev)
            fl.fetched_at = self.clock()
            if self.tracer.enabled and fl.span_ctx is not None:
                # no background thread emitted the device-wait span (bg
                # fetch failed or never ran): record it from the sync fetch
                self.tracer.span("device_wait", parent=fl.span_ctx,
                                 start=fl.dispatch_end,
                                 sync_fallback=True).finish(
                    end=fl.fetched_at)
        # an extender batch's join waits on callouts, not a device fetch —
        # keep the attribution honest (the extender_wait phase bucket)
        self.phase_wall[
            "extender_wait" if fl.engine == "extender" else "fetch"
        ] += self.clock() - t_f
        if fl.algo_lat is None:
            # decision became available when the background fetch landed,
            # not when the (possibly later) _complete joined it
            algo = max(fl.fetched_at - fl.t0, 0.0)
            fl.algo_lat = np.full(len(fl.infos), algo)
            # one algorithm invocation for the whole batch → one sample
            # (the extender path samples per-pod cycles itself)
            m.scheduling_algorithm_duration.observe(algo)
        node_row = np.array(node_row)  # own copy — may be demoted below
        # overlapped-sync seam: the background update_snapshot reads cache
        # clones, and the assumes below mutate the cache — join the sync
        # thread BEFORE the first assume so its capture is a consistent
        # point-in-time (the fetch join above is exactly the GIL-released
        # window the sync was spawned to overlap)
        self._join_sync_ahead()
        # resolve rows through the DISPATCH-time map (see _InFlight.name_of);
        # a node deleted since dispatch fails the cache liveness check below
        # and its pod retries, exactly like the reference's binding-error path
        name_of = fl.name_of if fl.name_of is not None else self.encoder.row_to_name()
        fl.node_names = [None] * len(fl.infos)
        for i, qi in enumerate(fl.infos):
            row = int(node_row[i])
            if row >= 0:
                name = name_of.get(row)
                info = self.cache._nodes.get(name) if name is not None else None
                # a deleted node that still hosts pods keeps a ghost cache
                # entry with .node=None — that's gone too, retry the pod
                if info is None or info.node is None:
                    node_row[i] = -1  # node gone since dispatch — retry the pod
                    continue
                fl.node_names[i] = name
                self._nominated.pop(qi.pod.uid, None)
                self.cache.assume_pod(qi.pod, name)
        if fl.trace is not None:
            fl.trace.step("Decision fetch")
        if self.tracer.enabled and fl.span_ctx is not None:
            # fetch join + cache assumes, under the attempt tree (seam #3:
            # the context came through the record, not a thread-local).
            # end stamped explicitly from the SCHEDULER clock — every
            # scheduler-emitted span uses one clock domain even when the
            # tracer was built with a different default clock
            self.tracer.span("complete", parent=fl.span_ctx,
                             start=t_f).finish(end=self.clock())
        # kill-point: the whole batch is assumed in the cache, nothing is
        # bound in the store — process death here loses every assume (soft
        # state); recovery must reschedule the batch from the store's truth
        from .chaos.faults import maybe_crash

        maybe_crash("crash.after_assume")
        return node_row

    def _bind_phase(self, fl: _InFlight, node_row: np.ndarray) -> CycleStats:
        """The binding cycle for a completed batch: reserve → permit → bind
        per scheduled pod, diagnosis + preemption per unschedulable pod."""
        stats = CycleStats(attempted=len(fl.infos))
        t_bind = self.clock()
        fw = fl.fw
        batch, dsnap, dyn, auxes = fl.batch, fl.dsnap, fl.dyn, fl.auxes
        diag_np = cand_np = min_sched_prio = None
        pf_ctx = None  # per-batch preemption context, built on first failure
        fast_bound_uids: List[str] = []  # nominations to release at phase end
        tracer = self.tracer
        bp_span = (tracer.span("bind_phase", parent=fl.span_ctx,
                               start=t_bind)
                   if tracer.enabled and fl.span_ctx is not None else None)
        bp_ctx = bp_span.context() if bp_span is not None else None
        # Per-pod attempt-phase accounting: the three tiling phases sum
        # EXACTLY to the pod's scheduling_attempt_duration observation —
        # dispatch (host work to program enqueue), device (enqueue → its
        # decision host-side; the extender round walk for extender
        # batches), bind (its own reserve→bind segment).  Records ride the
        # attempt root span's pod_phases attribute (harness aggregation +
        # `ktpu trace`); the histograms are always-on (`ktpu slo`).
        dispatch_host = max(fl.dispatch_end - fl.t0, 0.0)
        pod_phases: Optional[List[dict]] = (
            [] if fl.span is not None else None)
        # micro-bucket policy feed: attempts from compile-stalled batches
        # are excluded (one cold-shape dispatch would read as a latency
        # regression and poison the tier's profile)
        track_lat = self.latency_target_ms is not None
        if track_lat and fl.compiles0 >= 0:
            from .utils.compilemon import monitor as _cmon

            track_lat = _cmon.snapshot()[0] == fl.compiles0
        batch_attempts: List[float] = []

        def _note_phases(i, qi, t_pod, now, queued_at, outcome) -> float:
            algo = float(fl.algo_lat[i])
            d = min(dispatch_host, algo)
            dev = algo - d
            b = max(now - t_pod, 0.0)
            m.attempt_phase_duration.observe(d, ("dispatch",))
            m.attempt_phase_duration.observe(dev, ("device",))
            m.attempt_phase_duration.observe(b, ("bind",))
            m.attempt_phase_duration.observe(
                max(fl.t0 - queued_at, 0.0), ("queue_wait",))
            if pod_phases is not None:
                pod_phases.append({
                    "pod": qi.pod.key(), "cycle": fl.cycle,
                    "engine": fl.engine, "outcome": outcome,
                    "dispatch": d, "device": dev, "bind": b,
                    "queue_wait": max(fl.t0 - queued_at, 0.0),
                    "total": algo + b,
                })
            return algo + b
        for i, qi in enumerate(fl.infos):
            t_pod = self.clock()
            outcome = "unschedulable"  # per-pod attempt record label
            # captured BEFORE any requeue: add_unschedulable/_push_backoff
            # reset qi.timestamp, which would zero the e2e wait term below
            queued_at = qi.timestamp
            row = int(node_row[i])
            if row >= 0:
                # name resolved at completion time (see _complete) — the
                # row→name map may have changed under the next dispatch's sync
                node_name = fl.node_names[i]
                bind_span = (tracer.span("bind", parent=bp_ctx, start=t_pod,
                                         pod=qi.pod.key(), node=node_name)
                             if bp_ctx is not None else None)
                try:
                    ok = self._run_reserve_and_bind(fw, qi.pod, node_name,
                                                    qi=qi,
                                                    span_ctx=fl.span_ctx)
                except _TransientBindError:
                    # already rolled back; timer retry via backoff — the
                    # rest of the batch's bind phase proceeds untouched
                    self.cache.forget_pod(qi.pod)
                    self._requeue_after_failure(qi)
                    if bind_span is not None:
                        bind_span.set(outcome="transient_error").finish(
                            end=self.clock())
                    m.scheduling_attempt_duration.observe(_note_phases(
                        i, qi, t_pod, self.clock(), queued_at, "retry"))
                    continue
                if bind_span is not None:
                    # explicit end: scheduler-clock domain (see _complete)
                    bind_span.set(outcome=(
                        "permit_wait" if ok is _PERMIT_WAIT
                        else "bound" if ok else "rejected")).finish(
                        end=self.clock())
                if ok is _PERMIT_WAIT:
                    # gang Permit hold: assume + reserve kept, bind deferred
                    # to _flush_waiting_binds — neither scheduled nor
                    # unschedulable yet; the attempt latency is still real
                    m.scheduling_attempt_duration.observe(_note_phases(
                        i, qi, t_pod, self.clock(), queued_at,
                        "permit_wait"))
                    continue
                if ok:
                    outcome = "scheduled"
                    self.cache.finish_binding(qi.pod)
                    stats.scheduled += 1
                    m.schedule_attempts.inc(("scheduled",))
                    m.pod_scheduling_attempts.observe(qi.attempts)
                    m.pod_scheduling_duration.observe(
                        self.clock() - qi.initial_attempt_timestamp
                    )
                    klog.V(4).info_s(
                        "Scheduled", pod=qi.pod.key(), node=node_name,
                        attempts=qi.attempts,
                    )
                    # scheduler.go:488 (Normal/Scheduled on bind success)
                    self.recorder.eventf(
                        qi.pod, "Normal", "Scheduled",
                        f"Successfully assigned {qi.pod.namespace}/"
                        f"{qi.pod.metadata.name} to {node_name}",
                    )
                else:  # reserve/bind failed — roll back (scheduler.go:676-689)
                    outcome = "bind_rejected"
                    self.cache.forget_pod(qi.pod)
                    # a pod deleted while in flight consumed its DELETE event
                    # already — requeueing it would create a permanent ghost
                    if self.store.get("Pod", qi.pod.namespace, qi.pod.metadata.name) is not None:
                        self.queue.add_unschedulable(qi, fl.cycle)
            else:
                fast_bound = None  # node name when preemption fast-binds
                if diag_np is None:
                    diag_np = fl.diag_np  # prefetched by the bg thread
                if diag_np is None and fl.diag_dev is not None:
                    raw = np.asarray(fl.diag_dev)  # one sync per failing batch
                    nf = len(fw.filter_names)
                    diag_np = (_unpack_diag(raw[1], nf)
                               if nf <= 31 else raw)
                    if nf <= 31 and fl.rounds_np is None:
                        fl.rounds_np = int(raw[2, 0])
                if diag_np is None:
                    # extender batches carry no fused diag plane: run the
                    # whole-batch diagnosis program ONCE for this failing
                    # batch (bool[B, K] — every failing pod shares it)
                    diag_np = np.asarray(self._jitted_by[fl.profile][
                        "diag_bits"](batch, dsnap, dyn, auxes))
                diag_row = None if diag_np is None else diag_np[i]
                if diag_row is not None and bool(np.all(diag_row)) \
                        and self.gangs.is_member(qi.pod):
                    # every filter left this pod a feasible node yet no row
                    # came back: the gang all-or-nothing mask withdrew its
                    # gang (a sibling missed) — attribute to Coscheduling,
                    # not to a filter plugin that didn't reject it
                    qi.unschedulable_plugins = {"Coscheduling"}
                else:
                    qi.unschedulable_plugins = self._diagnose(
                        fw, diag_row=diag_row)
                # repeat-offender cost cap: the preemption candidate program
                # (full-pod-tier einsum + its own device round) only runs
                # when SOME scheduled pod could actually be a victim — a
                # priority-0 backlog pod riding the 60s flush otherwise pays
                # it every ride and stretches every cohabiting batch's tail
                if min_sched_prio is None:
                    valid = np.asarray(self.encoder.pod_valid)
                    prios = np.asarray(self.encoder.pod_priority)[valid]
                    min_sched_prio = int(prios.min()) if prios.size else 1 << 30
                can_preempt = (
                    qi.pod.spec.preemption_policy != "Never"
                    and min_sched_prio < (qi.pod.spec.priority or 0)
                    # a deep-chained batch's dry-run would run against
                    # chained-delta state it can neither see as victims nor
                    # evict — defer to the retry, which blocks the chain
                    # (_infos_block_deep: attempts > 1) and preempts clean
                    and not fl.chained
                    # gang guard: never evict victims for a gang that cannot
                    # fully place — only the LAST missing member may preempt
                    and self.gangs.allows_preemption(qi.pod)
                )
                if can_preempt:
                    # the lazy context (PDB list, row→name, candidate-mask
                    # program) is only built once a pod that CAN preempt
                    # fails — its full-pod-tier einsum must not run for
                    # Never-policy batches (store writes inside the post
                    # filter ride the bind_error guard at the call site
                    # below: a transient fault requeues this pod, it never
                    # kills the rest of the batch's bind phase)
                    if pf_ctx is None:
                        # row→name from _complete (pre-sync): the next batch's
                        # encoder.sync may have reused a deleted node's row,
                        # and dispatch-time candidate rows must not resolve
                        # through the post-sync map
                        name_of = (fl.name_of if fl.name_of is not None
                                   else self.encoder.row_to_name())
                        # row→name as an object ndarray: per-pod candidate
                        # name lists become one fancy index instead of an
                        # O(N) dict-lookup comprehension per failing pod
                        names_arr = np.full(
                            (max(name_of) + 1) if name_of else 0,
                            None, dtype=object,
                        )
                        for r, nm in name_of.items():
                            names_arr[r] = nm
                        pf_ctx = (self.store.list("PodDisruptionBudget")[0],
                                  name_of, names_arr)
                    if cand_np is None:
                        cand_np = fl.cand_np  # speculative dispatch landed it
                    if cand_np is None and fl.cand_dev is not None:
                        cand_np = np.asarray(fl.cand_dev)
                    if cand_np is None:
                        cand_np = np.asarray(
                            self._candidate_mask(
                                fl.profile, batch, dsnap, dyn, auxes,
                                levels=fl.cand_levels,
                            )
                        )
                    try:
                        fast_bound = self._run_post_filter(
                            fw, qi, batch, dsnap, dyn, auxes, i,
                            cand_row=cand_np[i], pf_ctx=pf_ctx,
                        )
                    except Exception as e:
                        # transient store fault mid-preemption (victim
                        # delete / nomination write blew through retries):
                        # degrade to nominate-nothing — the pod requeues
                        # with backoff below and re-runs preemption clean
                        m.scheduler_retries.inc(("bind_error",))
                        klog.V(1).info_s(
                            "PostFilter failed; pod will retry",
                            pod=qi.pod.key(),
                            error=f"{type(e).__name__}: {e}")
                        fast_bound = None
                if fast_bound is not None:
                    outcome = "scheduled_fast"
                    # preemption fast-bound the pod to its nominated node
                    # within this attempt (_try_nominated_fast_bind); its
                    # nomination entry stays live until the end of this bind
                    # phase so later preemptors in the batch see the claim
                    # through their nominated maps (the shared snapshot
                    # tables predate the assume)
                    fast_bound_uids.append(qi.pod.uid)
                    stats.scheduled += 1
                    m.schedule_attempts.inc(("scheduled",))
                    m.pod_scheduling_attempts.observe(qi.attempts)
                    m.pod_scheduling_duration.observe(
                        self.clock() - qi.initial_attempt_timestamp
                    )
                    self.recorder.eventf(
                        qi.pod, "Normal", "Scheduled",
                        f"Successfully assigned {qi.pod.namespace}/"
                        f"{qi.pod.metadata.name} to {fast_bound} "
                        f"(nominated-node fast path after preemption)",
                    )
                else:
                    stats.unschedulable += 1
                    m.schedule_attempts.inc(("unschedulable",))
                    self.queue.add_unschedulable(qi, fl.cycle)
                    # scheduler.go:386 (Warning/FailedScheduling + diagnosis)
                    failing = ", ".join(sorted(qi.unschedulable_plugins))
                    self.recorder.eventf(
                        qi.pod, "Warning", "FailedScheduling",
                        f"0/{len(self.snapshot.node_info_list)} nodes are "
                        f"available: failed plugins: {failing}",
                    )
            # True per-attempt latency (scheduler_perf util.go:238-276): the
            # pod's decision is unavailable until its device program returns
            # (whole batch in the fused path, its own cycle in the extender
            # path), so its attempt spans that algorithm time plus its own
            # host reserve/permit/bind segment — not a batch average.
            now = self.clock()
            attempt = _note_phases(i, qi, t_pod, now, queued_at, outcome)
            m.scheduling_attempt_duration.observe(attempt)
            if track_lat:
                batch_attempts.append(attempt)
            # e2e additionally covers the wait since this attempt entered
            # the queue (metrics.go:78-84); the algorithm window overlaps
            # the wait in the pipelined path, so take the max, not the sum
            m.e2e_scheduling_duration.observe(
                max(attempt, now - queued_at))
        # Fast-bound pods' nominations must OUTLIVE this bind phase: a later
        # batch was already dispatched before it ran (pipeline), so that
        # batch's bind-phase preemption tables come from a snapshot that
        # predates these assumes — only the nominated map makes the claims
        # visible there.  Mark them with the current dispatch sequence;
        # _dispatch_batch purges marks older than its own update_snapshot
        # (which then carries the binds), avoiding double-counting.
        # Releasing here instead made follow-on preemptor waves evict
        # victims on already-claimed nodes (measured: 338/392 of a tail
        # batch re-failing into 10s backoffs).
        for uid in fast_bound_uids:
            if uid in self._nominated:
                self._fastbound_noms[uid] = self._dispatch_seq
        t_end = self.clock()
        stats.batch_seconds = t_end - fl.t0
        self.phase_wall["bind"] += t_end - t_bind
        if bp_span is not None:
            bp_span.finish(end=t_end)
        if fl.span is not None:
            # root finishes LAST (the threshold exporter keys on it); the
            # per-pod phase records ride the root for harness aggregation
            fl.span.set(scheduled=stats.scheduled,
                        unschedulable=stats.unschedulable,
                        pod_phases=pod_phases)
            fl.span.finish(end=t_end)
        if fl.trace is not None:
            # the ISSUE-14 bugfix made concrete: the legacy utiltrace wraps
            # the WHOLE attempt — its logged total now covers
            # dispatch→complete→bind even when those ran cycles apart
            fl.trace.step("Binding cycle")
            fl.trace.log_if_long(0.1)
        # engine observability: the round count rode the packed decision
        # fetch (row 2); the extender path counted its rounds host-side
        if fl.rounds_np is not None:
            m.assignment_rounds.inc((fl.engine,), by=int(fl.rounds_np))
        if track_lat and batch_attempts \
                and 2 * len(batch_attempts) >= fl.batch.size:
            # per-tier latency profile: EMA of the batch's MAX attempt (a
            # batch's attempts are near-uniform — one program round — so
            # max is a tight, conservative p99 proxy); α=0.5 adapts within
            # a few batches when the regime drifts mid-window.  Only
            # ≥half-full batches feed it: a 1-pod warm padded to 512 runs
            # one low-contention assignment round and would record a
            # flattering profile the window's full batches can't hit.
            pad_t = fl.batch.size
            hi = max(batch_attempts)
            prev = self._tier_p99.get(pad_t)
            self._tier_p99[pad_t] = hi if prev is None \
                else 0.5 * prev + 0.5 * hi
        if stats.attempted:
            # the EMA drives the speculative candidate-mask dispatch, so it
            # must count attempts that NEEDED preemption — fast-bound pods
            # end up "scheduled" but consumed the mask all the same
            frac = (stats.unschedulable + len(fast_bound_uids)) / stats.attempted
            prev_ema = self._fail_ema.get(fl.profile, 0.0)
            self._fail_ema[fl.profile] = 0.5 * prev_ema + 0.5 * frac
        if klog.V(2):
            klog.V(2).info_s(
                "Scheduling cycle complete", profile=fl.profile,
                attempted=stats.attempted, scheduled=stats.scheduled,
                unschedulable=stats.unschedulable,
                seconds=round(stats.batch_seconds, 4),
            )
        return stats

    def _cancel_waiting_bind(self, uid: str) -> None:
        """Abort a held binding cycle without finishing it: unreserve in
        reverse, forget the assume, drop the waiting entries."""
        wb = self._waiting_binds.pop(uid, None)
        if wb is None:
            return
        self.waiting_pods.remove(uid)
        pod = wb.qi.pod
        for done in reversed(wb.reserved):
            un = getattr(done.plugin, "unreserve", None)
            if un is not None:
                un(None, pod, wb.node_name)
        self.cache.forget_pod(pod)

    def _flush_waiting_binds(self) -> CycleStats:
        """Resolve binding cycles held open at Permit (gang holds).

        Allowed pods (the gang's last member released them) finish the
        PreBind→Bind→PostBind half; rejected/expired pods roll back —
        unreserve runs the Coscheduling group-failure hook, which rejects
        every still-waiting sibling, so one member's deadline fails the
        WHOLE gang in this one flush pass — and every requeued gang pod
        re-enters the active queue together via the group-aware
        PriorityQueue.activate (atomic gang requeue)."""
        stats = CycleStats()
        if not self._waiting_binds:
            return stats
        requeued_gang_pods: List[v1.Pod] = []
        # loop to a fixed point: a member's timeout rejects its SIBLINGS'
        # entries via the group-failure hook, and those must resolve in
        # THIS flush (one atomic gang requeue), not trickle one per cycle
        progress = True
        while progress:
            progress = False
            for uid in list(self._waiting_binds):
                wb = self._waiting_binds.get(uid)
                if wb is None:
                    continue  # a sibling's rejection already consumed it
                resolved = self._flush_one_waiting(
                    uid, wb, stats, requeued_gang_pods)
                progress = progress or resolved
        if requeued_gang_pods:
            # atomic gang requeue: the group-aware activate pulls every
            # queued sibling (incl. backoff) to active in one step
            self.queue.activate(requeued_gang_pods)
        return stats

    def _flush_one_waiting(self, uid: str, wb: "_WaitingBind",
                           stats: CycleStats,
                           requeued_gang_pods: List[v1.Pod]) -> bool:
        """Resolve one held binding cycle; → True when it left the map."""
        pod = wb.qi.pod
        reason = self.waiting_pods.wait_on_permit(pod)
        if reason is None:
            # allowed: run the deferred PreBind→Bind→PostBind half
            del self._waiting_binds[uid]
            try:
                ok = self._finish_bind(wb.fw, pod, wb.node_name, wb.reserved,
                                       span_ctx=wb.ctx)
            except _TransientBindError:
                self.cache.forget_pod(pod)
                self._requeue_after_failure(wb.qi)
                return True
            now = self.clock()
            m.scheduling_attempt_duration.observe(now - wb.since)
            m.attempt_phase_duration.observe(now - wb.since, ("permit_wait",))
            if self.tracer.enabled:
                self.tracer.span(
                    "permit_wait", parent=wb.ctx, start=wb.since,
                    pod=pod.key(),
                    outcome="released" if ok else "bind_failed",
                ).finish(end=now)
            if ok:
                self.cache.finish_binding(pod)
                stats.scheduled += 1
                m.schedule_attempts.inc(("scheduled",))
                m.pod_scheduling_attempts.observe(wb.qi.attempts)
                m.pod_scheduling_duration.observe(
                    now - wb.qi.initial_attempt_timestamp)
                m.e2e_scheduling_duration.observe(
                    max(now - wb.qi.timestamp, now - wb.since))
                self.recorder.eventf(
                    pod, "Normal", "Scheduled",
                    f"Successfully assigned {pod.namespace}/"
                    f"{pod.metadata.name} to {wb.node_name} (gang released)",
                )
            else:
                self.cache.forget_pod(pod)
                if self.store.get("Pod", pod.namespace,
                                  pod.metadata.name) is not None:
                    self.queue.add_unschedulable(wb.qi, None)
                    requeued_gang_pods.append(pod)
            return True
        if self.waiting_pods.get(uid) is None:
            # rejected or deadline expired: roll the cycle back; the
            # unreserve chain fires the gang group-failure hook
            del self._waiting_binds[uid]
            now = self.clock()
            m.attempt_phase_duration.observe(now - wb.since, ("permit_wait",))
            if self.tracer.enabled:
                self.tracer.span(
                    "permit_wait", parent=wb.ctx, start=wb.since,
                    pod=pod.key(), outcome="rejected", reason=str(reason),
                ).finish(end=now)
            self.gangs.note_wait_rejected(pod, reason)
            for done in reversed(wb.reserved):
                un = getattr(done.plugin, "unreserve", None)
                if un is not None:
                    un(None, pod, wb.node_name)
            self.cache.forget_pod(pod)
            stats.unschedulable += 1
            m.schedule_attempts.inc(("unschedulable",))
            self.recorder.eventf(
                pod, "Warning", "FailedScheduling",
                f"pod rejected at permit: {reason}",
            )
            if self.store.get("Pod", pod.namespace,
                              pod.metadata.name) is not None:
                self.queue.add_unschedulable(wb.qi, None)
                requeued_gang_pods.append(pod)
            return True
        return False  # still waiting — leave the hold in place

    def _observe_pending(self):
        a, b, u = self.queue.pending_count()
        m.pending_pods.set(a, ("active",))
        m.pending_pods.set(b, ("backoff",))
        m.pending_pods.set(u, ("unschedulable",))
        m.pending_pods.set(len(self._waiting_binds), ("gated",))

    def _run_assignment(self, jt, batch, dsnap, upd, nom_rows, nom_req,
                        host_auxes, deltas=None, gang_seg=None,
                        gate_auxes=None, fw=None):
        """Dispatch between the conflict-partitioned batch engine and the
        exact serial scan (the parity oracle).  "auto" partitions the batch
        into pod–pod interaction components (framework/conflict.py: affinity
        term matches + shared spread constraints + gang membership) and uses
        the batch engine unless ONE component dominates the batch — the
        auction then serializes one commit per round against a dense
        per-round recompute, where the row-sliced scan is cheaper per step.
        Independent components and all uncoupled pods commit in parallel
        rounds regardless of the batch's total coupled fraction (the old
        all-or-nothing mode flip serialized those too).

        ``deltas`` are the deep pipeline's in-flight-batch carries
        (≤2 PrevBatch, oldest first) — see apply_prev_delta; the program
        always receives exactly two slots, noop-padded, so every depth
        shares one compiled executable.

        Returns ((AssignResult, auxes, updated dsnap, dyn, diag), engine)
        from ONE fused dispatch (snapshot scatter + nominations + prepare +
        assign); ``engine`` is "batch" | "scan" for the rounds metric."""
        # slot count is fixed per scheduler config (depth-1 chained carries;
        # none in sync mode) so every cycle of an instance shares one
        # compiled executable and shallow configs pay no noop passes
        n_slots = self.pipeline_depth - 1 if self.pipeline else 0
        # noop carries mirror the real ones: an affinity batch's slots ALWAYS
        # carry (possibly zeroed) term groups, so its chained and unchained
        # cycles share ONE compiled variant — the harness's template warmups
        # then cover the deep-chained affinity program too (a groups-only-
        # when-chained pytree compiled on the window's first deep dispatch:
        # measured one ~5s in-window compile collapsing the scaled anti
        # suite 792 → 19.5 pods/s)
        noop = self._noop_delta(
            batch,
            with_groups=(self._chain_affinity_now
                         and bool(getattr(batch, "has_affinity", False)))
            or any(d.req_affinity is not None for d in (deltas or [])))
        deltas = list(deltas or [])
        delta = tuple((deltas + [noop] * n_slots)[:n_slots])
        # numpy, NOT jnp.arange: an eager jnp op is its own device program,
        # and each program execution on the tunnel pays a ~100ms pacing round
        order = np.arange(batch.size, dtype=np.int32)
        if gang_seg is None:
            gang_seg = self.gangs.gang_segments([], batch.valid.shape[0])
        t_part = self.clock()
        mode, coupling, info = self.engine_choice(batch)
        self.phase_wall["partition"] += self.clock() - t_part
        if info is not None:
            for s in info.sizes:
                m.coupled_component_size.observe(s)
        if mode == "batch":
            classes = self._dedup_classes(
                batch, host_auxes if gate_auxes is None else gate_auxes,
                fw=fw)
            # the steady-state chain heuristic (see _affinity_chain_ok):
            # affinity batches may deep-chain on a CPU backend when the
            # workload is deduping — the chain work then lands on [C]-wide
            # rep tables, not the [B, T, N] full-path planes that measured
            # a 2× LOSS chained on 1 core
            self._last_dedup = classes is not None
            return jt["batch"](
                batch, dsnap, upd, nom_rows, nom_req, delta, host_auxes,
                order, gang_seg, coupling, self.rng_key, classes,
            ), "batch"
        self._last_dedup = False
        return jt["greedy"](
            batch, dsnap, upd, nom_rows, nom_req, delta, host_auxes, order,
            gang_seg, self.rng_key,
        ), "scan"

    def _dedup_classes(self, batch, host_auxes, fw=None):
        """Identity-class dedup gate + sticky-padded classes for the batch
        engine (framework/podbatch.py identity_classes).

        Dedup is sound when every input to a pod's filter/score planes is
        carried by its compiled batch rows OR mirrored exactly by the class
        rep view: (anti)affinity/spread content is ADMITTED since round 12
        — the coupled plugins' rep auxes track the round's commits via
        their ``update_batch_classes`` hooks (bit-exact: cross tensors are
        pure functions of the two pods' classes, so the full path's per-pod
        aux rows stay class-uniform), and InterPodAffinity's [G, B] host
        match matrix gathers to the rep view via ``host_aux_take`` (its
        columns are class content too).  Still excluded: per-pod tie noise
        (rng_key), host auxes without a rep-view hook (volume masks encode
        per-pod PVC state NOT in the batch arrays), gang-anchoring batches,
        and — for coupled batches only — preemption-capable pods (the
        affinity-dedup fused variant materializes no pod-level auxes for
        the bind phase's candidate program to consume).  Coscheduling's
        host aux is admitted when no batch pod anchors a gang (the anchor
        vector is then uniformly negative; under a mesh the caller passes
        the pre-device_put host arrays so this read never costs a device
        round).  Returns ``(class_of i32[B], rep_rows i32[Cp])`` or None
        (full path); every None increments
        ``scheduler_dedup_fallback_total{reason}``.

        Cp is the pow-2 bucket of the class count (floor 4, repeated first
        rep — duplicate classes compute redundant but harmless plane rows)
        so class-count jitter inside a bucket never changes compiled
        shapes; a heterogeneous batch (C > B/2: Cp would be ~B, the dedup
        planes as wide as the full path's plus gather overhead) takes the
        full path instead.
        """
        if self.rng_key is not None:
            m.dedup_fallback.inc(("rng_key",))
            return None
        coupled = getattr(batch, "has_affinity", False) or \
            getattr(batch, "has_spread", False)
        if coupled:
            if fw is None:
                m.dedup_fallback.inc(("class_hook",))
                return None
            for pw in fw.plugins:
                p = pw.plugin
                if p.dynamic and (
                        getattr(p, "update", None) is not None
                        or getattr(p, "update_batch", None) is not None) \
                        and getattr(p, "update_batch_classes", None) is None:
                    m.dedup_fallback.inc(("class_hook",))
                    return None
            # the affinity-dedup fused variant skips the pod-level auxes
            # entirely (see _build_jitted) — a failing preemption-capable
            # pod would find no aux state for its candidate program
            if self._batch_can_preempt(batch):
                m.dedup_fallback.inc(("preemption",))
                return None
        for name, aux in (host_auxes or {}).items():
            if aux is None:
                continue
            if name == "Coscheduling":
                anchor = np.asarray(aux[1])
                if anchor.size == 0 or int(anchor.max()) < 0:
                    continue
                m.dedup_fallback.inc(("gang_anchor",))
                return None
            if fw is not None and any(
                    pw.plugin.name == name
                    and getattr(pw.plugin, "host_aux_take", None) is not None
                    for pw in fw.plugins):
                continue  # exact rep view available (e.g. the IPA match)
            m.dedup_fallback.inc(("pod_indexed_aux",))
            return None
        from .framework.podbatch import identity_classes

        class_of, reps = identity_classes(batch)
        if len(reps) * 2 > batch.size:
            m.dedup_fallback.inc(("heterogeneous",))
            return None
        m.identity_class_count.observe(len(reps))
        cpad = _pow2(len(reps), 4)
        padded = np.full(cpad, reps[0], dtype=np.int32)
        padded[: len(reps)] = reps
        return class_of, padded

    def engine_choice(self, batch):
        """The auto/batch/scan routing decision as ONE shared predicate:
        (mode, coupling, partition info).  The whatif engine routes its
        fork solves through this SAME method — the bit-for-bit parity
        contract (predicted == actual bindings) depends on the two paths
        never drifting, so the decision must not be duplicated.

        Since round 12 the partition is first run through the
        parallel-safe relaxation (_relax_parallel_safe): a single-class
        component whose only intra-class effects are used-node-mask-
        equivalent or plane-uniform loses its ``multi`` flags, so its pods
        bid in parallel auction rounds like plain pods — the templated
        anti/required-affinity suites collapse from one-commit-per-round
        serialization to contention-bounded rounds."""
        from .framework.conflict import conflict_components
        from .framework.runtime import coupling_flags

        mode = self.assign_mode
        if mode not in ("auto", "batch"):
            return "scan", None, None
        info = conflict_components(
            batch.pods, batch.size,
            namespace_labels=self.namespace_labels,
        )
        info = self._relax_parallel_safe(info)
        coupling = coupling_flags(batch, info=info)
        n_valid = max(int(np.asarray(batch.valid).sum()), 1)
        # serial work in the auction is bounded by the LARGEST component,
        # so that — not the coupled fraction — is what the threshold
        # compares; a batch that is one giant chain still takes the scan
        if mode == "batch" or info.max_multi <= max(
                1, int(self.coupled_fraction_threshold * n_valid)):
            return "batch", coupling, info
        # a scan-bound batch whose content still admits identity-class
        # dedup takes the auction anyway: the component-head rule commits
        # one component pod per round against fresh dense planes — scan-
        # identical bindings (pinned in test_batch_assign) at [C, N]
        # deduped round cost instead of the scan's per-step [B, ...] aux
        # rewrites.  The caller re-checks the full gate with host auxes;
        # this cheap precheck only needs the class count.
        if self._dedup_precheck(batch):
            return "batch", coupling, info
        return "scan", coupling, info

    def _batch_can_preempt(self, batch) -> bool:
        """Any valid batch pod that could run the preemption dry-run —
        shared by the dedup gate and its router precheck so the two never
        drift."""
        prios = np.asarray(batch.priority)[np.asarray(batch.valid)]
        return bool(prios.size) and int(prios.max()) > 0 and any(
            (p.spec.priority or 0) > 0
            and p.spec.preemption_policy != "Never"
            for p in batch.pods)

    def _dedup_precheck(self, batch) -> bool:
        """Host-auxless precheck of the dedup gate, for the router's
        scan→auction upgrade: everything _dedup_classes checks that can be
        known before host_prepare — keyless instance, class hooks on every
        updating dynamic plugin, no gang members (their Coscheduling
        anchor refuses the gate later), no volume-carrying pods (the
        VolumeBinding host aux is pod-indexed), no preemption-capable
        pods, class count under B/2.  A residual mismatch (an exotic
        pod-indexed aux) costs one full-path auction dispatch instead of
        the scan — never an unsound dedup (the full gate still decides)."""
        if self.rng_key is not None:
            return False
        fw = next(iter(self._fws.values()), None)
        if fw is None:
            return False  # nothing dispatched yet: no hook evidence
        for pw in fw.plugins:
            p = pw.plugin
            if p.dynamic and (
                    getattr(p, "update", None) is not None
                    or getattr(p, "update_batch", None) is not None) \
                    and getattr(p, "update_batch_classes", None) is None:
                return False
        from .gang import POD_GROUP_LABEL

        for p in batch.pods:
            if POD_GROUP_LABEL in p.metadata.labels:
                return False
            if getattr(p.spec, "volumes", None):
                return False
            # DRA host aux is pod-indexed (per-pod claim pins/blocks): the
            # full gate would refuse it, so the upgrade is wasted work
            if getattr(p.spec, "resource_claims", None):
                return False
        if self._batch_can_preempt(batch):
            return False
        from .framework.podbatch import identity_classes

        class_of, reps = identity_classes(batch)
        return len(reps) * 2 <= batch.size

    def _relax_parallel_safe(self, info):
        """Demote parallel-safe single-class components to singletons (see
        engine_choice).  Exactness relative to the auction's contract: a
        rival's commit in such a component either (a) blocks exactly the
        rival's own node — required anti whose self-matching terms have
        SINGLETON live domains, already excluded by the one-commit-per-node
        used mask — or (b) shifts the class's plane UNIFORMLY over its
        choice set — (anti)affinity whose self-matching terms see at most
        ONE live domain value — which min-max normalization erases.  What
        remains is the same accepted cross-pod divergence plain contended
        pods already have (resource-score drift within a round)."""
        import dataclasses

        reps = info.single_class_reps or {}
        if not reps:
            return info
        safe = [r for r, rep in reps.items()
                if self._class_parallel_safe(rep)]
        if not safe:
            return info
        comp = info.comp.copy()
        multi = info.multi.copy()
        for r in safe:
            idxs = np.nonzero((comp == r) & multi)[0]
            multi[idxs] = False
            comp[idxs] = idxs
        sizes = [int(((comp == r) & multi).sum())
                 for r in sorted(set(comp[multi].tolist()))]
        return dataclasses.replace(
            info, comp=comp, multi=multi, sizes=sizes,
            single_class_reps={k: v for k, v in reps.items()
                               if k not in safe})

    def _class_parallel_safe(self, rep) -> bool:
        """May pods of this (single-class, gang-free) component commit in
        the same auction round?  True when every SELF-matching term's
        intra-class effect is used-node-equivalent or plane-uniform (see
        _relax_parallel_safe); terms that don't match the class itself
        have no intra-batch effect in a single-class component and are
        ignored.  Spread constraints' per-domain skew math is neither, so
        any self-matching constraint refuses."""
        from .api.labels import affinity_term_matches, match_label_selector

        for c in rep.spec.topology_spread_constraints:
            if match_label_selector(c.label_selector, rep.metadata.labels):
                return False
        aff = rep.spec.affinity
        if aff is None:
            return True
        pa, paa = aff.pod_affinity, aff.pod_anti_affinity
        groups = (
            ("anti_req", list(paa.required) if paa else []),
            ("aff_req", list(pa.required) if pa else []),
            ("pref", ([wt.pod_affinity_term for wt in pa.preferred]
                      if pa else [])
             + ([wt.pod_affinity_term for wt in paa.preferred]
                if paa else [])),
        )
        for kind, terms in groups:
            for term in terms:
                if not affinity_term_matches(term, rep, rep,
                                             self.namespace_labels):
                    continue
                n_keyed, n_vals, n_nodes = self._slot_domain_profile(
                    term.topology_key)
                if kind == "anti_req":
                    # a rival's commit blocks its node's whole domain:
                    # used-mask-equivalent iff every keyed node's value is
                    # unique (hostname-style topology)
                    if n_keyed != n_vals:
                        return False
                elif kind == "aff_req":
                    # filter (pods_exist) + score (hardPodAffinityWeight)
                    # deltas land on the whole single domain = the entire
                    # choice set (unkeyed nodes are statically infeasible)
                    if n_vals > 1:
                        return False
                else:
                    # preferred terms never filter, so the choice set is
                    # ALL nodes: the ±w delta is uniform only when every
                    # valid node carries the one value (or none do)
                    if n_vals > 1 or (n_vals == 1 and n_keyed != n_nodes):
                        return False
        return True

    def _slot_domain_profile(self, topo_key: str):
        """(keyed-node count, distinct live values, valid-node count) for a
        topology key over the encoder's live node mirror — the host-side
        evidence _class_parallel_safe needs.  An unregistered key has no
        keyed nodes (terms over it contribute nothing)."""
        enc = self.encoder
        valid = np.asarray(enc.node_valid)
        n_nodes = int(valid.sum())
        slot = enc._topo_slots.get(topo_key)
        if slot is None:
            return 0, 0, n_nodes
        from .state.dictionary import MISSING

        vals = np.asarray(enc.node_topo)[valid, slot]
        present = vals != MISSING
        return (int(present.sum()), int(np.unique(vals[present]).size),
                n_nodes)

    def _noop_delta(self, like_batch, with_groups: bool = False):
        """No-op PrevBatch (all rows -1) with the SAME array shapes as a
        real one built from ``like_batch``, so shallow and deep cycles share
        one compiled program per batch shape.  ``with_groups`` zero-fills
        the four affinity term groups too (all-invalid terms — semantically
        inert) so a cycle mixing real affinity carries with noop padding
        keeps ONE pytree structure instead of compiling per slot-combination."""
        from .framework.runtime import PrevBatch

        group_names = ("req_affinity", "req_anti_affinity",
                       "pref_affinity", "pref_anti_affinity")
        gshapes = None
        if with_groups:
            gshapes = tuple(
                np.asarray(leaf).shape
                for name in group_names
                for leaf in jax.tree_util.tree_leaves(getattr(like_batch, name))
            )
        key = (like_batch.request.shape, like_batch.label_keys.shape, gshapes)
        cached = getattr(self, "_noop_prev_cache", None)
        if cached is None or cached[0] != key:
            b = like_batch.valid.shape[0]
            groups = {}
            if with_groups:
                # zeroed groups are semantically inert: every term row is
                # invalid (valid=False gates all matching)
                groups = {
                    name: jax.tree_util.tree_map(
                        lambda a: np.zeros_like(np.asarray(a)),
                        getattr(like_batch, name))
                    for name in group_names
                }
            cached = (key, PrevBatch(
                rows=np.full(b, -1, dtype=np.int32),
                req=np.zeros_like(like_batch.request),
                nz=np.zeros_like(like_batch.non_zero),
                valid=np.zeros(b, dtype=bool),
                label_keys=np.full_like(like_batch.label_keys, -1),
                label_vals=np.full_like(like_batch.label_vals, -1),
                ns=np.full(b, -1, dtype=np.int32),
                **groups,
            ))
            self._noop_prev_cache = cached
        return cached[1]

    def _capture_walk_state(self):
        """Snapshot every live structure the extender round walk reads —
        taken on the DISPATCH thread, before an async walk spawns, so the
        bind phase and the store event pump (cache NodeInfo mutations, node
        add/remove) can never mutate them under the walk thread.  node
        objects are only materialized when a non-nodeCacheCapable extender
        will need manifests."""
        name_of = dict(self.encoder.row_to_name())
        row_of = dict(self.encoder.node_rows)
        alloc = np.array(self.encoder.allocatable, dtype=np.float64)
        requested = np.array(self.encoder.requested, dtype=np.float64)
        node_objs = None
        if any((e.cfg.filter_verb or e.cfg.prioritize_verb)
               and not e.cfg.node_cache_capable for e in self.extenders):
            node_objs = {
                name: info.node
                for name, info in self.cache._nodes.items()
                if info.node is not None
            }
        return name_of, row_of, alloc, requested, node_objs

    def _assign_with_extenders(
        self, fw, jt, batch, dsnap, dyn, auxes, pods, t0: float, packed0=None,
        nom=None, captured=None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """ROUND-BASED extender assignment (findNodesThatPassExtenders
        scheduler.go:1035 + extender prioritize merge :1146-1185).

        Each round is ONE dense device program (+ one fetch): every
        unresolved pod's mask/score rows land on host together, the host
        walks pods in order doing extender filter/prioritize callouts, and
        all of the round's accepts apply in ONE batched state-update program.
        The previous per-pod compute_row cadence paid a ~100ms tunnel round
        per pod (~13s per 128-pod batch with one extender); rounds cost two
        device rounds each and an uncoupled batch resolves in one round.

        Round-exactness: at most one pod commits per node per round, so
        node-local filters checked against round-start state stay valid; a
        host-side resource ledger re-checks the fit with the round's earlier
        accepts applied, deferring pods that no longer fit to the next round;
        a cross-pod-coupled pod commits only as its CONFLICT COMPONENT's
        first accept of the round (framework/conflict.py — pods in other
        components never write its tables), and a required-anti-affinity
        commit closes only its own component — exact greedy state relative
        to the component, as in batch_assign.

        Returns (node_row, per-pod algorithm latency measured from t0 to the
        pod's own round's decision, rounds executed, callout wall).  The
        walk reads NO live scheduler state — every encoder mirror and cache
        object it needs comes from ``captured`` (_capture_walk_state),
        taken on the DISPATCH thread before the async walk spawns — so the
        async path (``async_extenders``) may run it on a background thread
        while the bind phase / event pump / next snapshot sync mutate the
        live structures: chained == sync bindings by construction."""
        import json as _json

        from .extender import ExtenderError, _node_to_dict
        from .framework.runtime import coupling_flags

        b = batch.valid.shape[0]
        out = np.full(b, -1, dtype=np.int32)
        algo_lat = np.zeros(b)
        if captured is None:  # synchronous walk: no concurrent mutator
            captured = self._capture_walk_state()
        name_of, row_of, alloc, requested, node_objs = captured
        requested = np.array(requested)  # walk-local ledger (mutated below)
        _cpl = coupling_flags(batch, namespace_labels=self.namespace_labels)
        reads, solo = _cpl.reads, _cpl.solo
        cpl_comp, cpl_multi = _cpl.comp, _cpl.multi
        # per-feasible-set callout fragments: templated pods share mask
        # rows, so the name list AND its JSON encoding build once per
        # distinct row per walk instead of once per pod per round — at
        # ~8KB of names per callout the encode was a measured slice of
        # the single-core extender suite's wall (identity-class dedup
        # applied to the callout payloads)
        feas_cache: Dict[tuple, tuple] = {}  # (round, mask-row bytes) → hit
        names_json_cache: Dict[tuple, bytes] = {}

        def names_bytes(names) -> bytes:
            key = tuple(names)
            v = names_json_cache.get(key)
            if v is None:
                v = names_json_cache[key] = _json.dumps(names).encode()
            return v

        # non-nodeCacheCapable extenders receive full node manifests
        # (ExtenderArgs.Nodes — extender.go:416) for BOTH verbs; capable
        # ones get the name-list fast path (:277).  node_objs came from the
        # dispatch-thread capture, so the async walk never reads the live
        # cache.
        node_manifests = None
        if node_objs is not None:
            manifest_cache: Dict[tuple, bytes] = {}

            def node_manifests(names):
                key = tuple(names)
                got = manifest_cache.get(key)
                if got is None:
                    got = manifest_cache[key] = _json.dumps(
                        [_node_to_dict(node_objs[n])
                         for n in names if n in node_objs]).encode()
                return got

        callout_wait = 0.0
        if nom is not None:
            nom_rows = np.asarray(nom[0])
            nom_req = np.asarray(nom[1], dtype=np.float64)
            rows_ = np.clip(nom_rows, 0, requested.shape[0] - 1)
            np.add.at(requested, rows_,
                      np.where((nom_rows >= 0)[:, None], nom_req, 0.0))
        req_pod = np.asarray(batch.request, dtype=np.float64)  # [B, R]
        unresolved = [i for i in range(len(pods)) if bool(batch.valid[i])]
        # reference candidate-list bound for extender callouts (see
        # _num_feasible_nodes): the per-round WINDOW rotates by k_cap so
        # successive rounds (and retries) sweep the whole feasible set,
        # the analog of the reference's nextStartNodeIndex rotation
        n_live = len(name_of)
        k_cap = _num_feasible_nodes(n_live)
        no_prog_rounds = 0
        rounds = 0
        while unresolved and rounds <= b:
            rounds += 1
            if rounds == 1 and packed0 is not None:
                packed = np.asarray(packed0)  # rode the fused first program
            else:
                packed = np.asarray(
                    jt["compute_packed"](batch, dsnap, dyn, auxes))
            mask = np.isfinite(packed)
            scores = packed
            # claim membership as a bool plane + count: a per-pod np.isin
            # against a growing set was O(B²·N) per round (measured as the
            # walk's dominant term at B=512)
            claimed_mask = np.zeros(alloc.shape[0], dtype=bool)
            n_claimed = 0
            claimed_comps: Set[int] = set()  # components with a commit this round
            closed_comps: Set[int] = set()  # components a solo commit closed
            commit = np.zeros(b, dtype=bool)
            choice = np.zeros(b, dtype=np.int32)
            still: List[int] = []
            deferred_only = True

            # Concurrent extender callouts for the whole round (the
            # reference fans extender prioritizers out in goroutines,
            # scheduler.go:1146-1179; 16 matches its default parallelism):
            # each pod's filter runs against its round-start feasible list;
            # the sequential walk below then picks within the APPROVED list
            # minus same-round claims, so protocol semantics are unchanged.
            def window(feas, i):
                """Reference candidate sampling: cap the rows shipped to
                extenders at k_cap.  The window is STRIPED across the batch
                (pods land in ⌈feasible/k_cap⌉ window groups) so one round
                still covers the whole batch — a single shared window would
                bound commits per round at k_cap and buy extra device
                rounds — and rotates per round so retries sweep the whole
                feasible set (the nextStartNodeIndex analog).  Returns
                (rows, window count): n_win > 1 marks a capped view, and
                the caller's retry bound must cover ALL n_win windows
                before declaring a pod unschedulable."""
                if len(feas) <= k_cap:
                    return feas, 1
                n_win = -(-len(feas) // k_cap)
                start = (((i % n_win) + rounds - 1) * k_cap) % len(feas)
                idx = (np.arange(k_cap) + start) % len(feas)
                return feas[idx], n_win

            def callout(i):
                pod = pods[i]
                if serialize and n_claimed:
                    # serialized cadence: the sent list reflects the
                    # round's earlier accepts (nodes the live ledger says
                    # no longer fit are dropped), approximating the
                    # reference's assumed-snapshot view between sequential
                    # scheduleOne calls — per-pod, never cached
                    feas = np.where(mask[i])[0]
                    live = np.all(
                        (req_pod[i] == 0)
                        | (req_pod[i] <= alloc[feas] - requested[feas]),
                        axis=1,
                    )
                    feas, n_win = window(feas[live], i)
                    row_names = [name_of[r] for r in feas if r in name_of]
                    row_json = None
                else:
                    nfeas = int(np.count_nonzero(mask[i]))
                    n_win = max(1, -(-nfeas // k_cap))
                    key = (rounds, i % n_win, mask[i].tobytes())
                    hit = feas_cache.get(key)
                    if hit is None:
                        feas, n_win = window(np.where(mask[i])[0], i)
                        row_names = [name_of[r] for r in feas
                                     if r in name_of]
                        hit = feas_cache[key] = (
                            feas, row_names,
                            _json.dumps(row_names).encode(), n_win)
                    feas, row_names, row_json, n_win = hit
                # managed-resources gating (extender.go:444-471): extenders
                # not interested in this pod are skipped entirely
                exts = [e for e in self.extenders if e.is_interested(pod)]
                try:
                    names = row_names
                    names_json = row_json
                    for ext in exts:
                        names, _failed = ext.filter(
                            pod, names, names_json=names_json,
                            node_manifests=node_manifests)
                        names_json = None  # reply lists re-encode (cached)
                        if not names:
                            break
                    ranked_total: Dict[str, float] = {}
                    echoed = names == row_names
                    if names:
                        # every extender echoed the request list → its
                        # cached encoding serves the prioritize callout too
                        pr_json = (row_json if echoed and row_json is not None
                                   else names_bytes(names))
                        for ext in exts:
                            try:
                                for n, s in ext.prioritize(
                                        pod, names, names_json=pr_json,
                                        node_manifests=node_manifests,
                                ).items():
                                    ranked_total[n] = ranked_total.get(n, 0.0) + s
                            except ExtenderError:
                                continue  # prioritize errors ignored (:1152)
                    # rows fast path for the pick stage: every extender
                    # echoed the request list (the common approve-all
                    # reply), so the approved rows ARE the cached window —
                    # one list compare replaces
                    # per-callout O(K) name→row dict walks
                    rows_hint = feas if echoed else None
                    return names, rows_hint, ranked_total, None, n_win
                except ExtenderError as e:
                    # non-ignorable → pod unschedulable
                    return None, None, None, e, n_win

            # serialize_extender_callouts (see __init__): a stateful extender
            # (managedResources) must see requests in commit order, AFTER
            # earlier accepts — callouts then run lazily inside the walk
            # below instead of concurrently at round start
            mode = self.serialize_extender_callouts
            serialize = mode == "always" or (
                mode == "auto"
                and any(getattr(e.cfg, "managed_resources", None)
                        for e in self.extenders)
            )
            if serialize or len(unresolved) <= 1:
                results = {}  # filled on demand, in commit order
            else:
                t_w = self.clock()
                results = dict(zip(
                    unresolved, self._ext_pool().map(callout, unresolved)))
                callout_wait += self.clock() - t_w

            for i in unresolved:
                pod = pods[i]
                # batch_assign rule (c), per component: a required-anti
                # commit invalidates its COMPONENT-mates' later rows this
                # round (other components never read its tables)
                if cpl_multi[i] and int(cpl_comp[i]) in closed_comps:
                    still.append(i)
                    continue
                # a reader's row is only exact when no COMPONENT-mate
                # committed before it this round
                if reads[i] and cpl_multi[i] \
                        and int(cpl_comp[i]) in claimed_comps:
                    still.append(i)
                    continue
                if i in results:
                    approved, rows_hint, ranked, err, n_win = results[i]
                else:
                    t_w = self.clock()
                    approved, rows_hint, ranked, err, n_win = callout(i)
                    callout_wait += self.clock() - t_w
                if err is not None:
                    algo_lat[i] = self.clock() - t0
                    m.scheduling_algorithm_duration.observe(algo_lat[i])
                    deferred_only = False
                    continue
                # vectorized pick over the approved rows (the per-name
                # python loops here were ~1s of a 256-pod round's 2s):
                # ledger re-check drops nodes the round's earlier accepts
                # already filled (resource dims only — node-local sets are
                # safe under the one-commit-per-node rule)
                if rows_hint is not None:
                    rows = rows_hint
                else:
                    rows = np.fromiter(
                        (row_of[n] for n in approved), dtype=np.int64,
                        count=len(approved),
                    )
                ok = ~claimed_mask[rows]
                fits = np.all(
                    (req_pod[i] == 0)
                    | (req_pod[i] <= alloc[rows] - requested[rows]),
                    axis=1,
                )
                ok &= fits
                if not ok.any():
                    # nothing left this round; if other pods committed (or
                    # the pod saw only a CAPPED window of its feasible set
                    # and the rotation hasn't yet swept ALL of its n_win
                    # windows), the next round differs — retry, else
                    # unschedulable.  The bound covers every window: a pod
                    # whose extender only approves nodes deep in the
                    # rotation must see each window once before giving up.
                    if n_claimed or still or (
                            n_win > 1 and no_prog_rounds < n_win):
                        still.append(i)
                    else:
                        algo_lat[i] = self.clock() - t0
                        m.scheduling_algorithm_duration.observe(algo_lat[i])
                        deferred_only = False
                    continue
                merged = scores[i, rows]
                if ranked:
                    merged = merged + np.fromiter(
                        (ranked.get(n, 0.0) for n in approved),
                        dtype=np.float64, count=len(approved),
                    )
                merged = np.where(ok, merged, -np.inf)
                row = int(rows[int(np.argmax(merged))])
                out[i] = row
                commit[i] = True
                choice[i] = row
                claimed_mask[row] = True
                n_claimed += 1
                requested[row] += req_pod[i]
                algo_lat[i] = self.clock() - t0
                m.scheduling_algorithm_duration.observe(algo_lat[i])
                deferred_only = False
                if cpl_multi[i]:
                    claimed_comps.add(int(cpl_comp[i]))
                    if solo[i]:
                        closed_comps.add(int(cpl_comp[i]))  # rule (c)
            if commit.any() and still:
                # the committed state only feeds LATER rounds; the final
                # round's device update would be dead weight (the next
                # batch's dispatch re-syncs from the authoritative store)
                dyn, auxes = jt["apply_commits"](
                    batch, dsnap, dyn, auxes, commit, choice
                )
            # progress: `still` non-empty implies a commit happened this
            # round OR a capped window is still sweeping (bounded by the
            # no_prog_rounds counter above); the rounds <= b condition is
            # the hard bound either way
            no_prog_rounds = 0 if n_claimed else no_prog_rounds + 1
            unresolved = still
        for i in unresolved:  # pods left at the rounds bound
            algo_lat[i] = self.clock() - t0
            m.scheduling_algorithm_duration.observe(algo_lat[i])
        return out, algo_lat, rounds, callout_wait

    def _ext_pool(self):
        """Persistent extender-callout thread pool.  The previous per-round
        ``with ThreadPoolExecutor(16)`` spawned and JOINED 16 threads every
        round on the extender suite's critical path; a long-lived pool keeps
        the workers (and their warmed keep-alive sockets in the extender's
        connection pool) across rounds and batches.  16 workers matches the
        reference's extender fan-out AND is measured, not vestigial: a
        round-12 A/B at 4 workers on the 1-core container LOST 2× — the
        workers' lock waits are idle time with the GIL released (the
        extender subprocess runs during them), so deep pipelining is what
        keeps the wire full.  Released by close()."""
        with self._ext_pool_lock:
            pool = self._ext_pool_obj
            if pool is None:
                from concurrent.futures import ThreadPoolExecutor

                pool = self._ext_pool_obj = ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix="extender-callout")
            return pool

    def _fence_ok(self) -> bool:
        """Evaluate the bind fence; an unprovable fence (predicate raised)
        is a failed fence, mirroring LeaderElector's release-on-doubt."""
        try:
            return bool(self.fence())
        except Exception as e:
            klog.V(1).info_s("Bind fence predicate failed; treating as "
                             "fenced out", error=f"{type(e).__name__}: {e}")
            return False

    def abandon_inflight(self) -> None:
        """Outgoing-leader stop-work hook (wire as the elector's
        ``on_stopped_leading``): a replica that lost its lease mid-cycle
        must not carry dispatched-but-unbound work into a window where a
        new leader schedules the same pods.  Drops every in-flight batch
        (pods requeue through the failure handler; their device decisions
        are never fetched), rolls back binding cycles held open at Permit
        (the gang group-failure hook requeues whole gangs atomically), and
        clears cross-cycle nominated reservations — the new leader
        re-derives its own.  The bind-time fence (``fence``) covers the
        race this hook cannot: work already past Permit when the lease was
        lost."""
        self._join_sync_ahead()
        rec, self._sync_ahead = self._sync_ahead, None
        if rec is not None and rec.error is None and rec.upd is not None:
            # un-consume the payload: if this replica ever schedules again,
            # its next upload must still carry these rows
            self.encoder.restore_dirty(rec.consumed)
        inflight, self._inflight_q = self._inflight_q, []
        for fl in inflight:
            if fl.fetch_thread is not None:
                fl.fetch_thread.join()  # let the bg fetch land before discard
            if fl.span is not None:
                fl.span.set(error="abandoned: leadership lost").finish()
            for qi in fl.infos:
                self._requeue_after_failure(qi)
        if inflight:
            m.scheduler_retries.inc(
                ("leadership_lost",),
                by=sum(len(fl.infos) for fl in inflight))
        for uid in list(self._waiting_binds):
            wb = self._waiting_binds.get(uid)
            self._cancel_waiting_bind(uid)
            if wb is not None:
                self._requeue_after_failure(wb.qi)
        self._nominated.clear()
        self._fastbound_noms.clear()
        klog.V(1).info_s("Leadership lost; in-flight scheduling work "
                         "abandoned", batches=len(inflight))

    def close(self, flush_events: bool = True) -> None:
        """Release long-lived resources: the store watch and the persistent
        extender-callout pool (its 16 workers otherwise live to interpreter
        exit — processes that build many schedulers, e.g. the perf harness
        or the chaos soak, must not accumulate them).  Flushes the event
        recorder's retained failed writes (client/events.py) so a CLEAN
        shutdown bounds event loss; ``flush_events=False`` is the simulated
        process DEATH form (recovery/failover) — a dead process writes
        nothing, its retained events are simply lost.  Idempotent."""
        unwatch, self._unwatch = getattr(self, "_unwatch", None), None
        if unwatch is not None:
            unwatch()
        self._join_sync_ahead()  # no background sync may outlive the watch
        recorder = getattr(self, "recorder", None)
        if recorder is not None and flush_events:
            recorder.flush()
        with self._ext_pool_lock:
            pool, self._ext_pool_obj = self._ext_pool_obj, None
        if pool is not None:
            pool.shutdown(wait=False)

    def _run_reserve_and_bind(self, fw, pod: v1.Pod, node_name: str,
                              qi: Optional[QueuedPodInfo] = None,
                              span_ctx=None):
        """Reserve → Permit → PreBind → Bind → PostBind (scheduler.go:584-698).

        Returns True (bound), False (rejected, rolled back), or the
        _PERMIT_WAIT sentinel: a Permit plugin with ``holds_on_wait`` (the
        gang Coscheduling plugin) left the pod pending — the assume and the
        reserve are KEPT, the rest of the binding cycle is deferred to
        _flush_waiting_binds (released when the gang completes, rolled back
        when the wait deadline fires).  A plain Wait (no holding plugin)
        keeps the synchronous-sim contract: the cycle fails and the pod
        retries after backoff.  On any failure, already-reserved plugins
        are unreserved in reverse order.
        """
        from .framework.interface import Code

        reserved = []

        def rollback():
            # the waiting-pod entry dies with its binding cycle
            # (runtime/framework.go removes it from waitingPods either way)
            self.waiting_pods.remove(pod.uid)
            for done in reversed(reserved):
                un = getattr(done.plugin, "unreserve", None)
                if un is not None:
                    un(None, pod, node_name)

        for pw in fw.reserve_plugins:
            status = pw.plugin.reserve(None, pod, node_name)
            if status is not None and not status.is_success():
                rollback()
                return False
            reserved.append(pw)
        # Permit: plugins may Wait with a timeout (waiting_pods_map analog)
        if fw.permit_plugins:
            holding = False
            for pw in fw.permit_plugins:
                status, timeout = pw.plugin.permit(None, pod, node_name)
                if status is not None and status.code == Code.WAIT:
                    self.waiting_pods.add(pod, pw.plugin.name, timeout)
                    holding = holding or getattr(
                        pw.plugin, "holds_on_wait", False)
                elif status is not None and not status.is_success():
                    rollback()
                    return False
            reason = self.waiting_pods.wait_on_permit(pod)
            if reason is not None:
                if holding and qi is not None \
                        and self.waiting_pods.get(pod.uid) is not None:
                    # still pending (not rejected): hold the binding cycle
                    # open — gang members keep their node until the last
                    # sibling releases them or the deadline fires
                    self._waiting_binds[pod.uid] = _WaitingBind(
                        qi=qi, node_name=node_name, fw=fw,
                        reserved=reserved, since=self.clock(),
                        ctx=span_ctx)
                    self.gangs.note_waiting(pod, node_name)
                    return _PERMIT_WAIT
                rollback()
                return False
        return self._finish_bind(fw, pod, node_name, reserved,
                                 span_ctx=span_ctx)

    def _finish_bind(self, fw, pod: v1.Pod, node_name: str,
                     reserved: List, span_ctx=None) -> bool:
        """The post-Permit half of the binding cycle (PreBind → Bind →
        PostBind), shared by the synchronous path and the waiting-bind
        flush; rolls back ``reserved`` on failure.  ``span_ctx`` is the
        attempt-tree context handed to the store so its WAL append/fsync
        spans link under this bind (sim/store.py bind_pod)."""

        def rollback():
            self.waiting_pods.remove(pod.uid)
            for done in reversed(reserved):
                un = getattr(done.plugin, "unreserve", None)
                if un is not None:
                    un(None, pod, node_name)

        for pw in fw.pre_bind_plugins:
            status = pw.plugin.pre_bind(None, pod, node_name)
            if status is not None and not status.is_success():
                rollback()
                return False
        if self.fence is not None and not self._fence_ok():
            # fencing token moved on (leadership lost/stolen since this
            # cycle dispatched): refuse the shared-state write.  Transient
            # semantics on purpose — the pod requeues to backoff, and only
            # a replica that actually holds the lease will retry the bind.
            m.scheduler_retries.inc(("fence_reject",))
            klog.V(1).info_s("Bind refused by leadership fence",
                             pod=pod.key(), node=node_name)
            rollback()
            raise _TransientBindError("fencing check failed: not the "
                                      "current leader")
        try:
            if self.tracer.enabled and self._bind_takes_trace:
                ok = self.store.bind_pod(pod.namespace, pod.metadata.name,
                                         node_name, trace_parent=span_ctx)
            else:
                ok = self.store.bind_pod(pod.namespace, pod.metadata.name,
                                         node_name)
        except Exception as e:
            # transport fault that outlived the client's retries: rollback,
            # then surface as _TransientBindError so the caller requeues to
            # BACKOFF (timer retry) rather than unschedulableQ (event wait).
            # Chaos faults inject BEFORE the store mutation, so a failed
            # bind provably did not half-apply (no double-bind ambiguity).
            m.scheduler_retries.inc(("bind_error",))
            klog.V(1).info_s("Bind failed; pod will retry",
                             pod=pod.key(), node=node_name,
                             error=f"{type(e).__name__}: {e}")
            rollback()
            raise _TransientBindError(str(e)) from e
        if not ok:
            # binding-cycle error (e.g. pod deleted mid-cycle) unreserves too,
            # else VolumeBinding assume-state leaks (scheduler.go:676-689)
            rollback()
            return False
        # kill-point: the store bind LANDED but every in-memory consequence
        # (finish_binding TTL, gang on_bound, events, queue bookkeeping) is
        # lost — the nastiest restart state: recovery must treat the pod as
        # bound (store truth) and never bind it again
        from .chaos.faults import maybe_crash

        maybe_crash("crash.mid_bind")
        for pw in fw.post_bind_plugins:
            pw.plugin.post_bind(None, pod, node_name)
        return True

    def _nominated_arrays(self, batch_uids: Set[str]):
        """Nominated-but-pending pods (not in this batch) as fixed-shape
        arrays for the fused program: rows i32[K] (-1 pad), reqs f32[K, R].
        K is a sticky pow-2 cap so nomination churn never changes shapes."""
        rows, reqs = [], []
        for uid, (node_name, req, _pod) in list(self._nominated.items()):
            if uid in batch_uids:
                continue
            row = self.encoder.node_rows.get(node_name)
            if row is None:
                del self._nominated[uid]
                continue
            rows.append(row)
            reqs.append(req)
        # floor at 2×batch: a preemption burst nominates up to a whole batch
        # at once, and each pow2 K crossing recompiles the fused program
        k = max(_pow2(len(rows), 4), getattr(self, "_nom_cap", _pow2(2 * self.batch_size, 4)))
        self._nom_cap = k
        r = self.encoder.cfg.num_resource_dims
        out_rows = np.full(k, -1, dtype=np.int32)
        out_reqs = np.zeros((k, r), dtype=np.float32)
        if rows:
            out_rows[: len(rows)] = rows
            out_reqs[: len(rows)] = np.asarray(reqs, dtype=np.float32)
        return out_rows, out_reqs

    # static (UnschedulableAndUnresolvable-style) plugins preemption can't fix
    _STATIC_PLUGINS = {"NodeName", "NodeUnschedulable", "TaintToleration", "NodeAffinity"}

    def _infos_block_deep(self, infos: List[QueuedPodInfo]) -> bool:
        """_pods_block_deep with the preemption refinement: a
        preemption-capable pod blocks the deep chain only when it is LIKELY
        to actually preempt — it failed before, or it fits nowhere in the
        current snapshot (a fresh fitting pod, e.g. MixedChurn's
        priority-10 churn pod on a roomy cluster, schedules normally and
        never runs the dry-run).  If the prediction misses and a chained
        preemptor fails, _bind_phase defers preemption to the retry, which
        then blocks — so a preemption dry-run never sees chained-delta
        state it can't evict.

        Soundness of chaining ON such a batch (a later batch B chained on
        this batch A while A still runs bind-phase preemption after a
        prediction miss): B's program can only place pods within A's
        snapshot-view free space (it carries A's deltas), and
        _try_nominated_fast_bind's claimable guard refuses the fast bind
        whenever ANY in-flight pod fits that same snapshot free space — so
        a fast-bound preemptor and a chained batch can never double-book a
        node; the nominate-and-requeue path only FREES resources (victims
        deleted, claim reserved at future dispatches).
        """
        preempt_qis: List[QueuedPodInfo] = []
        for qi in infos:
            p = qi.pod
            if _pod_blocks_static(p):
                return True
            if not self._chain_affinity_now and _pod_has_affinity(p):
                return True  # chain disabled (CPU backend, non-dedup
                # workload): stay shallow
            if (p.spec.priority or 0) > 0 and p.spec.preemption_policy != "Never":
                # pop_batch already counted this attempt: >1 means a retry
                if qi.attempts > 1 or qi.unschedulable_plugins:
                    return True
                preempt_qis.append(qi)
        if not preempt_qis:
            return False
        if not self.pipeline or self.extenders:
            # the result only gates deep chaining; sync/extender modes must
            # not pay the per-pod fit scans below (their dispatch path
            # ignores it) — conservatively block
            return True
        # the fit scan below reads live encoder mirrors the overlapped
        # sync thread may be mid-rewrite — barrier first
        self._join_sync_ahead()
        valid = np.asarray(self.encoder.node_valid)
        free = (self.encoder.allocatable[valid].astype(np.int64)
                - self.encoder.requested[valid])
        seen_fit: Dict[bytes, bool] = {}  # templated pods share request vectors
        for qi in preempt_qis:
            req = np.asarray(self.encoder.pod_request_units(qi.pod))
            key = req.tobytes()
            fit = seen_fit.get(key)
            if fit is None:
                fit = bool(np.any(np.all(
                    (req == 0) | (req[None, :] <= free), axis=1)))
                seen_fit[key] = fit
            if not fit:
                return True
        return False

    def _priority_levels(self):
        """Sorted unique scheduled-pod priorities, padded to the fixed
        PRIORITY_LEVEL_CAP with i32-max, for the segment-sum candidate mask;
        None routes to the dense-einsum fallback (>cap distinct levels)."""
        from .preemption import PRIORITY_LEVEL_CAP

        valid = np.asarray(self.encoder.pod_valid)
        u = np.unique(np.asarray(self.encoder.pod_priority)[valid])
        if u.size > PRIORITY_LEVEL_CAP:
            return None
        out = np.full(PRIORITY_LEVEL_CAP, np.iinfo(np.int32).max,
                      dtype=np.int32)
        out[: u.size] = u
        return out

    def _candidate_mask(self, profile, batch, dsnap, dyn, auxes, levels=None):
        """Preemption candidate mask for a whole batch — the profile's jitted
        program, ONE device round per failing batch (eager plugin.filter
        calls would each pay a ~100ms pacing round on the tunnel)."""
        return self._jitted_by[profile]["cand"](batch, dsnap, dyn, auxes,
                                                levels)

    def _run_post_filter(self, fw, qi: QueuedPodInfo, batch, dsnap, dyn, auxes,
                         i: int, cand_row, pf_ctx):
        """DefaultPreemption PostFilter (scheduler.go:533-552 → preemption.go:138).

        ``cand_row`` bool[N] comes from the per-batch jitted candidate mask;
        ``pf_ctx`` is the batch-hoisted (PDB list, row→name map, row→name
        object ndarray).

        Returns the node name when the preemptor was FAST-BOUND to its
        nominated node within this attempt (_try_nominated_fast_bind), else
        None (nominated-and-requeued, or no preemption happened).
        """
        pod = qi.pod
        if pod.spec.preemption_policy == "Never":
            return
        m.preemption_attempts.inc()
        rows = np.where(cand_row)[0]
        if rows.size == 0:
            return
        pdbs, _name_of, names_arr = pf_ctx
        rows = rows[rows < names_arr.size]
        picked = names_arr[rows]
        names = picked[picked != None].tolist()  # noqa: E711 — elementwise
        nominated: Dict[str, List[v1.Pod]] = {}
        for _uid, (nn, _req, npod) in self._nominated.items():
            nominated.setdefault(nn, []).append(npod)
        from .extender import ExtenderError

        try:
            cand = self.preemption.preempt(
                pod, self.snapshot, names, pdbs, nominated=nominated,
                extenders=self.extenders,
            )
        except ExtenderError:
            # non-ignorable extender failure aborts this preemption attempt
            # (preemption.go callExtenders error path); pod retries later
            return None
        if cand is None:
            return None
        for victim in cand.victims:
            # through the single eviction gate (events + metrics + budget
            # drain), override_pdb per the preemption last-resort contract;
            # pdbs reuses the batch-hoisted list — no per-victim store list
            result = self.eviction_api.evict(
                victim, reason=f"Preempted by {pod.key()}",
                policy="preemption", override_pdb=True, pdbs=pdbs)
            if result.allowed and not result.evicted and result.reason \
                    and result.reason.startswith("store delete failed"):
                # transient store fault mid-preemption: surface it to the
                # call site's degrade-to-nominate-nothing guard, exactly as
                # the raw store.delete used to
                raise RuntimeError(result.reason)
        m.preemption_victims.observe(len(cand.victims))
        pod.status.nominated_node_name = cand.node_name
        self._nominated[pod.uid] = (
            cand.node_name, np.asarray(self.encoder.pod_request_units(pod)), pod
        )
        self.store.update("Pod", pod)
        if self._try_nominated_fast_bind(fw, qi, cand):
            return cand.node_name
        return None

    def _try_nominated_fast_bind(self, fw, qi: QueuedPodInfo, cand) -> bool:
        """Bind a successful preemptor to its nominated node in the SAME
        attempt — the reference's nominated-node fast path
        (scheduler.go:926-935: a nominatedNodeName pod's retry evaluates
        that node first and uses it without re-scoring) compressed to zero
        queue round-trips, which is exact here because sim victims terminate
        instantly at eviction (the reference requeues only to wait out
        graceful termination).  Restricted to PLAIN preemptors with no
        preemption-capable extender in play: for those the dry-run verified
        the full filter suite (statics + resources; ports/volumes/spread/
        affinity are structurally absent), and the live-cache re-check below
        confirms nothing changed between dry-run and now.  All other
        preemptors keep the nominate-and-requeue flow."""
        from .api.resource import compute_pod_resource_request
        from .oracle import (
            fits_resources,
            node_affinity_fits,
            node_name_fits,
            node_schedulable,
            tolerates_all_hard_taints,
        )
        from .preemption import _is_plain_preemptor

        if not self.nominated_fast_bind:
            return False
        pod = qi.pod
        has_anti = bool(self.snapshot.have_pods_with_required_anti_affinity_list)
        if not _is_plain_preemptor(pod, has_anti):
            return False
        if compute_pod_resource_request(pod).scalar_resources:
            return False
        if any(getattr(e, "supports_preemption", False) and e.is_interested(pod)
               for e in self.extenders):
            return False
        # live cache view: the evictions above already flowed through the
        # synchronous store watch into cache NodeInfos
        info = self.cache._nodes.get(cand.node_name)
        if info is None or info.node is None:
            return False
        node = info.node
        if not (node_name_fits(pod, node) and node_schedulable(pod, node)
                and node_affinity_fits(pod, node)
                and tolerates_all_hard_taints(pod, node)
                and fits_resources(pod, info)):
            return False
        # In-flight batches were dispatched against the PRE-eviction
        # snapshot and may be placing pods into this node's then-free space
        # right now — the live cache can't show those placements until
        # their completes.  If any in-flight pod could fit that
        # snapshot-view free space, only fast-bind when the preemptor fits
        # entirely within the resources its evictions freed (leaving the
        # contested free space untouched); otherwise nominate-and-requeue.
        row = self.encoder.node_rows.get(cand.node_name)
        if row is not None and self._inflight_q:
            free_snap = (self.encoder.allocatable[row].astype(np.int64)
                         - self.encoder.requested[row])
            claimable = any(
                bool(np.any(np.all(
                    np.asarray(fl2.batch.request)[np.asarray(fl2.batch.valid)]
                    <= free_snap[None, :], axis=1)))
                for fl2 in self._inflight_q
            )
            if claimable:
                req = compute_pod_resource_request(pod)
                freed = np.zeros(4, dtype=np.int64)
                for victim in cand.victims:
                    vr = compute_pod_resource_request(victim)
                    freed += (vr.milli_cpu, vr.memory,
                              vr.ephemeral_storage, 1)
                need = np.array(
                    [req.milli_cpu, req.memory, req.ephemeral_storage, 1],
                    dtype=np.int64,
                )
                if not bool(np.all(need <= freed)):
                    return False
        pod.status.nominated_node_name = None
        self.cache.assume_pod(pod, cand.node_name)
        try:
            ok = self._run_reserve_and_bind(fw, pod, cand.node_name, qi=qi)
        except _TransientBindError:
            ok = False  # rolled back; fall through to nominate-and-requeue
        if ok is _PERMIT_WAIT:
            # a gang member reached the fast path while its gang is still
            # incomplete (the allows_preemption guard makes this rare):
            # don't hold a preemption fast-bind open — cancel the wait and
            # fall back to nominate-and-requeue
            self._cancel_waiting_bind(pod.uid)
            ok = False
        if not ok:
            self.cache.forget_pod(pod)
            pod.status.nominated_node_name = cand.node_name
            return False
        self.cache.finish_binding(pod)
        return True

    def _diagnose(self, fw, diag_row=None) -> Set[str]:
        """Which plugins reject the pod everywhere (FitError.Diagnosis
        analog) — a pure host-side decode of one diag-plane row.

        ``diag_row`` (bool[K]) comes from the fused cycle program's packed
        plane or, on the extender path, the batched diag_bits program —
        _bind_phase always supplies one now.  The eager per-plugin loop
        this replaced ran one device program per plugin per failing pod
        (flagged by the host-sync dataflow pass)."""
        names = fw.filter_names
        if diag_row is None:
            return set(names)  # no diagnosis plane: attribute to all
        failing = {names[k] for k in range(len(names)) if not bool(diag_row[k])}
        return failing or set(names)

    def run_until_idle(self, max_cycles: int = 1000,
                       backoff_wait: Optional[float] = None) -> CycleStats:
        """Drive cycles until nothing is attempted, in flight, OR waiting out
        backoff.  Pods in the 1s-10s backoff queue are not poppable at the
        instant a cycle finds the activeQ empty — without the bounded spin
        below, the scheduler binary would report them unschedulable even
        though they'd schedule right after their backoff expires."""
        if backoff_wait is None:
            # outlast the longest configured per-pod backoff, with headroom
            backoff_wait = 1.2 * self.queue._max_backoff
        total = CycleStats()
        waited = 0.0
        cycles = 0
        while cycles < max_cycles:
            s = self.schedule_cycle()
            if s.attempted == 0 and s.in_flight == 0:
                _a, b, _u = self.queue.pending_count()
                # only the BACKOFF queue is worth spinning on: its pods become
                # poppable within pod_max_backoff.  UnschedulableQ pods need a
                # cluster event or the 60s flush — callers wanting that drive
                # cycles themselves (the perf harness does).  Gang Permit
                # holds (s.waiting) also resolve on later cycles (release or
                # deadline), so they keep the spin alive up to the budget.
                if (b == 0 and s.waiting == 0) or waited >= backoff_wait:
                    break
                time.sleep(0.05)
                waited += 0.05
                continue
            cycles += 1
            if s.scheduled:
                waited = 0.0
            total.attempted += s.attempted
            total.scheduled += s.scheduled
            total.unschedulable += s.unschedulable
            total.batch_seconds += s.batch_seconds
        return total
