"""Static-analysis core: parsed-module model + finding type + engine.

The reproduction's analog of the reference's ``hack/verify-*`` static gates
(go vet / staticcheck): an AST-based invariant checker over this project's
real failure modes — jit trace safety, recompile hazards, lock discipline,
exception hygiene, metrics registration.  Checks plug into a registry
(analysis/registry.py) mirroring the scheduler's plugin registry; findings
are ratcheted against a committed baseline (analysis/baseline.py) so
pre-existing violations are grandfathered while new ones fail tier-1.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple


@dataclass(frozen=True)
class Finding:
    """One rule violation at one site.

    The baseline key deliberately excludes the line NUMBER: unrelated edits
    above a grandfathered site must not churn the ratchet.  Identity is
    (check, path, enclosing scope, rule, normalized source line); duplicate
    keys are count-matched (see baseline.diff).
    """

    check: str  # registered check name, e.g. "trace-safety"
    rule: str  # short rule id within the check, e.g. "host-sync"
    path: str  # repo-relative posix path
    line: int  # 1-based line (report only — not part of the key)
    symbol: str  # dotted scope ("" = module level)
    message: str
    snippet: str  # stripped source line at ``line``

    def key(self) -> str:
        return "::".join(
            (self.check, self.path, self.symbol, self.rule, self.snippet))

    def location(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}{sym}"


# Per-finding suppression: at the end of the flagged line, or standalone on
# the line directly above it.  The justification after ``--`` is REQUIRED
# and is itself linted (missing/unknown-check/unused → findings that cannot
# be suppressed).
SUPPRESSION_RE = re.compile(
    r"#\s*ktpu-analysis:\s*ignore\[([^\]]*)\]\s*(?:--\s*(\S.*))?\s*$")


@dataclass
class Suppression:
    """One parsed ``# ktpu-analysis: ignore[check] -- why`` comment."""

    line: int  # 1-based line the comment sits on
    target_line: int  # line a finding must sit on to match
    checks: Tuple[str, ...]
    justification: str
    used: bool = False


class ModuleInfo:
    """One parsed source file: AST + source lines + scope/parent maps."""

    def __init__(self, path: str, source: str):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.scopes: Dict[ast.AST, str] = {self.tree: ""}
        self._index(self.tree, "")
        self.suppressions: List[Suppression] = self._parse_suppressions()
        # every FunctionDef/AsyncFunctionDef/Lambda keyed by qualname; nested
        # functions use dotted names ("TPUScheduler._build_jitted.fused_greedy")
        self.functions: Dict[str, ast.AST] = {
            q: n for n, q in self.scopes.items()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }

    def _index(self, node: ast.AST, scope: str) -> None:
        for child in ast.iter_child_nodes(node):
            self.parents[child] = node
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                sub = f"{scope}.{child.name}" if scope else child.name
            else:
                sub = scope
            self.scopes[child] = sub
            self._index(child, sub)

    def _parse_suppressions(self) -> List[Suppression]:
        """Real COMMENT tokens only (tokenize, not a line regex): the
        marker's own documentation would otherwise read as a suppression."""
        import io
        import tokenize

        out: List[Suppression] = []
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.source).readline))
        except (tokenize.TokenError, IndentationError):
            return out
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = SUPPRESSION_RE.search(tok.string)
            if not m:
                continue
            i = tok.start[0]
            checks = tuple(c.strip() for c in m.group(1).split(",")
                           if c.strip())
            standalone = self.line_text(i).startswith("#")
            out.append(Suppression(
                line=i, target_line=i + 1 if standalone else i,
                checks=checks, justification=(m.group(2) or "").strip()))
        return out

    def scope_of(self, node: ast.AST) -> str:
        return self.scopes.get(node, "")

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return a
        return None

    def finding(self, check: str, rule: str, node: ast.AST,
                message: str) -> Finding:
        line = getattr(node, "lineno", 0)
        return Finding(check=check, rule=rule, path=self.path, line=line,
                       symbol=self.scope_of(node), message=message,
                       snippet=self.line_text(line))


@dataclass
class Project:
    """All modules under analysis (the unit every check receives)."""

    modules: List[ModuleInfo] = field(default_factory=list)

    def by_path(self) -> Dict[str, ModuleInfo]:
        return {m.path: m for m in self.modules}

    def find(self, suffix: str) -> Optional[ModuleInfo]:
        for m in self.modules:
            if m.path.endswith(suffix):
                return m
        return None


def dotted_name(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


# paths scanned by default (repo-relative); tests/ is deliberately out of
# scope — fixtures there contain violations on purpose
DEFAULT_SCAN_PATHS = ("kubernetes_tpu", "tools", "bench.py")


def discover_files(root: str,
                   paths: Iterable[str] = DEFAULT_SCAN_PATHS) -> List[str]:
    out: List[str] = []
    for p in paths:
        full = os.path.join(root, p)
        if os.path.isfile(full) and full.endswith(".py"):
            out.append(full)
        elif os.path.isdir(full):
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames) if f.endswith(".py"))
    return sorted(out)


def load_project(root: str,
                 paths: Iterable[str] = DEFAULT_SCAN_PATHS) -> Project:
    modules: List[ModuleInfo] = []
    for f in discover_files(root, paths):
        rel = os.path.relpath(f, root)
        with open(f, "r", encoding="utf-8") as fh:
            src = fh.read()
        try:
            modules.append(ModuleInfo(rel, src))
        except SyntaxError:
            # non-importable scratch files must not kill the gate; the
            # test suite imports everything that matters anyway
            continue
    return Project(modules=modules)


def project_from_sources(sources: Dict[str, str]) -> Project:
    """Build a Project from {virtual_path: source} — the test fixture path."""
    return Project(modules=[ModuleInfo(p, s) for p, s in sources.items()])


def apply_suppressions(project: Project, findings: List[Finding],
                       run_names: Iterable[str]) -> List[Finding]:
    """Drop findings covered by a ``ktpu-analysis: ignore`` comment and
    emit the suppression lint: a justification is REQUIRED, check names
    must be real, and a suppression that matches nothing (for a check
    that actually ran) is stale.  Lint findings carry check name
    ``suppression`` and are never themselves suppressible — the escape
    hatch must not be able to hide its own misuse."""
    from .registry import CHECK_REGISTRY, default_checks

    default_checks()  # ensure the registry is populated
    known = set(CHECK_REGISTRY) | {"suppression"}
    ran = set(run_names)
    kept: List[Finding] = []
    by_mod: Dict[str, ModuleInfo] = project.by_path()
    for f in findings:
        mod = by_mod.get(f.path)
        sup = None
        if mod is not None:
            for s in mod.suppressions:
                if s.target_line == f.line and f.check in s.checks:
                    sup = s
                    break
        if sup is None:
            kept.append(f)
        else:
            sup.used = True
    for mod in project.modules:
        for s in mod.suppressions:
            loc = ast.Module(body=[], type_ignores=[])  # line carrier
            loc.lineno = s.line
            if not s.justification:
                kept.append(mod.finding(
                    "suppression", "missing-justification", loc,
                    f"suppression of [{', '.join(s.checks)}] carries no "
                    f"`-- justification`; every ignore must say why"))
            for c in s.checks:
                if c not in known:
                    kept.append(mod.finding(
                        "suppression", "unknown-check", loc,
                        f"suppression names unknown check `{c}` "
                        f"(registered: {sorted(known)})"))
            if (s.justification and not s.used
                    and s.checks and set(s.checks) <= ran
                    and all(c in known for c in s.checks)):
                kept.append(mod.finding(
                    "suppression", "unused", loc,
                    f"suppression of [{', '.join(s.checks)}] matched no "
                    f"finding — the violation was fixed; delete the "
                    f"comment so it cannot mask a future one"))
    return kept


def run_checks(project: Project, checks) -> List[Finding]:
    findings: List[Finding] = []
    for check in checks:
        findings.extend(check.run(project))
    findings = apply_suppressions(project, findings,
                                  [c.name for c in checks])
    findings.sort(key=lambda f: (f.path, f.line, f.check, f.rule))
    return findings
