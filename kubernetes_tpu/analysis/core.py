"""Static-analysis core: parsed-module model + finding type + engine.

The reproduction's analog of the reference's ``hack/verify-*`` static gates
(go vet / staticcheck): an AST-based invariant checker over this project's
real failure modes — jit trace safety, recompile hazards, lock discipline,
exception hygiene, metrics registration.  Checks plug into a registry
(analysis/registry.py) mirroring the scheduler's plugin registry; findings
are ratcheted against a committed baseline (analysis/baseline.py) so
pre-existing violations are grandfathered while new ones fail tier-1.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple


@dataclass(frozen=True)
class Finding:
    """One rule violation at one site.

    The baseline key deliberately excludes the line NUMBER: unrelated edits
    above a grandfathered site must not churn the ratchet.  Identity is
    (check, path, enclosing scope, rule, normalized source line); duplicate
    keys are count-matched (see baseline.diff).
    """

    check: str  # registered check name, e.g. "trace-safety"
    rule: str  # short rule id within the check, e.g. "host-sync"
    path: str  # repo-relative posix path
    line: int  # 1-based line (report only — not part of the key)
    symbol: str  # dotted scope ("" = module level)
    message: str
    snippet: str  # stripped source line at ``line``

    def key(self) -> str:
        return "::".join(
            (self.check, self.path, self.symbol, self.rule, self.snippet))

    def location(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}{sym}"


class ModuleInfo:
    """One parsed source file: AST + source lines + scope/parent maps."""

    def __init__(self, path: str, source: str):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.scopes: Dict[ast.AST, str] = {self.tree: ""}
        self._index(self.tree, "")
        # every FunctionDef/AsyncFunctionDef/Lambda keyed by qualname; nested
        # functions use dotted names ("TPUScheduler._build_jitted.fused_greedy")
        self.functions: Dict[str, ast.AST] = {
            q: n for n, q in self.scopes.items()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }

    def _index(self, node: ast.AST, scope: str) -> None:
        for child in ast.iter_child_nodes(node):
            self.parents[child] = node
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                sub = f"{scope}.{child.name}" if scope else child.name
            else:
                sub = scope
            self.scopes[child] = sub
            self._index(child, sub)

    def scope_of(self, node: ast.AST) -> str:
        return self.scopes.get(node, "")

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return a
        return None

    def finding(self, check: str, rule: str, node: ast.AST,
                message: str) -> Finding:
        line = getattr(node, "lineno", 0)
        return Finding(check=check, rule=rule, path=self.path, line=line,
                       symbol=self.scope_of(node), message=message,
                       snippet=self.line_text(line))


@dataclass
class Project:
    """All modules under analysis (the unit every check receives)."""

    modules: List[ModuleInfo] = field(default_factory=list)

    def by_path(self) -> Dict[str, ModuleInfo]:
        return {m.path: m for m in self.modules}

    def find(self, suffix: str) -> Optional[ModuleInfo]:
        for m in self.modules:
            if m.path.endswith(suffix):
                return m
        return None


def dotted_name(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


# paths scanned by default (repo-relative); tests/ is deliberately out of
# scope — fixtures there contain violations on purpose
DEFAULT_SCAN_PATHS = ("kubernetes_tpu", "tools", "bench.py")


def discover_files(root: str,
                   paths: Iterable[str] = DEFAULT_SCAN_PATHS) -> List[str]:
    out: List[str] = []
    for p in paths:
        full = os.path.join(root, p)
        if os.path.isfile(full) and full.endswith(".py"):
            out.append(full)
        elif os.path.isdir(full):
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames) if f.endswith(".py"))
    return sorted(out)


def load_project(root: str,
                 paths: Iterable[str] = DEFAULT_SCAN_PATHS) -> Project:
    modules: List[ModuleInfo] = []
    for f in discover_files(root, paths):
        rel = os.path.relpath(f, root)
        with open(f, "r", encoding="utf-8") as fh:
            src = fh.read()
        try:
            modules.append(ModuleInfo(rel, src))
        except SyntaxError:
            # non-importable scratch files must not kill the gate; the
            # test suite imports everything that matters anyway
            continue
    return Project(modules=modules)


def project_from_sources(sources: Dict[str, str]) -> Project:
    """Build a Project from {virtual_path: source} — the test fixture path."""
    return Project(modules=[ModuleInfo(p, s) for p, s in sources.items()])


def run_checks(project: Project, checks) -> List[Finding]:
    findings: List[Finding] = []
    for check in checks:
        findings.extend(check.run(project))
    findings.sort(key=lambda f: (f.path, f.line, f.check, f.rule))
    return findings
