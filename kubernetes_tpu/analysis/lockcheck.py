"""Runtime lock instrumentation: acquisition-order tracking across threads.

The static lock-discipline check sees lexical structure; this is its
runtime partner — the project's analog of the Go race detector run over
the scheduler's concurrent integration tests.  An active LockMonitor
records, per thread, the stack of held instrumented locks and builds a
global acquired-after graph; acquiring B while holding A records edge
A→B, and a pre-existing path B→…→A is a lock-order inversion (two threads
interleaving those orders can deadlock, as informer relist vs store
fan-out nearly did — see client/informer.py's _relist_lock comments).

Opt-in and zero-cost when inactive: lock owners construct through
``maybe_wrap``, which returns the raw lock unless a monitor is active
(one module-global read per construction).  tests/test_chaos.py activates
a monitor for every test and asserts no inversions at teardown.
"""

from __future__ import annotations

import itertools
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

_active: Optional["LockMonitor"] = None
_seq = itertools.count(1)


class LockOrderViolation(RuntimeError):
    pass


class LockMonitor:
    """Acquired-after graph + per-thread held stacks.

    ``strict=True`` raises LockOrderViolation at the acquiring site;
    default collects into ``violations`` so a mid-critical-section raise
    cannot corrupt the structure under test — the chaos fixture asserts
    at teardown instead.
    """

    def __init__(self, strict: bool = False):
        self.strict = strict
        self.violations: List[str] = []
        self._edges: Dict[str, Set[str]] = {}  # key -> keys acquired after
        self._edge_site: Dict[Tuple[str, str], str] = {}
        self._tls = threading.local()
        self._mu = threading.Lock()

    # --- per-thread held stack ------------------------------------------------

    def _stack(self) -> List[str]:
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = self._tls.stack = []
        return s

    # --- graph ----------------------------------------------------------------

    def _path_exists(self, src: str, dst: str) -> Optional[List[str]]:
        seen = {src}
        work: List[Tuple[str, List[str]]] = [(src, [src])]
        while work:
            cur, path = work.pop()
            if cur == dst:
                return path
            for nxt in self._edges.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    work.append((nxt, path + [nxt]))
        return None

    def note_acquire(self, key: str, where: str = "") -> None:
        stack = self._stack()
        if key in stack:  # RLock reentry: no new ordering information
            stack.append(key)
            return
        held = list(dict.fromkeys(stack))
        with self._mu:
            for h in held:
                inverse = self._path_exists(key, h)
                if inverse is not None:
                    prior = self._edge_site.get((inverse[0], inverse[1]), "?")
                    msg = (f"lock-order inversion: acquiring {key} while "
                           f"holding {h}, but order {' -> '.join(inverse)} "
                           f"was established at {prior}; now at {where or 'n/a'}")
                    self.violations.append(msg)
                    if self.strict:
                        raise LockOrderViolation(msg)
                    # do NOT record the inverted edge: closing the cycle
                    # would make every later acquisition in the ORIGINAL
                    # (correct) order report a violation too, burying the
                    # one real site in noise
                    continue
                self._edges.setdefault(h, set()).add(key)
                self._edge_site.setdefault((h, key), where or "n/a")
        stack.append(key)

    def note_release(self, key: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == key:
                del stack[i]
                return

    def note_release_all(self, key: str) -> int:
        """Drop every held occurrence of ``key`` on this thread, returning
        the reentrant depth dropped — Condition.wait's _release_save fully
        releases an RLock regardless of depth, and the held stack must
        agree or every lock acquired during the wait would appear ordered
        after a lock this thread no longer holds."""
        stack = self._stack()
        depth = stack.count(key)
        if depth:
            stack[:] = [k for k in stack if k != key]
        return depth

    def report(self) -> str:
        if not self.violations:
            return "lockcheck: no lock-order inversions observed"
        lines = [f"lockcheck: {len(self.violations)} lock-order violation(s):"]
        lines.extend(f"  {v}" for v in self.violations)
        return "\n".join(lines)

    def assert_clean(self) -> None:
        if self.violations:
            raise LockOrderViolation(self.report())


class CheckedLock:
    """Proxy over a threading.Lock/RLock reporting to a LockMonitor.

    Distinct instances sharing a display name stay distinct in the order
    graph (keyed by a process-unique sequence number), so two ObjectStore
    instances' `_lock`s are separate vertices — an inversion between them
    is real, an inversion with *itself* is impossible."""

    __slots__ = ("_inner", "name", "_key", "_monitor")

    def __init__(self, inner, name: str, monitor: LockMonitor):
        self._inner = inner
        self.name = name
        self._key = f"{name}#{next(_seq)}"
        self._monitor = monitor

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # record intent BEFORE blocking: a true deadlock never returns, so
        # post-acquire bookkeeping would miss exactly the case that matters
        self._monitor.note_acquire(self._key, _caller())
        ok = self._inner.acquire(blocking, timeout)
        if not ok:
            self._monitor.note_release(self._key)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._monitor.note_release(self._key)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def locked(self) -> bool:
        try:
            return self._inner.locked()
        except AttributeError:  # RLock pre-3.12 has no locked()
            return False

    # --- Condition protocol ---------------------------------------------------
    # threading.Condition(lock) hasattr-probes these at construction; if
    # absent it falls back to one release()/acquire() pair, which both
    # under-releases a reentrant RLock in wait() and mis-probes ownership
    # via acquire(0).  Delegating versions make
    # ``Condition(maybe_wrap(RLock(), name))`` safe to instrument
    # (sim/replication.py's FollowerReplica._cond).

    def _is_owned(self) -> bool:
        inner = getattr(self._inner, "_is_owned", None)
        if inner is not None:
            return inner()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        depth = self._monitor.note_release_all(self._key)
        inner = getattr(self._inner, "_release_save", None)
        if inner is not None:
            return (inner(), depth)
        self._inner.release()
        return (None, depth)

    def _acquire_restore(self, saved) -> None:
        state, depth = saved
        # like acquire(): record intent BEFORE blocking
        self._monitor.note_acquire(self._key, _caller())
        inner = getattr(self._inner, "_acquire_restore", None)
        if inner is not None:
            inner(state)
        else:
            self._inner.acquire()
        for _ in range(depth - 1):  # restore reentrant depth on the stack
            self._monitor.note_acquire(self._key, _caller())


def _caller() -> str:
    import sys

    try:
        f = sys._getframe(1)
    except (AttributeError, ValueError):
        return "n/a"
    try:
        # walk past every lockcheck frame (acquire / __enter__ depth
        # varies between `with lock:` and direct lock.acquire() calls)
        while f is not None and "lockcheck" in f.f_code.co_filename:
            f = f.f_back
        if f is None:
            return "n/a"
        return f"{f.f_code.co_filename}:{f.f_lineno} in {f.f_code.co_name}"
    finally:
        del f


def maybe_wrap(lock, name: str):
    """Instrument ``lock`` iff a monitor is active (else return it as-is).

    Lock OWNERS call this at construction; cost when inactive is a single
    global read, so it belongs even on the ObjectStore hot path."""
    if _active is None:
        return lock
    return CheckedLock(lock, name, _active)


def activate(monitor: Optional[LockMonitor] = None) -> LockMonitor:
    global _active
    _active = monitor or LockMonitor()
    return _active


def deactivate() -> None:
    global _active
    _active = None


def active_monitor() -> Optional[LockMonitor]:
    return _active


# ---------------------------------------------------------------------------
# access sanitizer: the runtime cross-check of the STATIC ownership report
# (analysis/threads.py).  The thread-ownership check claims every shared
# field is single-role, lock-protected, a handoff, or loaned; this records
# which threads actually WRITE each instrumented field and whether they
# held a same-class instrumented lock at the time.  A field written
# unsynchronized by two threads on ONE instance, whose static
# classification says "single-role" or "locked", is a contradiction —
# static said safe, runtime disproved it.
#
# Sampling policy (documented limits, by design):
#   - write-side only: __setattr__ interception sees rebinds, not interior
#     container mutation (`self.d[k] = v` mutates the dict, not the field)
#     and not reads — cheap enough for every autouse fixture run;
#   - lock attribution is the lockcheck held stack, so it only sees locks
#     the monitor instruments (maybe_wrap'd): pair the sanitizer with an
#     active LockMonitor;
#   - per-instance keying uses id(self); id reuse after gc can merge two
#     short-lived instances (more candidates, then the static report
#     adjudicates — never fewer).
# ---------------------------------------------------------------------------

_san_active: Optional["AccessSanitizer"] = None


class OwnershipViolation(RuntimeError):
    pass


class AccessSanitizer:
    """Per-thread field-write recording over instrumented classes."""

    def __init__(self):
        self._mu = threading.Lock()
        # (class name, attr) → {instance id → set of unsynchronized
        # writer thread idents}
        self._unsync: Dict[Tuple[str, str], Dict[int, Set[int]]] = {}
        self._patched: List[Tuple[type, Optional[object]]] = []

    # --- recording ------------------------------------------------------------

    def note_write(self, cls_name: str, attr: str, instance_id: int) -> None:
        mon = _active
        if mon is not None:
            prefix = cls_name + "."
            for key in mon._stack():
                if key.split("#", 1)[0].startswith(prefix):
                    return  # a same-class instrumented lock is held
        ident = threading.get_ident()
        with self._mu:
            by_inst = self._unsync.setdefault((cls_name, attr), {})
            by_inst.setdefault(instance_id, set()).add(ident)

    def instrument(self, classes) -> None:
        """Patch each class's __setattr__ to report writes here."""
        for cls in classes:
            if any(c is cls for c, _ in self._patched):
                continue
            own = cls.__dict__.get("__setattr__")
            fallback = cls.__setattr__  # resolved through the MRO
            cname = cls.__name__
            san = self

            def _recording_setattr(obj, name, value,
                                   _f=fallback, _c=cname, _s=san):
                # mirror the static engine's EXEMPT_METHODS: constructor
                # writes are single-threaded by convention and handed off
                # with a happens-before edge (Thread.start), so a
                # construct-on-main / drive-on-worker instance is ONE
                # writer, exactly as the ownership report models it
                try:
                    caller = sys._getframe(1).f_code.co_name
                except (AttributeError, ValueError):
                    caller = ""
                if caller not in ("__init__", "__new__"):
                    _s.note_write(_c, name, id(obj))
                _f(obj, name, value)

            self._patched.append((cls, own))
            cls.__setattr__ = _recording_setattr

    def restore(self) -> None:
        for cls, own in reversed(self._patched):
            if own is None:
                # the class never defined its own __setattr__ — removing
                # the wrapper falls back to the inherited slot
                del cls.__setattr__
            else:
                cls.__setattr__ = own
        self._patched.clear()

    # --- verification ---------------------------------------------------------

    def candidates(self) -> List[Tuple[str, str, int]]:
        """(class, attr, thread count) for every field some single
        instance saw unsynchronized writes from ≥2 threads."""
        out = []
        with self._mu:
            for (cname, attr), by_inst in sorted(self._unsync.items()):
                worst = max((len(t) for t in by_inst.values()), default=0)
                if worst >= 2:
                    out.append((cname, attr, worst))
        return out

    def needs_verify(self) -> bool:
        """True iff verify() could possibly fail — fixtures call this
        first so clean runs never pay for the static ownership report."""
        return bool(self.candidates())

    def verify(self, ownership_report: Dict[str, Dict[str, dict]]
               ) -> List[str]:
        """Contradictions between observed writes and the static report.

        A candidate field contradicts when the static engine classified it
        "single-role" (no cross-role access exists) or "locked" (every
        conflicting site holds a lock): two unsynchronized runtime writer
        threads disprove either claim.  "handoff"/"loaned" fields are
        join-protocol-protected — multi-thread writes are their normal
        operation, ordered by the join that handoff-discipline verifies.
        Fields absent from the report (dynamic attrs the AST never saw)
        are skipped: no static claim exists to contradict."""
        violations = []
        for cname, attr, nthreads in self.candidates():
            claim = ownership_report.get(cname, {}).get(attr)
            if claim is None:
                continue
            if claim["classification"] in ("single-role", "locked"):
                violations.append(
                    f"{cname}.{attr}: static ownership says "
                    f"{claim['classification']!r} but {nthreads} threads "
                    f"wrote it unsynchronized on one instance "
                    f"(roles: {', '.join(claim['roles']) or 'none'})")
        return violations

    def assert_consistent(self, ownership_report) -> None:
        v = self.verify(ownership_report)
        if v:
            raise OwnershipViolation(
                "access sanitizer: runtime writes contradict the static "
                "ownership report:\n  " + "\n  ".join(v))


def sanitize(classes) -> AccessSanitizer:
    """Activate an AccessSanitizer over ``classes`` (idempotent per call
    pair with unsanitize; reuses the active sanitizer if one exists)."""
    global _san_active
    if _san_active is None:
        _san_active = AccessSanitizer()
    _san_active.instrument(classes)
    return _san_active


def unsanitize() -> Optional[AccessSanitizer]:
    """Restore every patched __setattr__; returns the retired sanitizer
    (fixtures verify against the static report AFTER restoring, off the
    instrumented path)."""
    global _san_active
    san, _san_active = _san_active, None
    if san is not None:
        san.restore()
    return san
