"""Runtime lock instrumentation: acquisition-order tracking across threads.

The static lock-discipline check sees lexical structure; this is its
runtime partner — the project's analog of the Go race detector run over
the scheduler's concurrent integration tests.  An active LockMonitor
records, per thread, the stack of held instrumented locks and builds a
global acquired-after graph; acquiring B while holding A records edge
A→B, and a pre-existing path B→…→A is a lock-order inversion (two threads
interleaving those orders can deadlock, as informer relist vs store
fan-out nearly did — see client/informer.py's _relist_lock comments).

Opt-in and zero-cost when inactive: lock owners construct through
``maybe_wrap``, which returns the raw lock unless a monitor is active
(one module-global read per construction).  tests/test_chaos.py activates
a monitor for every test and asserts no inversions at teardown.
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional, Set, Tuple

_active: Optional["LockMonitor"] = None
_seq = itertools.count(1)


class LockOrderViolation(RuntimeError):
    pass


class LockMonitor:
    """Acquired-after graph + per-thread held stacks.

    ``strict=True`` raises LockOrderViolation at the acquiring site;
    default collects into ``violations`` so a mid-critical-section raise
    cannot corrupt the structure under test — the chaos fixture asserts
    at teardown instead.
    """

    def __init__(self, strict: bool = False):
        self.strict = strict
        self.violations: List[str] = []
        self._edges: Dict[str, Set[str]] = {}  # key -> keys acquired after
        self._edge_site: Dict[Tuple[str, str], str] = {}
        self._tls = threading.local()
        self._mu = threading.Lock()

    # --- per-thread held stack ------------------------------------------------

    def _stack(self) -> List[str]:
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = self._tls.stack = []
        return s

    # --- graph ----------------------------------------------------------------

    def _path_exists(self, src: str, dst: str) -> Optional[List[str]]:
        seen = {src}
        work: List[Tuple[str, List[str]]] = [(src, [src])]
        while work:
            cur, path = work.pop()
            if cur == dst:
                return path
            for nxt in self._edges.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    work.append((nxt, path + [nxt]))
        return None

    def note_acquire(self, key: str, where: str = "") -> None:
        stack = self._stack()
        if key in stack:  # RLock reentry: no new ordering information
            stack.append(key)
            return
        held = list(dict.fromkeys(stack))
        with self._mu:
            for h in held:
                inverse = self._path_exists(key, h)
                if inverse is not None:
                    prior = self._edge_site.get((inverse[0], inverse[1]), "?")
                    msg = (f"lock-order inversion: acquiring {key} while "
                           f"holding {h}, but order {' -> '.join(inverse)} "
                           f"was established at {prior}; now at {where or 'n/a'}")
                    self.violations.append(msg)
                    if self.strict:
                        raise LockOrderViolation(msg)
                    # do NOT record the inverted edge: closing the cycle
                    # would make every later acquisition in the ORIGINAL
                    # (correct) order report a violation too, burying the
                    # one real site in noise
                    continue
                self._edges.setdefault(h, set()).add(key)
                self._edge_site.setdefault((h, key), where or "n/a")
        stack.append(key)

    def note_release(self, key: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == key:
                del stack[i]
                return

    def report(self) -> str:
        if not self.violations:
            return "lockcheck: no lock-order inversions observed"
        lines = [f"lockcheck: {len(self.violations)} lock-order violation(s):"]
        lines.extend(f"  {v}" for v in self.violations)
        return "\n".join(lines)

    def assert_clean(self) -> None:
        if self.violations:
            raise LockOrderViolation(self.report())


class CheckedLock:
    """Proxy over a threading.Lock/RLock reporting to a LockMonitor.

    Distinct instances sharing a display name stay distinct in the order
    graph (keyed by a process-unique sequence number), so two ObjectStore
    instances' `_lock`s are separate vertices — an inversion between them
    is real, an inversion with *itself* is impossible."""

    __slots__ = ("_inner", "name", "_key", "_monitor")

    def __init__(self, inner, name: str, monitor: LockMonitor):
        self._inner = inner
        self.name = name
        self._key = f"{name}#{next(_seq)}"
        self._monitor = monitor

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # record intent BEFORE blocking: a true deadlock never returns, so
        # post-acquire bookkeeping would miss exactly the case that matters
        self._monitor.note_acquire(self._key, _caller())
        ok = self._inner.acquire(blocking, timeout)
        if not ok:
            self._monitor.note_release(self._key)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._monitor.note_release(self._key)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def locked(self) -> bool:
        try:
            return self._inner.locked()
        except AttributeError:  # RLock pre-3.12 has no locked()
            return False


def _caller() -> str:
    import sys

    try:
        f = sys._getframe(1)
    except (AttributeError, ValueError):
        return "n/a"
    try:
        # walk past every lockcheck frame (acquire / __enter__ depth
        # varies between `with lock:` and direct lock.acquire() calls)
        while f is not None and "lockcheck" in f.f_code.co_filename:
            f = f.f_back
        if f is None:
            return "n/a"
        return f"{f.f_code.co_filename}:{f.f_lineno} in {f.f_code.co_name}"
    finally:
        del f


def maybe_wrap(lock, name: str):
    """Instrument ``lock`` iff a monitor is active (else return it as-is).

    Lock OWNERS call this at construction; cost when inactive is a single
    global read, so it belongs even on the ObjectStore hot path."""
    if _active is None:
        return lock
    return CheckedLock(lock, name, _active)


def activate(monitor: Optional[LockMonitor] = None) -> LockMonitor:
    global _active
    _active = monitor or LockMonitor()
    return _active


def deactivate() -> None:
    global _active
    _active = None


def active_monitor() -> Optional[LockMonitor]:
    return _active
