"""Interprocedural device-boundary dataflow: call graph + taint lattice.

The second analysis engine (the first, core.py, is per-module AST
invariants).  This one answers the cross-module questions PR 2's checker
could not: "did this value silently leave the device?" and "is this
closure safe under vmap/jit/shard_map?".  The pipeline:

  1. module graph    — repo-relative paths resolved to dotted module
                       names; per-module import tables (``from ..x
                       import y as z`` → alias → (module, symbol)).
  2. call graph      — every FunctionDef is a node keyed
                       (path, qualname); call sites resolve through
                       local defs, self-methods, imported symbols,
                       imported-module attributes, and (for methods
                       whose bare name is UNIQUE project-wide) duck-
                       typed ``obj.meth()`` receivers.
  3. taint fixpoint  — device-array taint seeded from known producers
                       (``jax.numpy`` results, jitted-callable returns,
                       ``DeviceSnapshot``/``PendingScatter`` values,
                       ``.to_device()``) and propagated through
                       assignments, calls (args → params, returns →
                       call sites), attribute loads, container packing
                       (tuple/list/dict), and dataclass/self fields —
                       iterated project-wide until stable, so summaries
                       converge even across call-graph cycles.

The lattice has TWO tainted levels, which is what keeps the checks
quiet on idiomatic host code:

  DEVICE  the value IS a device array — branching on it, iterating it,
          or np.asarray-ing it blocks on the device;
  LOOSE   a host object/container HOLDING device values (an _InFlight
          record, a list of PrevBatch carries, a jit-program table) —
          iterating or branching on it is free, but its attribute loads
          and the results of CALLING it (jitted callables) are DEVICE.

Checks built on top live in checks/device_boundary.py.  The analysis is
deliberately may-taint (over-approximate) at each level, and the
sanctioned fetch-site list plus suppression comments (core.py) handle
the deliberate crossings.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import ModuleInfo, Project, dotted_name

# taint levels
NONE, LOOSE, DEVICE = 0, 1, 2

# ---------------------------------------------------------------------------
# seeds: names / types whose values live on device
# ---------------------------------------------------------------------------

# class names whose instances hold device arrays in their fields: the
# instances themselves are LOOSE, their attribute loads DEVICE
DEVICE_CLASSES = {"DeviceSnapshot", "PendingScatter", "DynamicState",
                  "ForkPayload", "PrevBatch"}
# parameter / variable names conventionally bound to DEVICE values across
# the codebase (the DeviceSnapshot threading idiom) — a name-based seed is
# how the analysis crosses untyped boundaries
DEVICE_VALUE_NAMES = {"dsnap", "fsnap", "dsnap_out", "dyn", "dyn_out",
                      "diag_dev", "node_row_dev", "cand_dev", "packed0"}
# methods whose RESULT holds device values regardless of receiver
DEVICE_PRODUCER_METHODS = {"to_device", "to_device_deferred"}
# calls that move a device value to host (the result is NOT tainted —
# they are the sync operations themselves, judged by the checks)
HOST_TRANSFER_CALLS = {"np.asarray", "np.array", "jax.device_get",
                       "float", "int", "bool", "len"}
# static array metadata: reading these never blocks on the device, so a
# branch on `arr.shape[0]` or `int(arr.ndim)` is host work
ARRAY_METADATA_ATTRS = {"shape", "ndim", "dtype", "size"}
# jax.* entry points whose result stays on device
JAX_DEVICE_RESULTS = {"jax.device_put", "jax.block_until_ready"}
# wrapping these returns a callable whose RESULTS are device arrays; the
# callable value itself is LOOSE so that calling through a variable or a
# program-table subscript yields DEVICE
JIT_WRAPPERS = {"jax.jit", "jit", "jax.vmap", "vmap", "shard_map",
                "jax.pmap", "pmap"}

# receiver method names too generic to duck-type across classes
_COMMON_METHODS = {"get", "put", "pop", "append", "extend", "update", "add",
                   "items", "keys", "values", "copy", "clear", "sort",
                   "join", "split", "strip", "read", "write", "close",
                   "setdefault", "remove", "insert", "index", "count",
                   "inc", "observe", "set", "info", "error", "warning",
                   "debug", "info_s", "error_s", "release", "acquire",
                   "start", "run", "stop", "name", "format", "encode",
                   "decode", "list", "create", "delete", "obj"}


def module_name_of(path: str) -> str:
    """'kubernetes_tpu/whatif/engine.py' → 'kubernetes_tpu.whatif.engine'."""
    p = path[:-3] if path.endswith(".py") else path
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


@dataclass
class ImportTable:
    """One module's imported names."""

    # local alias → dotted module ("jnp" → "jax.numpy")
    modules: Dict[str, str] = field(default_factory=dict)
    # local alias → (dotted module, symbol) ("apply_fork" →
    # ("kubernetes_tpu.whatif.fork", "apply_fork"))
    symbols: Dict[str, Tuple[str, str]] = field(default_factory=dict)

    def jnp_aliases(self) -> Set[str]:
        return {a for a, m in self.modules.items() if m == "jax.numpy"} | {
            a for a, (m, s) in self.symbols.items()
            if m == "jax" and s == "numpy"}

    def np_aliases(self) -> Set[str]:
        return {a for a, m in self.modules.items() if m == "numpy"}


def build_import_table(mod: ModuleInfo, pkg: str) -> ImportTable:
    """Resolve imports, including package-relative ones, against ``pkg``
    (the module's own dotted name)."""
    table = ImportTable()
    parts = pkg.split(".")
    # In a package __init__, ``pkg`` IS the containing package (the
    # '/__init__' segment was stripped), so level-1 imports resolve
    # against pkg itself, not its parent — getting this wrong drops every
    # re-export edge package modules contribute to the call graph
    is_pkg = mod.path.endswith("/__init__.py")
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    table.modules[a.asname] = a.name
                else:
                    root = a.name.split(".")[0]
                    table.modules.setdefault(root, root)
                    table.modules[a.name] = a.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                strip = node.level - 1 if is_pkg else node.level
                base = parts[: len(parts) - strip] if strip else parts
                src = ".".join(base + ([node.module] if node.module else []))
            else:
                src = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                table.symbols[a.asname or a.name] = (src, a.name)
    return table


@dataclass
class FunctionNode:
    """One function in the project-wide graph."""

    path: str
    qual: str
    node: ast.AST  # FunctionDef / AsyncFunctionDef
    mod: ModuleInfo
    params: List[str] = field(default_factory=list)
    # taint state (mutated by the fixpoint): name → level
    taint: Dict[str, int] = field(default_factory=dict)
    param_taint: Dict[str, int] = field(default_factory=dict)
    returns: int = NONE
    callees: Set[Tuple[str, str]] = field(default_factory=set)

    @property
    def key(self) -> Tuple[str, str]:
        return (self.path, self.qual)


def _raise_to(levels: Dict[str, int], name: str, lvl: int) -> bool:
    if lvl > levels.get(name, NONE):
        levels[name] = lvl
        return True
    return False


class DataflowAnalysis:
    """The shared project-wide model every device-boundary check reads.

    Build once per run (checks/device_boundary.py caches one instance per
    Project identity) — the fixpoint over ~160 modules runs in well under
    a second, but five checks re-deriving it would still quintuple the
    gate's cost.
    """

    def __init__(self, project: Project):
        self.project = project
        self.mod_by_name: Dict[str, ModuleInfo] = {}
        self.imports: Dict[str, ImportTable] = {}  # path → table
        self.functions: Dict[Tuple[str, str], FunctionNode] = {}
        # bare method name → every (path, qual) defining it on a class
        self._methods_by_bare: Dict[str, List[Tuple[str, str]]] = {}
        # (path, ClassName) → field name → level
        self.class_fields: Dict[Tuple[str, str], Dict[str, int]] = {}
        self._index()
        self._solve()

    # --- indexing -------------------------------------------------------------

    def _index(self) -> None:
        for mod in self.project.modules:
            name = module_name_of(mod.path)
            self.mod_by_name[name] = mod
            self.imports[mod.path] = build_import_table(mod, name)
            for qual, fn in mod.functions.items():
                node = FunctionNode(
                    path=mod.path, qual=qual, node=fn, mod=mod,
                    params=[a.arg for a in fn.args.posonlyargs
                            + fn.args.args + fn.args.kwonlyargs])
                self.functions[node.key] = node
                bare = qual.rsplit(".", 1)[-1]
                if "." in qual:  # a method (or nested def)
                    self._methods_by_bare.setdefault(bare, []).append(
                        node.key)

    # --- call resolution ------------------------------------------------------

    def resolve_call(self, mod: ModuleInfo, caller_qual: str,
                     call: ast.Call) -> List[Tuple[str, str]]:
        """Possible (path, qual) targets of one call expression."""
        func = call.func
        out: List[Tuple[str, str]] = []
        table = self.imports.get(mod.path)
        if isinstance(func, ast.Name):
            name = func.id
            # local def: prefer the caller's own nesting chain outward
            scope = caller_qual
            while scope:
                nested = f"{scope}.{name}"
                if nested in mod.functions:
                    return [(mod.path, nested)]
                scope = scope.rsplit(".", 1)[0] if "." in scope else ""
            if name in mod.functions:
                return [(mod.path, name)]
            # imported symbol
            if table and name in table.symbols:
                src, sym = table.symbols[name]
                tgt = self._function_in(src, sym)
                if tgt:
                    return [tgt]
            return out
        if isinstance(func, ast.Attribute):
            recv, meth = func.value, func.attr
            if isinstance(recv, ast.Name):
                if recv.id == "self":
                    # method on the caller's class (same module)
                    cls = caller_qual.split(".")[0] if "." in caller_qual \
                        else ""
                    cand = f"{cls}.{meth}"
                    if cand in mod.functions:
                        return [(mod.path, cand)]
                    for q in mod.functions:
                        if q.rsplit(".", 1)[-1] == meth and "." in q:
                            out.append((mod.path, q))
                    return out
                if table and recv.id in table.modules:
                    tgt = self._function_in(table.modules[recv.id], meth)
                    return [tgt] if tgt else []
                if table and recv.id in table.symbols:
                    # symbol import of a module: from .. import whatif
                    src, sym = table.symbols[recv.id]
                    tgt = self._function_in(f"{src}.{sym}", meth)
                    if tgt:
                        return [tgt]
            # duck-typed receiver: resolve only when the method name is
            # defined exactly once project-wide and is not a common verb
            if meth not in _COMMON_METHODS:
                defs = self._methods_by_bare.get(meth, [])
                if len(defs) == 1:
                    return list(defs)
        return out

    def _function_in(self, module: str, sym: str) -> Optional[Tuple[str, str]]:
        mod = self.mod_by_name.get(module)
        if mod is None:
            return None
        if sym in mod.functions:
            return (mod.path, sym)
        return None

    # --- the taint fixpoint ---------------------------------------------------

    def _solve(self) -> None:
        for _ in range(20):  # converges in 3-5 passes on this tree
            changed = False
            for fn in self.functions.values():
                changed |= self._analyze_function(fn)
            if not changed:
                break

    def _seed_taint(self, fn: FunctionNode) -> Dict[str, int]:
        taint: Dict[str, int] = dict(fn.param_taint)
        for p in fn.params:
            if p in DEVICE_VALUE_NAMES:
                taint[p] = DEVICE
        # annotated params: ``def f(snap: DeviceSnapshot)`` → LOOSE object
        # (its attribute loads become DEVICE)
        for a in fn.node.args.args + fn.node.args.kwonlyargs:
            ann = a.annotation
            if ann is not None and \
                    dotted_name(ann).rsplit(".", 1)[-1] in DEVICE_CLASSES:
                _raise_to(taint, a.arg, LOOSE)
        return taint

    def _analyze_function(self, fn: FunctionNode) -> bool:
        """One intra-procedural pass under current summaries; returns True
        when any project-visible fact (param/return/class-field taint,
        local levels) changed."""
        taint = self._seed_taint(fn)
        cls_key = self._class_key(fn)
        changed = False
        # iterate the body to a local fixpoint (loops can taint backwards)
        for _ in range(8):
            grew = False
            for stmt in ast.walk(fn.node):
                if fn.mod.scope_of(stmt) != fn.qual:
                    continue
                grew |= self._transfer(fn, stmt, taint, cls_key)
            if not grew:
                break
        for name, lvl in taint.items():
            changed |= _raise_to(fn.taint, name, lvl)
        # return taint
        for stmt in ast.walk(fn.node):
            if isinstance(stmt, ast.Return) and stmt.value is not None \
                    and fn.mod.scope_of(stmt) == fn.qual:
                lvl = self.level_of(fn, stmt.value, taint)
                if lvl > fn.returns:
                    fn.returns = lvl
                    changed = True
        # call-site propagation: tainted args taint callee params
        for call in ast.walk(fn.node):
            if not isinstance(call, ast.Call) or \
                    fn.mod.scope_of(call) != fn.qual:
                continue
            targets = self.resolve_call(fn.mod, fn.qual, call)
            for key in targets:
                callee = self.functions.get(key)
                if callee is None:
                    continue
                fn.callees.add(key)
                params = callee.params
                skip = 1 if params[:1] == ["self"] else 0
                for i, arg in enumerate(call.args):
                    pi = i + skip
                    if pi >= len(params):
                        break
                    lvl = self.level_of(fn, arg, taint)
                    if lvl:
                        changed |= _raise_to(
                            callee.param_taint, params[pi], lvl)
                for kw in call.keywords:
                    if kw.arg and kw.arg in params:
                        lvl = self.level_of(fn, kw.value, taint)
                        if lvl:
                            changed |= _raise_to(
                                callee.param_taint, kw.arg, lvl)
        return changed

    def _class_key(self, fn: FunctionNode) -> Optional[Tuple[str, str]]:
        if "." not in fn.qual:
            return None
        return (fn.path, fn.qual.split(".")[0])

    def _transfer(self, fn: FunctionNode, stmt: ast.AST,
                  taint: Dict[str, int], cls_key) -> bool:
        """Apply one statement's taint transfer; True if levels grew."""
        grew = False
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            if value is None:
                return False
            lvl = self.level_of(fn, value, taint)
            if not lvl:
                return False
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            for tgt in targets:
                if isinstance(tgt, ast.Name):
                    grew |= _raise_to(taint, tgt.id, lvl)
                elif isinstance(tgt, (ast.Tuple, ast.List)):
                    # tuple-unpack of a tainted producer: each target gets
                    # LOOSE (which element is the array is not tracked)
                    for e in tgt.elts:
                        if isinstance(e, ast.Starred):
                            e = e.value
                        if isinstance(e, ast.Name):
                            grew |= _raise_to(taint, e.id, LOOSE)
                elif isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self" and cls_key is not None:
                    # self-field taint: device state stored on the object
                    # carries across method boundaries
                    fields = self.class_fields.setdefault(cls_key, {})
                    grew |= _raise_to(fields, tgt.attr, lvl)
                elif isinstance(tgt, ast.Subscript) and \
                        isinstance(tgt.value, ast.Attribute) and \
                        isinstance(tgt.value.value, ast.Name) and \
                        tgt.value.value.id == "self" and cls_key is not None:
                    # self._table[key] = <tainted> → the table is a LOOSE
                    # container of it
                    fields = self.class_fields.setdefault(cls_key, {})
                    grew |= _raise_to(fields, tgt.value.attr, LOOSE)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            lvl = self.level_of(fn, stmt.iter, taint)
            if lvl:
                # iterating a DEVICE array yields DEVICE rows; iterating a
                # LOOSE container yields its (loose) members
                for n in ast.walk(stmt.target):
                    if isinstance(n, ast.Name):
                        grew |= _raise_to(taint, n.id, lvl)
        elif isinstance(stmt, ast.comprehension):
            lvl = self.level_of(fn, stmt.iter, taint)
            if lvl:
                for n in ast.walk(stmt.target):
                    if isinstance(n, ast.Name):
                        grew |= _raise_to(taint, n.id, lvl)
        elif isinstance(stmt, ast.withitem) and stmt.optional_vars is not None:
            lvl = self.level_of(fn, stmt.context_expr, taint)
            if lvl and isinstance(stmt.optional_vars, ast.Name):
                grew |= _raise_to(taint, stmt.optional_vars.id, lvl)
        return grew

    # --- expression taint -----------------------------------------------------

    def level_of(self, fn: FunctionNode, expr: ast.AST,
                 taint: Optional[Dict[str, int]] = None) -> int:
        """NONE / LOOSE / DEVICE for one expression."""
        t = fn.taint if taint is None else taint

        def walk(e: ast.AST) -> int:
            if isinstance(e, ast.Name):
                if e.id in DEVICE_VALUE_NAMES:
                    return DEVICE
                return t.get(e.id, NONE)
            if isinstance(e, ast.Attribute):
                if e.attr in ARRAY_METADATA_ATTRS:
                    return NONE
                if e.attr in DEVICE_VALUE_NAMES:
                    return DEVICE
                if isinstance(e.value, ast.Name) and e.value.id == "self":
                    cls_key = self._class_key(fn)
                    if cls_key:
                        return self.class_fields.get(cls_key, {}).get(
                            e.attr, NONE)
                    return NONE
                base = walk(e.value)
                # a field of a device-holding object is (may be) an array
                return DEVICE if base else NONE
            if isinstance(e, ast.Subscript):
                base = walk(e.value)
                # a row of a DEVICE array is DEVICE; an element of a LOOSE
                # container stays LOOSE (which member is hot is untracked)
                return base
            if isinstance(e, ast.Call):
                return self.call_level(fn, e, t)
            if isinstance(e, ast.BinOp):
                return max(walk(e.left), walk(e.right))
            if isinstance(e, ast.UnaryOp):
                return walk(e.operand)
            if isinstance(e, ast.Compare):
                # identity checks never touch the device
                if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
                    return NONE
                lvl = max([walk(e.left)] + [walk(c) for c in e.comparators])
                # an elementwise compare OF a device array is a device
                # array; comparing LOOSE host objects is host work
                return DEVICE if lvl == DEVICE else NONE
            if isinstance(e, ast.BoolOp):
                return max(walk(v) for v in e.values)
            if isinstance(e, ast.IfExp):
                return max(walk(e.body), walk(e.orelse))
            if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
                lvl = max([walk(v) for v in e.elts], default=NONE)
                return LOOSE if lvl else NONE
            if isinstance(e, ast.Dict):
                lvl = max([walk(v) for v in e.values if v is not None],
                          default=NONE)
                return LOOSE if lvl else NONE
            if isinstance(e, ast.Starred):
                return walk(e.value)
            if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
                lvl = walk(e.elt)
                return LOOSE if lvl else NONE
            if isinstance(e, ast.NamedExpr):
                return walk(e.value)
            return NONE

        return walk(expr)

    def call_level(self, fn: FunctionNode, call: ast.Call,
                   taint: Optional[Dict[str, int]] = None) -> int:
        """Taint level of this call's RESULT."""
        t = fn.taint if taint is None else taint
        table = self.imports.get(fn.mod.path)
        name = dotted_name(call.func)
        head = name.split(".")[0] if name else ""
        # jnp.* results are device arrays; np.* (and int()/float()/
        # device_get) move to host
        if table is not None:
            if head in table.jnp_aliases():
                return DEVICE
            if head in table.np_aliases():
                return NONE
        elif head == "jnp":
            return DEVICE
        if name in HOST_TRANSFER_CALLS:
            return NONE
        if name in JAX_DEVICE_RESULTS:
            return DEVICE if (call.args and self.level_of(
                fn, call.args[0], t)) else NONE
        if name in JIT_WRAPPERS:
            # the jitted callable itself: LOOSE, so calling through a
            # variable / program-table subscript yields DEVICE below
            return LOOSE
        if name.startswith("jax.tree_util") or name.startswith("jax.tree"):
            # tree_map/tree_leaves over tainted pytrees keep their level
            lvl = max([self.level_of(fn, a, t) for a in call.args],
                      default=NONE)
            return lvl
        if isinstance(call.func, ast.Attribute):
            meth = call.func.attr
            if meth in DEVICE_PRODUCER_METHODS:
                return LOOSE  # DeviceSnapshot / (dsnap, upd) object
            if meth in ("item", "tolist"):
                return NONE
            if meth == "_replace":
                return self.level_of(fn, call.func.value, t)
        if isinstance(call.func, ast.Name):
            if call.func.id in DEVICE_CLASSES:
                return LOOSE
            # calling a local bound to a jitted program:
            #   prog = jax.jit(f); ... ; out = prog(x)
            if t.get(call.func.id, NONE):
                return DEVICE
        # calling through a jit-table subscript or tainted attribute:
        # jt["fused"](...) / self._progs[key](...)
        if isinstance(call.func, (ast.Subscript, ast.Attribute)) and \
                self.level_of(fn, call.func, t):
            return DEVICE
        # interprocedural: any resolved callee's return summary
        lvl = NONE
        for key in self.resolve_call(fn.mod, fn.qual, call):
            callee = self.functions.get(key)
            if callee is not None:
                lvl = max(lvl, callee.returns)
        return lvl

    # convenience predicates used by the checks ------------------------------

    def expr_tainted(self, fn: FunctionNode, expr: ast.AST) -> bool:
        return self.level_of(fn, expr) >= LOOSE

    def expr_device(self, fn: FunctionNode, expr: ast.AST) -> bool:
        return self.level_of(fn, expr) == DEVICE

    # --- reachability (for cycle-path checks) ---------------------------------

    def reachable_from(self, roots: Iterable[Tuple[str, str]],
                       stop: Iterable[Tuple[str, str]] = ()) -> \
            Set[Tuple[str, str]]:
        """Transitive callees of ``roots``; traversal does not descend
        INTO ``stop`` nodes (sanctioned fetch boundaries), though the
        boundary nodes themselves are listed as reached."""
        stop_set = set(stop)
        seen: Set[Tuple[str, str]] = set()
        work = [k for k in roots if k in self.functions]
        while work:
            key = work.pop()
            if key in seen:
                continue
            seen.add(key)
            if key in stop_set:
                continue
            fn = self.functions[key]
            # callees recorded during the fixpoint cover resolved calls;
            # nested defs are implicit callees (the enclosing function
            # builds and usually invokes or schedules them)
            for k2 in fn.callees:
                if k2 not in seen:
                    work.append(k2)
            for q2 in fn.mod.functions:
                if q2.startswith(fn.qual + ".") and \
                        (fn.path, q2) not in seen:
                    work.append((fn.path, q2))
        return seen

    def find_function(self, path_suffix: str,
                      qual: str) -> Optional[Tuple[str, str]]:
        for (path, q) in self.functions:
            if q == qual and path.endswith(path_suffix):
                return (path, q)
        return None


_CACHE: Dict[int, DataflowAnalysis] = {}


def analysis_for(project: Project) -> DataflowAnalysis:
    """One shared DataflowAnalysis per Project instance (checks run back
    to back over the same project; the fixpoint is the expensive part)."""
    key = id(project)
    hit = _CACHE.get(key)
    if hit is not None and hit.project is project:
        return hit
    _CACHE.clear()  # never hold more than one project alive
    _CACHE[key] = DataflowAnalysis(project)
    return _CACHE[key]
