"""Check registry — the analyzer's analog of plugins/__init__.py.

Each check is a class with a ``name``/``description`` and a
``run(project) -> Iterable[Finding]``; ``@register_check`` enrolls it so
tools/analyze.py and the tier-1 gate drive the same default set (mirroring
how the scheduler's BatchedFramework drives the registered plugin list).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Type

from .core import Finding, Project

CHECK_REGISTRY: Dict[str, Type["Check"]] = {}


class Check:
    name: str = ""
    description: str = ""

    def run(self, project: Project) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


def register_check(cls: Type[Check]) -> Type[Check]:
    assert cls.name, f"{cls.__name__} must define a name"
    CHECK_REGISTRY[cls.name] = cls
    return cls


def default_checks(names: Iterable[str] = ()) -> List[Check]:
    """Instantiate the requested checks (all registered ones by default).

    Importing .checks here (not at module import) keeps the lockcheck /
    maybe_wrap hot path free of analyzer imports.
    """
    from . import checks  # noqa: F401  (registers via decorators)

    wanted = list(names) or sorted(CHECK_REGISTRY)
    unknown = [n for n in wanted if n not in CHECK_REGISTRY]
    if unknown:
        raise KeyError(f"unknown checks: {unknown}; "
                       f"registered: {sorted(CHECK_REGISTRY)}")
    return [CHECK_REGISTRY[n]() for n in wanted]
