"""Ratchet baseline: grandfathered findings, count-matched by stable key.

The committed analysis_baseline.json maps Finding.key() -> count.  A run
is clean when no key exceeds its baselined count (NEW violations fail);
keys whose live count dropped are STALE — the baseline should be shrunk
(tools/analyze.py --write-baseline) so fixed sites stay fixed.  The gate
in tests/test_static_analysis.py enforces both directions: the baseline
only ever shrinks.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, List, Tuple

from .core import Finding

BASELINE_FILENAME = "analysis_baseline.json"


def baseline_counts(findings: List[Finding]) -> Dict[str, int]:
    return dict(Counter(f.key() for f in findings))


def load(path: str) -> Dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def write(findings: List[Finding], path: str) -> None:
    counts = dict(sorted(baseline_counts(findings).items()))
    comment = ("Grandfathered static-analysis findings — shrink this "
               "file (fix sites, rerun tools/analyze.py "
               "--write-baseline), never grow it.")
    if not counts:
        comment = ("EMPTY ratchet: the grandfathered baseline was burned "
                   "to zero — keep it empty.  Every finding now fails CI "
                   "outright; fix the site or add a justified "
                   "`ktpu-analysis: ignore[check] -- why` suppression.")
    data = {
        "version": 1,
        "comment": comment,
        "findings": counts,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1, sort_keys=False)
        fh.write("\n")


def diff(findings: List[Finding],
         baseline: Dict[str, int]) -> Tuple[List[Finding], List[str]]:
    """(new_findings, stale_keys).

    new_findings: concrete findings beyond the baselined count for their
    key (if a key has 2 live sites but baseline says 1, the LAST site by
    line number is reported as new).  stale_keys: baseline entries whose
    live count dropped below the recorded count.
    """
    live = baseline_counts(findings)
    by_key: Dict[str, List[Finding]] = {}
    for f in findings:
        by_key.setdefault(f.key(), []).append(f)
    new: List[Finding] = []
    for key, fs in by_key.items():
        allowed = baseline.get(key, 0)
        if len(fs) > allowed:
            fs_sorted = sorted(fs, key=lambda f: f.line)
            new.extend(fs_sorted[allowed:])
    stale = [k for k, n in baseline.items() if live.get(k, 0) < n]
    new.sort(key=lambda f: (f.path, f.line, f.check))
    return new, sorted(stale)
