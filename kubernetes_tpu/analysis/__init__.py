"""Project-native static invariant checkers + runtime lock instrumentation.

The reproduction's hack/verify-* analog, two engines deep: per-module AST
checks over this codebase's real failure modes (trace safety at the jit
boundary, recompile hazards, lock discipline, exception hygiene, metrics
registration) and an interprocedural device-boundary dataflow pass
(call graph + two-level device-taint lattice) behind the host-sync /
vmap-purity / donation-aliasing / shape-drift / blocking-in-cycle checks.
The committed baseline is EMPTY — every finding fails tier-1 outright; the
sanctioned escapes are the FETCH_BOUNDARIES config and justified
``ktpu-analysis: ignore[check] -- why`` comments (which the engine lints).
An opt-in runtime lock-order monitor (lockcheck) runs under the chaos,
descheduler, and autoscaler batteries.

Entry points:
  tools/analyze.py           CLI (human/JSON reports, --check all gate,
                             --diff REF changed-files gate,
                             --write-baseline)
  analysis.registry          check registry (default_checks)
  analysis.core              engine 1 (load_project / run_checks /
                             suppressions)
  analysis.dataflow          engine 2 (DataflowAnalysis / analysis_for)
  analysis.baseline          ratchet (load / diff / write)
  analysis.lockcheck         runtime lock wrapper (maybe_wrap / activate)

This __init__ stays import-light on purpose: lock owners import
``analysis.lockcheck`` on hot construction paths; the ast machinery loads
only when a caller pulls registry/core explicitly.
"""
