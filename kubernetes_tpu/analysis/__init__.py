"""Project-native static invariant checker + runtime lock instrumentation.

The reproduction's hack/verify-* analog: AST checks over this codebase's
real failure modes (trace safety at the jit boundary, recompile hazards,
lock discipline, exception hygiene, metrics registration), ratcheted
against a committed baseline so tier-1 fails only on NEW violations, plus
an opt-in runtime lock-order monitor (lockcheck) the chaos battery runs
under.

Entry points:
  tools/analyze.py           CLI (human/JSON reports, --check gate,
                             --write-baseline)
  analysis.registry          check registry (default_checks)
  analysis.core              engine (load_project / run_checks)
  analysis.baseline          ratchet (load / diff / write)
  analysis.lockcheck         runtime lock wrapper (maybe_wrap / activate)

This __init__ stays import-light on purpose: lock owners import
``analysis.lockcheck`` on hot construction paths; the ast machinery loads
only when a caller pulls registry/core explicitly.
"""
