"""Thread-ownership analysis: role graph + ownership lattice + handoffs.

The THIRD analysis engine (core.py is per-module AST invariants, PR 7's
dataflow.py is the device-boundary taint engine).  This one answers the
question the concurrent runtime (PRs 10-16) has so far answered only by
convention: "which thread owns this field, and is every cross-thread
access synchronized or explicitly handed off?"  The pipeline:

  1. spawn sites    — every ``threading.Thread(target=…)``, ``Timer``,
                      executor ``submit``/``map`` and ``ThreadPoolExecutor``
                      construction in the project; each resolvable target
                      seeds one thread ROLE.
  2. role graph     — roles propagate through PR 7's interprocedural call
                      graph (DataflowAnalysis.resolve_call edges): a
                      function's role set is every thread kind it may run
                      under.  MAIN seeds every function not exclusively
                      reachable from spawn targets, so a helper called both
                      from the dispatch path and from a background closure
                      ends up {main, <spawn role>} — the racy shape.
  3. ownership      — per-class ``self``-field lattice (plus ``global``
                      writes): each access site carries (role set,
                      lock-held).  A field written under ≥2 roles, or
                      written under one role and read under another, must
                      be lock-protected at every conflicting site (reusing
                      lock_discipline's always-locked-helper propagation),
                      be a recognized HANDOFF field, or carry a justified
                      suppression.
  4. handoffs       — the `_InFlight`/`_SyncAhead` pattern: a record local
                      published once, its fields written by the spawned
                      closure (directly or through default-arg aliases) and
                      consumed only after an explicit ``<rec>.<thread>
                      .join()`` the engine verifies DOMINATES the read
                      (lexical statement order, join-helper calls resolved
                      transitively, pre-joined aliases tracked through
                      calls to joining functions).

Deliberate approximations (documented, covered elsewhere):
  - callbacks registered into fan-out seams (``store.watch(self._apply)``)
    run under the REGISTRAR's roles — the runtime access sanitizer
    (lockcheck.AccessSanitizer) is the cross-check for those paths;
  - join dominance is lexical (statement order within one function, plus
    caller-side domination for annotated record parameters), not a CFG
    dominator tree;
  - consumer discovery is same-module (every handoff record in this tree
    lives and dies inside the module that spawns its thread).

Checks built on top live in checks/thread_ownership.py.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import ModuleInfo, Project, dotted_name
from .dataflow import DataflowAnalysis, analysis_for

MAIN = "main"

# synchronization-primitive constructors (beyond Lock/RLock, which
# lock_discipline._lock_attrs already recognizes): a Condition wraps a
# lock, so ``with self._cond`` is lock-held; Semaphores gate, not own
_SYNC_CTORS = {"Condition", "Semaphore", "BoundedSemaphore"}

# method bare-names recognized as a stop/close path for daemon-lifecycle
STOP_METHODS = {"close", "stop", "shutdown", "abandon_inflight"}

Key = Tuple[str, str]  # (path, qualname) — dataflow FunctionNode key


# ---------------------------------------------------------------------------
# spawn sites
# ---------------------------------------------------------------------------


@dataclass
class SpawnSite:
    """One thread/executor creation point."""

    path: str
    lineno: int
    call: ast.Call
    kind: str  # "thread" | "timer" | "submit" | "map" | "executor"
    spawner_qual: str  # enclosing function qualname ("" = module level)
    target_expr: Optional[ast.AST]  # the callable handed to the thread
    target_key: Optional[Key]  # resolved project function, if any
    role: str  # role label seeded by this site
    store_obj: str = ""  # receiver name when stored `<obj>.<attr> = Thread`
    store_attr: str = ""  # the attr ("" when not attribute-stored)
    store_local: str = ""  # local var name when `t = Thread(...)`


def _sync_attrs(cls: ast.ClassDef) -> Set[str]:
    """Lock-like self attributes: Lock/RLock (lock_discipline) plus bare
    Condition()/Semaphore() constructions."""
    out = _lock_attrs(cls)
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        makes = any(
            isinstance(n, ast.Call)
            and dotted_name(n.func).rsplit(".", 1)[-1] in _SYNC_CTORS
            for n in ast.walk(node.value))
        if not makes:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
                out.add(tgt.attr)
    return out


def _spawn_kind(call: ast.Call) -> Optional[str]:
    name = dotted_name(call.func)
    last = name.rsplit(".", 1)[-1] if name else ""
    if last == "Thread":
        return "thread"
    if last == "Timer":
        return "timer"
    if last == "ThreadPoolExecutor":
        return "executor"
    if isinstance(call.func, ast.Attribute):
        if call.func.attr == "submit":
            return "submit"
        if call.func.attr == "map":
            # Executor.map only: plain ``.map`` is too common — require a
            # pool-ish receiver (the scheduler's ``self._ext_pool().map``)
            recv = call.func.value
            recv_name = (dotted_name(recv.func) if isinstance(recv, ast.Call)
                         else dotted_name(recv)).lower()
            if "pool" in recv_name or "executor" in recv_name:
                return "map"
    return None


def _spawn_target_expr(call: ast.Call, kind: str) -> Optional[ast.AST]:
    if kind == "thread":
        for kw in call.keywords:
            if kw.arg == "target":
                return kw.value
        return None
    if kind == "timer":
        for kw in call.keywords:
            if kw.arg == "function":
                return kw.value
        return call.args[1] if len(call.args) > 1 else None
    if kind in ("submit", "map"):
        return call.args[0] if call.args else None
    return None  # executor construction has no target


# ---------------------------------------------------------------------------
# ownership lattice
# ---------------------------------------------------------------------------


@dataclass
class AccessSite:
    node: ast.AST
    lineno: int
    scope: str  # innermost function qualname containing the access
    method: str  # bare class-method name the site lives in
    roles: Set[str]
    locked: bool
    is_write: bool


@dataclass
class FieldOwnership:
    """One (class, field) row of the ownership report."""

    path: str
    cls: str
    name: str
    sites: List[AccessSite] = field(default_factory=list)
    # filled by _classify():
    write_roles: Set[str] = field(default_factory=set)
    read_roles: Set[str] = field(default_factory=set)
    conflict: bool = False
    classification: str = "single-role"  # | locked | handoff | racy

    def writes(self) -> List[AccessSite]:
        return [s for s in self.sites if s.is_write]

    def reads(self) -> List[AccessSite]:
        return [s for s in self.sites if not s.is_write]


@dataclass
class Handoff:
    """One record class published to a spawned thread (`_SyncAhead`)."""

    path: str  # module the spawner lives in
    cls: str  # record class name
    thread_attrs: Set[str] = field(default_factory=set)  # `thread`
    data_fields: Set[str] = field(default_factory=set)  # thread-written
    spawner_quals: Set[str] = field(default_factory=set)
    spawn_lines: Dict[str, int] = field(default_factory=dict)  # qual → line
    spawn_nodes: Dict[str, ast.Call] = field(default_factory=dict)
    record_locals: Dict[str, str] = field(default_factory=dict)  # qual → name
    publish_fields: Set[str] = field(default_factory=set)  # self.<f> = rec


class ThreadAnalysis:
    """Shared project-wide thread model every thread check reads."""

    def __init__(self, project: Project):
        self.project = project
        self.dfa: DataflowAnalysis = analysis_for(project)
        self.spawns: List[SpawnSite] = []
        self.roles: Dict[Key, Set[str]] = {}
        self.fields: Dict[Tuple[str, str, str], FieldOwnership] = {}
        self.globals: Dict[Tuple[str, str], FieldOwnership] = {}
        self.handoffs: Dict[Tuple[str, str], Handoff] = {}
        # functions that (transitively) join a handoff thread attr
        self._joinish: Set[Key] = set()
        # roles whose EVERY spawn stores its thread into a handoff record
        # attr (join-dominance of those attrs is handoff-discipline's job)
        self.join_bounded_roles: Set[str] = set()
        # role → classes containing its spawn sites (the spawning class
        # itself gets no loan exemption: it runs concurrently with the
        # thread it spawned, by construction)
        self.role_spawn_class: Dict[str, Set[Tuple[str, str]]] = {}
        self._find_spawns()
        self._assign_roles()
        self._find_handoffs()
        self._build_ownership()

    # --- spawn discovery --------------------------------------------------

    def _find_spawns(self) -> None:
        for mod in self.project.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                kind = _spawn_kind(node)
                if kind is None:
                    continue
                target = _spawn_target_expr(node, kind)
                key = self._resolve_target(mod, node, target)
                self.spawns.append(SpawnSite(
                    path=mod.path, lineno=node.lineno, call=node, kind=kind,
                    spawner_qual=mod.scope_of(node),
                    target_expr=target, target_key=key,
                    role=self._role_name(mod, node, key, target),
                    **self._storage_of(mod, node)))

    def _resolve_target(self, mod: ModuleInfo, call: ast.Call,
                        target: Optional[ast.AST]) -> Optional[Key]:
        if target is None:
            return None
        # resolve_call only inspects .func — wrap the target expression so
        # the dataflow engine's whole resolution ladder (nesting chain,
        # self-methods, imports, unique-bare-name duck typing) applies
        probe = ast.Call(func=target, args=[], keywords=[])
        hits = self.dfa.resolve_call(mod, mod.scope_of(call), probe)
        return hits[0] if len(hits) == 1 else None

    def _role_name(self, mod: ModuleInfo, call: ast.Call,
                   key: Optional[Key], target: Optional[ast.AST]) -> str:
        base = os.path.basename(mod.path)
        if key is not None:
            return f"{base}:{key[1]}"
        label = dotted_name(target) if target is not None else "<opaque>"
        return f"{base}:{label or '<lambda>'}@L{call.lineno}"

    def _storage_of(self, mod: ModuleInfo, call: ast.Call) -> Dict[str, str]:
        """Where the Thread/executor object lands: `<obj>.<attr> = …`,
        `local = …`, or nothing (fire-and-forget / comprehension)."""
        out = {"store_obj": "", "store_attr": "", "store_local": ""}
        parent = mod.parents.get(call)
        # `t = Thread(…)` nested in a list comprehension: credit the
        # comprehension's assignment target (chaos/flood.py reader pool)
        hops = 0
        while parent is not None and not isinstance(parent, ast.Assign) \
                and hops < 4:
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef, ast.Module)):
                return out
            parent = mod.parents.get(parent)
            hops += 1
        if not isinstance(parent, ast.Assign):
            return out
        for tgt in parent.targets:
            if isinstance(tgt, ast.Attribute):
                out["store_attr"] = tgt.attr
                out["store_obj"] = dotted_name(tgt.value)
                return out
            if isinstance(tgt, ast.Name):
                out["store_local"] = tgt.id
                # keep scanning: `pool = self._ext_pool_obj = …` stores both
        return out

    # --- role graph -------------------------------------------------------

    def _bfs(self, roots: Iterable[Key]) -> Set[Key]:
        """Transitive callees over RESOLVED call edges only — nested defs
        are NOT implicit callees here (defining a closure is not running
        it; the spawn site decides which role runs it)."""
        seen: Set[Key] = set()
        work = [k for k in roots if k in self.dfa.functions]
        while work:
            key = work.pop()
            if key in seen:
                continue
            seen.add(key)
            for k2 in self.dfa.functions[key].callees:
                if k2 not in seen and k2 in self.dfa.functions:
                    work.append(k2)
        return seen

    def _assign_roles(self) -> None:
        spawn_only: Set[Key] = set()
        for sp in self.spawns:
            if sp.target_key is None:
                continue
            closure = self._bfs([sp.target_key])
            spawn_only |= closure
            for k in closure:
                self.roles.setdefault(k, set()).add(sp.role)
        # MAIN seeds: every function NOT exclusively thread-reachable.
        # Propagating main through the same call edges then re-adds it to
        # shared helpers (e.g. _assign_with_extenders: called from the
        # dispatch path AND from the async walk closure → {main, walk}).
        main_seeds = [k for k in self.dfa.functions if k not in spawn_only]
        for k in self._bfs(main_seeds):
            self.roles.setdefault(k, set()).add(MAIN)
        for k in main_seeds:
            self.roles.setdefault(k, set()).add(MAIN)

    def roles_of(self, path: str, qual: str) -> Set[str]:
        """Role set for code whose innermost function scope is ``qual``
        (class-body / module-level statements run on the importing or
        constructing thread → MAIN)."""
        got = self.roles.get((path, qual))
        if got:
            return got
        return {MAIN}

    # --- handoff recognition ----------------------------------------------

    def _find_handoffs(self) -> None:
        by_path = self.project.by_path()
        for sp in self.spawns:
            if sp.target_key is None or not sp.store_attr:
                continue
            if not sp.store_obj or sp.store_obj == "self" or \
                    "." in sp.store_obj:
                continue  # self-attr storage is the ownership lattice's job
            mod = by_path.get(sp.path)
            if mod is None or sp.spawner_qual not in mod.functions:
                continue
            spawner = mod.functions[sp.spawner_qual]
            cls_name = self._record_class(mod, spawner, sp.store_obj)
            if cls_name is None:
                continue
            h = self.handoffs.setdefault(
                (sp.path, cls_name), Handoff(path=sp.path, cls=cls_name))
            h.thread_attrs.add(sp.store_attr)
            h.spawner_quals.add(sp.spawner_qual)
            h.spawn_lines[sp.spawner_qual] = sp.lineno
            h.spawn_nodes[sp.spawner_qual] = sp.call
            h.record_locals[sp.spawner_qual] = sp.store_obj
            h.data_fields |= self._thread_written_fields(
                mod, sp.spawner_qual, sp.store_obj)
            h.publish_fields |= self._publish_fields(
                mod, spawner, sp.store_obj)
            self.join_bounded_roles.add(sp.role)
        if self.handoffs:
            self._solve_joinish()
        # a role is join-bounded only when ALL of its spawns are record-
        # stored; any bare spawn of the same role voids the bound
        for sp in self.spawns:
            key = (sp.path, self._record_class_of_spawn(sp))
            if key not in self.handoffs and sp.role in self.join_bounded_roles:
                self.join_bounded_roles.discard(sp.role)
        for sp in self.spawns:
            self.role_spawn_class.setdefault(sp.role, set()).add(
                (sp.path, self._spawn_class_name(sp)))

    def _record_class_of_spawn(self, sp: SpawnSite) -> str:
        if not sp.store_attr or not sp.store_obj or sp.store_obj == "self" \
                or "." in sp.store_obj:
            return ""
        mod = self.project.by_path().get(sp.path)
        if mod is None or sp.spawner_qual not in mod.functions:
            return ""
        return self._record_class(mod, mod.functions[sp.spawner_qual],
                                  sp.store_obj) or ""

    def _spawn_class_name(self, sp: SpawnSite) -> str:
        mod = self.project.by_path().get(sp.path)
        if mod is None:
            return ""
        for anc in mod.ancestors(sp.call):
            if isinstance(anc, ast.ClassDef):
                return anc.name
        return ""

    def _record_class(self, mod: ModuleInfo, spawner: ast.AST,
                      name: str) -> Optional[str]:
        """Class of the record local ``name = ClassName(...)`` in spawner."""
        for node in ast.walk(spawner):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            if any(isinstance(t, ast.Name) and t.id == name
                   for t in node.targets):
                ctor = dotted_name(node.value.func).rsplit(".", 1)[-1]
                if ctor and (ctor[:1].isupper() or ctor.startswith("_")):
                    return ctor
        return None

    def _thread_written_fields(self, mod: ModuleInfo, spawner_qual: str,
                               record: str) -> Set[str]:
        """Attrs the spawned closure (any nested def of the spawner, which
        is where every thread body in this tree lives) writes on the record
        — directly by its captured name or through a default-arg alias
        (``def _bg_fetch(rec=fl)``)."""
        out: Set[str] = set()
        for qual, fn in mod.functions.items():
            if not qual.startswith(spawner_qual + "."):
                continue
            aliases = {record}
            args = fn.args
            defaults = args.defaults
            pos = (args.posonlyargs + args.args)[-len(defaults):] \
                if defaults else []
            for a, d in zip(pos, defaults):
                if isinstance(d, ast.Name) and d.id == record:
                    aliases.add(a.arg)
            for a, d in zip(args.kwonlyargs, args.kw_defaults):
                if isinstance(d, ast.Name) and d.id == record:
                    aliases.add(a.arg)
            for node in ast.walk(fn):
                if mod.scope_of(node) != qual:
                    continue
                if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    tgts = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in tgts:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id in aliases:
                            out.add(t.attr)
        return out

    def _publish_fields(self, mod: ModuleInfo, spawner: ast.AST,
                        record: str) -> Set[str]:
        """self-fields the spawner publishes the record into."""
        out: Set[str] = set()
        for node in ast.walk(spawner):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == record:
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr:
                        out.add(attr)
        return out

    def _solve_joinish(self) -> None:
        """Functions that join a handoff thread attr, transitively: a call
        to a joinish function is as good as the ``.join()`` itself (the
        scheduler's `_join_sync_ahead` helper)."""
        thread_attrs = set()
        for h in self.handoffs.values():
            thread_attrs |= h.thread_attrs
        direct: Set[Key] = set()
        for key, fn in self.dfa.functions.items():
            if self._has_direct_join(fn.mod, fn.node, fn.qual, thread_attrs):
                direct.add(key)
        self._joinish = set(direct)
        changed = True
        while changed:
            changed = False
            for key, fn in self.dfa.functions.items():
                if key in self._joinish:
                    continue
                if fn.callees & self._joinish:
                    self._joinish.add(key)
                    changed = True

    @staticmethod
    def _has_direct_join(mod: ModuleInfo, fn: ast.AST, qual: str,
                         thread_attrs: Set[str]) -> bool:
        for node in ast.walk(fn):
            if mod.scope_of(node) != qual:
                continue
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "join" and \
                    isinstance(node.func.value, ast.Attribute) and \
                    node.func.value.attr in thread_attrs:
                return True
        return False

    def join_barrier_lines(self, mod: ModuleInfo, fn: ast.AST,
                           qual: str, h: Handoff) -> List[int]:
        """Line numbers in ``fn`` after which the handoff's thread has
        provably been joined: direct ``.<thread>.join()`` calls and calls
        resolving to joinish functions."""
        out: List[int] = []
        for node in ast.walk(fn):
            if mod.scope_of(node) != qual or not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "join" and \
                    isinstance(node.func.value, ast.Attribute) and \
                    node.func.value.attr in h.thread_attrs:
                out.append(node.lineno)
                continue
            for key in self.dfa.resolve_call(mod, qual, node):
                if key in self._joinish:
                    out.append(node.lineno)
                    break
        return sorted(out)

    def record_aliases(self, mod: ModuleInfo, fn: ast.AST, qual: str,
                       h: Handoff) -> Dict[str, Tuple[int, bool, str]]:
        """Locals in ``fn`` bound to a handoff record:
        name → (binding line, pre_joined, kind).

        pre_joined=True when the alias came from a call to a joinish
        function (``prep = self._take_sync_ahead()`` hands back an
        already-joined record — no further barrier needed).  kind is one
        of "param" (annotated parameter), "publish" (loaded from the
        publication field), "joinish", "ctor"."""
        out: Dict[str, Tuple[int, bool, str]] = {}
        # annotated parameters: ``def _complete(self, fl: _InFlight)``
        for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
            if a.annotation is not None and \
                    self._annotation_names(a.annotation) & {h.cls}:
                out[a.arg] = (fn.lineno, False, "param")
        for node in ast.walk(fn):
            if mod.scope_of(node) != qual or not isinstance(node, ast.Assign):
                continue
            pairs: List[Tuple[ast.AST, ast.AST]] = []
            if len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Tuple) and \
                    isinstance(node.value, ast.Tuple) and \
                    len(node.targets[0].elts) == len(node.value.elts):
                pairs = list(zip(node.targets[0].elts, node.value.elts))
            else:
                pairs = [(t, node.value) for t in node.targets]
            for tgt, val in pairs:
                if not isinstance(tgt, ast.Name):
                    continue
                if _self_attr(val) in h.publish_fields:
                    out[tgt.id] = (node.lineno, False, "publish")
                elif isinstance(val, ast.Call):
                    keys = self.dfa.resolve_call(mod, qual, val)
                    if keys and all(k in self._joinish for k in keys):
                        # prep = self._take_sync_ahead(): the record comes
                        # back already joined — no further barrier needed
                        out[tgt.id] = (node.lineno, True, "joinish")
                    elif dotted_name(val.func).rsplit(".", 1)[-1] == h.cls:
                        out[tgt.id] = (node.lineno, False, "ctor")
        return out

    @staticmethod
    def _annotation_names(ann: ast.AST) -> Set[str]:
        """Bare class names mentioned by an annotation (unwraps Optional[X],
        quotes, unions)."""
        out: Set[str] = set()
        for node in ast.walk(ann):
            if isinstance(node, ast.Name):
                out.add(node.id)
            elif isinstance(node, ast.Attribute):
                out.add(node.attr)
            elif isinstance(node, ast.Constant) and isinstance(node.value, str):
                out.add(node.value.split("[")[-1].rstrip("]").strip())
        return out

    # --- ownership lattice ------------------------------------------------

    def _build_ownership(self) -> None:
        for mod in self.project.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    self._scan_class(mod, node)
            self._scan_globals(mod)
        for fo in list(self.fields.values()) + list(self.globals.values()):
            self._classify(fo)

    def _scan_class(self, mod: ModuleInfo, cls: ast.ClassDef) -> None:
        cls_qual = mod.scope_of(cls) or cls.name
        locks = _sync_attrs(cls)
        wrappers = _lock_wrappers(cls, locks)
        propagated = _always_locked_methods(
            _intra_class_calls(mod, cls, cls_qual, locks, wrappers))
        method_names = {n.name for n in cls.body
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))}

        def add_site(attr: str, node: ast.AST, is_write: bool) -> None:
            if attr in locks or attr in method_names:
                return
            scope = mod.scope_of(node)
            if not scope.startswith(cls_qual + "."):
                return  # class-body statement: construction, not sharing
            method = scope[len(cls_qual) + 1:].split(".", 1)[0]
            if method in EXEMPT_METHODS:
                return  # the object is not shared during construction
            locked = (_under_lock(mod, node, locks, cls, wrappers)
                      or method in propagated)
            fo = self.fields.setdefault(
                (mod.path, cls.name, attr),
                FieldOwnership(path=mod.path, cls=cls.name, name=attr))
            fo.sites.append(AccessSite(
                node=node, lineno=getattr(node, "lineno", 0), scope=scope,
                method=method, roles=self.roles_of(mod.path, scope),
                locked=locked, is_write=is_write))

        for node in ast.walk(cls):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                tgts = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in tgts:
                    attr = _self_attr(t)
                    if attr:
                        add_site(attr, node, True)
                if isinstance(node, ast.AugAssign):
                    attr = _self_attr(node.target)
                    if attr:
                        add_site(attr, node, False)  # += also reads
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr:
                        add_site(attr, node, True)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in MUTATING_METHODS:
                attr = _self_attr(node.func.value)
                if attr:
                    add_site(attr, node, True)
            elif isinstance(node, ast.Attribute) and \
                    isinstance(node.ctx, ast.Load) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self":
                parent = mod.parents.get(node)
                if isinstance(parent, ast.Subscript) and \
                        isinstance(parent.ctx, (ast.Store, ast.Del)):
                    pass  # self.X[k] = v — already a write via the Assign
                elif isinstance(parent, ast.Call) and parent.func is node:
                    pass  # self.meth(...) handled via MUTATING_METHODS
                elif isinstance(parent, ast.Attribute) and \
                        isinstance(mod.parents.get(parent), ast.Call) and \
                        mod.parents[parent].func is parent and \
                        parent.attr in MUTATING_METHODS:
                    pass  # self.X.append(...) — already a write site
                else:
                    add_site(node.attr, node, False)

    def _scan_globals(self, mod: ModuleInfo) -> None:
        """Module globals written via ``global X`` inside functions."""
        for qual, fn in mod.functions.items():
            declared: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Global) and \
                        mod.scope_of(node) == qual:
                    declared |= set(node.names)
            if not declared:
                continue
            roles = self.roles_of(mod.path, qual)
            for node in ast.walk(fn):
                if mod.scope_of(node) != qual:
                    continue
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id in declared:
                            fo = self.globals.setdefault(
                                (mod.path, t.id),
                                FieldOwnership(path=mod.path, cls="",
                                               name=t.id))
                            fo.sites.append(AccessSite(
                                node=node, lineno=node.lineno, scope=qual,
                                method=qual, roles=roles, locked=False,
                                is_write=True))

    def _classify(self, fo: FieldOwnership) -> None:
        for s in fo.sites:
            (fo.write_roles if s.is_write else fo.read_roles).update(s.roles)
        writes = fo.writes()
        multi_write = len(fo.write_roles) >= 2
        cross_read = (len(fo.write_roles) == 1
                      and bool(fo.read_roles - fo.write_roles))
        fo.conflict = bool(writes) and (multi_write or cross_read)
        if not fo.conflict:
            fo.classification = "single-role"
            return
        if fo.cls and self._is_handoff_field(fo):
            fo.classification = "handoff"
            return
        conflicting = writes + [r for r in fo.reads()
                                if r.roles - fo.write_roles]
        if all(s.locked for s in conflicting):
            fo.classification = "locked"
        elif fo.cls and self._is_loaned(fo):
            fo.classification = "loaned"
        else:
            fo.classification = "racy"

    def _is_loaned(self, fo: FieldOwnership) -> bool:
        """The sync-overlap protocol LOANS whole objects (the encoder, the
        snapshot) to a spawned thread for its bounded lifetime; the join
        that handoff-discipline verifies transfers ownership back.  A
        conflict whose every non-main role is join-bounded is therefore
        protected by that protocol — except on the spawning class itself,
        which by construction runs concurrently with its own spawn (its
        shared fields need a lock or a record handoff, not a loan).  The
        runtime access sanitizer is the cross-check for loaned classes."""
        nonmain = (fo.write_roles | fo.read_roles) - {MAIN}
        if not nonmain:
            return False
        for r in nonmain:
            if r not in self.join_bounded_roles:
                return False
            if (fo.path, fo.cls) in self.role_spawn_class.get(r, set()):
                return False
        return True

    def _is_handoff_field(self, fo: FieldOwnership) -> bool:
        h = self.handoffs.get((fo.path, fo.cls))
        if h is None:
            return False
        return fo.name in h.data_fields or fo.name in h.thread_attrs

    # --- the report (CLI --report-ownership + the runtime sanitizer) -------

    def ownership_report(self) -> Dict[str, Dict[str, dict]]:
        """class name → field → {roles, write_roles, classification}.

        The runtime access sanitizer (lockcheck.AccessSanitizer.verify)
        compares observed per-thread write patterns against this: a field
        the static engine calls single-role or locked must never show
        unsynchronized multi-thread writes at runtime."""
        out: Dict[str, Dict[str, dict]] = {}
        for (path, cls, name), fo in sorted(self.fields.items()):
            out.setdefault(cls, {})[name] = {
                "path": path,
                "roles": sorted(fo.write_roles | fo.read_roles),
                "write_roles": sorted(fo.write_roles),
                "classification": fo.classification,
            }
        return out


# ---------------------------------------------------------------------------
# cache (mirrors dataflow.analysis_for)
# ---------------------------------------------------------------------------

_CACHE: Dict[int, ThreadAnalysis] = {}


def thread_analysis_for(project: Project) -> ThreadAnalysis:
    key = id(project)
    hit = _CACHE.get(key)
    if hit is not None and hit.project is project:
        return hit
    _CACHE.clear()
    _CACHE[key] = ThreadAnalysis(project)
    return _CACHE[key]


_REPO_REPORT: Optional[Dict[str, Dict[str, dict]]] = None


def repo_ownership_report() -> Dict[str, Dict[str, dict]]:
    """The repo's own ownership report, computed once per process — the
    runtime access sanitizer's static reference (test fixtures call this
    lazily, only when a candidate contradiction was actually observed)."""
    global _REPO_REPORT
    if _REPO_REPORT is None:
        from .core import DEFAULT_SCAN_PATHS, load_project

        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        project = load_project(root, DEFAULT_SCAN_PATHS)
        _REPO_REPORT = ThreadAnalysis(project).ownership_report()
    return _REPO_REPORT


# Imported LAST, not at the top: importing checks/ runs checks/__init__,
# which imports checks/thread_ownership.py, which imports back into THIS
# module.  With every name above already bound, the cycle resolves in
# either entry order (threads first, or the check registry first).  The
# helpers are only called from function bodies, never at module scope.
from .checks.lock_discipline import (  # noqa: E402
    EXEMPT_METHODS,
    MUTATING_METHODS,
    _always_locked_methods,
    _intra_class_calls,
    _lock_attrs,
    _lock_wrappers,
    _self_attr,
    _under_lock,
)
