"""Thread checks: ownership, handoff discipline, thread-locals, lifecycle.

Four checks over the shared ThreadAnalysis model (analysis/threads.py):

  thread-ownership     — a self-field or module global written under one
                         thread role and touched under another, with no
                         held lock, no handoff, no suppression.
  handoff-discipline   — a handoff record's data field read before the
                         thread join that makes the write visible, or the
                         record republished without consuming/guarding the
                         previous one.
  thread-local-context — implicit thread-local context passing: module-
                         level ``threading.local()`` blobs, and class
                         thread-locals whose attrs leak outside the class
                         (the PR 14 span-context rule, now enforced).
  daemon-lifecycle     — every spawned thread must be joined somewhere or
                         poll a stop signal wired to a recognized stop /
                         close path; executors need a shutdown path.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import Finding, ModuleInfo, Project, dotted_name
from ..registry import Check, register_check
from ..threads import (
    MAIN,
    STOP_METHODS,
    Handoff,
    SpawnSite,
    ThreadAnalysis,
    thread_analysis_for,
)


def _fmt_roles(roles: Set[str]) -> str:
    return "{" + ", ".join(sorted(roles)) + "}"


@register_check
class ThreadOwnershipCheck(Check):
    name = "thread-ownership"
    description = ("self-fields / globals accessed under multiple thread "
                   "roles without a held lock or recognized handoff")

    def run(self, project: Project) -> Iterable[Finding]:
        ta = thread_analysis_for(project)
        by_path = project.by_path()
        for (path, cls, attr), fo in sorted(ta.fields.items()):
            if fo.classification != "racy":
                continue
            mod = by_path[path]
            for s in fo.writes():
                if s.locked:
                    continue
                yield mod.finding(
                    self.name, "unsynchronized-cross-role-write", s.node,
                    f"`self.{attr}` ({cls}) is written under roles "
                    f"{_fmt_roles(fo.write_roles)} and read under "
                    f"{_fmt_roles(fo.read_roles)} — this write in "
                    f"`{s.method}` holds no lock and the field is not a "
                    f"recognized handoff")
            for s in fo.reads():
                if s.locked or not (s.roles - fo.write_roles):
                    continue
                yield mod.finding(
                    self.name, "cross-role-read", s.node,
                    f"`self.{attr}` ({cls}) is written under roles "
                    f"{_fmt_roles(fo.write_roles)} but read here in "
                    f"`{s.method}` under {_fmt_roles(s.roles)} with no "
                    f"held lock — the read races the writer")
        for (path, name), fo in sorted(ta.globals.items()):
            if fo.classification != "racy":
                continue
            mod = by_path[path]
            for s in fo.writes():
                if s.locked:
                    continue
                yield mod.finding(
                    self.name, "global-cross-role", s.node,
                    f"module global `{name}` is written under roles "
                    f"{_fmt_roles(fo.write_roles)} with no lock — "
                    f"cross-thread global mutation")


@register_check
class HandoffDisciplineCheck(Check):
    name = "handoff-discipline"
    description = ("handoff record fields read before the dominating "
                   "join; records republished while a consumer is live")

    def run(self, project: Project) -> Iterable[Finding]:
        ta = thread_analysis_for(project)
        by_path = project.by_path()
        for (path, cls), h in sorted(ta.handoffs.items()):
            mod = by_path[path]
            yield from self._check_reads(ta, mod, h)
            yield from self._check_republish(ta, mod, h)

    def _check_reads(self, ta: ThreadAnalysis, mod: ModuleInfo,
                     h: Handoff) -> Iterable[Finding]:
        for qual, fn in sorted(mod.functions.items()):
            # the spawned closures themselves are the PRODUCER side —
            # their record writes/reads happen on the handoff thread
            if any(qual.startswith(sq + ".") for sq in h.spawner_quals):
                continue
            aliases = ta.record_aliases(mod, fn, qual, h)
            if not aliases:
                continue
            barriers = ta.join_barrier_lines(mod, fn, qual, h)
            spawn_line = h.spawn_lines.get(qual)
            rec_local = h.record_locals.get(qual)
            callers_joined: Dict[str, bool] = {}
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.ctx, ast.Load)
                        and isinstance(node.value, ast.Name)
                        and node.value.id in aliases
                        and node.attr in h.data_fields):
                    continue
                if mod.scope_of(node) != qual:
                    continue
                _bind_line, pre_joined, kind = aliases[node.value.id]
                if pre_joined:
                    continue
                if spawn_line is not None and node.value.id == rec_local:
                    if node.lineno <= spawn_line:
                        continue  # before start(): the thread isn't running
                    if any(spawn_line < b < node.lineno for b in barriers):
                        continue
                    if self._spawn_arm_returns(mod, fn, h, qual, node):
                        continue  # the spawning branch returned — this
                        # read only executes on the no-spawn path
                else:
                    if any(b < node.lineno for b in barriers):
                        continue  # a join of this handoff's thread attr
                        # precedes the read (binding through the publish
                        # field after the join sees a joined record)
                    if kind == "param":
                        key = node.value.id
                        if key not in callers_joined:
                            callers_joined[key] = self._callsites_joined(
                                ta, mod, qual, h)
                        if callers_joined[key]:
                            continue  # every caller joins before passing
                yield mod.finding(
                    self.name, "read-before-join", node,
                    f"`{node.value.id}.{node.attr}` is a {h.cls} handoff "
                    f"field written by its spawned thread, but no "
                    f"`.{'/'.join(sorted(h.thread_attrs))}.join()` "
                    f"dominates this read in `{qual}` — the value may "
                    f"still be mid-write")

    @staticmethod
    def _spawn_arm_returns(mod: ModuleInfo, fn: ast.AST, h: Handoff,
                           qual: str, read: ast.AST) -> bool:
        """True when an ``if`` arm containing the spawn — but not the read
        — ends in return/raise: control never flows from the spawn to the
        read (the scheduler's async-walk arm returns the record; the sync
        arm below it fills the same fields on the main thread)."""
        spawn = h.spawn_nodes.get(qual)
        if spawn is None:
            return False
        cur = spawn
        for anc in mod.ancestors(spawn):
            if anc is fn:
                break
            if isinstance(anc, ast.If) and \
                    not any(n is read for n in ast.walk(anc)):
                for arm in (anc.body, anc.orelse):
                    if any(any(n is cur for n in ast.walk(s)) for s in arm):
                        if arm and isinstance(arm[-1],
                                              (ast.Return, ast.Raise)):
                            return True
            cur = anc
        return False

    @staticmethod
    def _callsites_joined(ta: ThreadAnalysis, mod: ModuleInfo,
                          qual: str, h: Handoff) -> bool:
        """Caller-side domination for annotated record parameters: every
        resolvable call site of ``qual`` in this module either follows a
        join barrier in its own function or passes an already-joined
        alias (`_bind_phase(fl, …)` is only called after `_complete(fl)`
        joined the fetch thread)."""
        target_key = (mod.path, qual)
        sites = []
        for cqual, cfn in mod.functions.items():
            if cqual == qual:
                continue
            for node in ast.walk(cfn):
                if not isinstance(node, ast.Call) or \
                        mod.scope_of(node) != cqual:
                    continue
                if target_key in ta.dfa.resolve_call(mod, cqual, node):
                    sites.append((cqual, cfn, node))
        if not sites:
            return False
        for cqual, cfn, node in sites:
            if any(b < node.lineno
                   for b in ta.join_barrier_lines(mod, cfn, cqual, h)):
                continue
            aliases = ta.record_aliases(mod, cfn, cqual, h)
            if any(isinstance(a, ast.Name) and a.id in aliases
                   and aliases[a.id][1]
                   for a in list(node.args)
                   + [kw.value for kw in node.keywords]):
                continue
            return False
        return True

    def _check_republish(self, ta: ThreadAnalysis, mod: ModuleInfo,
                         h: Handoff) -> Iterable[Finding]:
        for qual in sorted(h.spawner_quals):
            fn = mod.functions.get(qual)
            if fn is None or not h.publish_fields:
                continue
            rec_local = h.record_locals.get(qual)
            barriers = ta.join_barrier_lines(mod, fn, qual, h)
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == rec_local
                        and mod.scope_of(node) == qual):
                    continue
                pubs = [t for t in node.targets
                        if isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and t.attr in h.publish_fields]
                if not pubs:
                    continue
                attr = pubs[0].attr
                guarded = any(b < node.lineno for b in barriers) or any(
                    isinstance(n, ast.Attribute)
                    and isinstance(n.ctx, ast.Load)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self" and n.attr == attr
                    and mod.scope_of(n) == qual
                    and n.lineno < node.lineno
                    for n in ast.walk(fn))
                if guarded:
                    continue
                yield mod.finding(
                    self.name, "republish-while-live", node,
                    f"`self.{attr}` is republished with a fresh {h.cls} "
                    f"without first checking or joining the previous one "
                    f"in `{qual}` — an in-flight consumer would be "
                    f"orphaned")


@register_check
class ThreadLocalContextCheck(Check):
    name = "thread-local-context"
    description = ("implicit thread-local context passing: module-level "
                   "threading.local() and class thread-locals leaking "
                   "outside their class")

    @staticmethod
    def _makes_local(value: ast.AST) -> bool:
        return any(
            isinstance(n, ast.Call)
            and dotted_name(n.func).rsplit(".", 1)[-1] == "local"
            for n in ast.walk(value))

    def run(self, project: Project) -> Iterable[Finding]:
        # (path, owning class qualname, attr) for self.<attr> = local()
        owners: List[Tuple[str, str, str]] = []
        for mod in project.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Assign) or \
                        not self._makes_local(node.value):
                    continue
                scope = mod.scope_of(node)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and scope == "":
                        yield mod.finding(
                            self.name, "implicit-thread-local", node,
                            f"module-level `threading.local()` blob "
                            f"`{tgt.id}` — context must be passed "
                            f"explicitly (argument or record field), not "
                            f"smuggled through thread-local state")
                    elif isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        cls_qual = scope.rsplit(".", 1)[0] if "." in scope \
                            else ""
                        owners.append((mod.path, cls_qual, tgt.attr))
        for path, cls_qual, attr in owners:
            for mod in project.modules:
                for node in ast.walk(mod.tree):
                    if not (isinstance(node, ast.Attribute)
                            and node.attr == attr):
                        continue
                    scope = mod.scope_of(node)
                    inside = (mod.path == path
                              and (scope == cls_qual
                                   or scope.startswith(cls_qual + ".")))
                    if inside:
                        continue
                    yield mod.finding(
                        self.name, "thread-local-escape", node,
                        f"thread-local attr `.{attr}` (owned by "
                        f"`{cls_qual}` in {path}) is touched outside its "
                        f"owning class — per-thread state must not leak "
                        f"across component boundaries")


@register_check
class DaemonLifecycleCheck(Check):
    name = "daemon-lifecycle"
    description = ("spawned threads must be joined or wired to a stop/"
                   "close path; executors need a shutdown path")

    def run(self, project: Project) -> Iterable[Finding]:
        ta = thread_analysis_for(project)
        by_path = project.by_path()
        for sp in ta.spawns:
            mod = by_path[sp.path]
            if sp.kind == "executor":
                if not self._has_shutdown(mod, sp):
                    yield mod.finding(
                        self.name, "unmanaged-executor", sp.call,
                        "ThreadPoolExecutor constructed with no "
                        "`.shutdown(` call in the owning class/module — "
                        "worker threads leak past close")
                continue
            if sp.kind in ("submit", "map"):
                continue  # lifecycle owned by the executor's shutdown
            if self._managed(ta, mod, sp):
                continue
            yield mod.finding(
                self.name, "unjoined-thread", sp.call,
                f"thread spawned here ({sp.role}) is never joined and "
                f"polls no stop signal wired to a "
                f"{'/'.join(sorted(STOP_METHODS))} path — it outlives "
                f"its owner")

    @staticmethod
    def _has_shutdown(mod: ModuleInfo, sp: SpawnSite) -> bool:
        scope: ast.AST = mod.tree
        for anc in mod.ancestors(sp.call):
            if isinstance(anc, ast.ClassDef):
                scope = anc
                break
        return any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr == "shutdown"
            for n in ast.walk(scope))

    def _managed(self, ta: ThreadAnalysis, mod: ModuleInfo,
                 sp: SpawnSite) -> bool:
        if sp.store_attr:
            # `self._thread = Thread(…)` — the join must live in the SAME
            # class (another class joining its own `_thread` proves
            # nothing); record-stored handles (`fl.fetch_thread`) may be
            # joined anywhere in the module (the scheduler joins them in
            # _complete / abandon_inflight)
            scope: ast.AST = mod.tree
            if sp.store_obj == "self":
                for anc in mod.ancestors(sp.call):
                    if isinstance(anc, ast.ClassDef):
                        scope = anc
                        break
            if any(isinstance(n, ast.Call)
                   and isinstance(n.func, ast.Attribute)
                   and n.func.attr in ("join", "cancel")
                   and isinstance(n.func.value, ast.Attribute)
                   and n.func.value.attr == sp.store_attr
                   for n in ast.walk(scope)):
                return True
            # swap-join idiom: `t, self.<attr> = self.<attr>, None` then
            # `t.join(…)` — the handle moves to a local before the join
            swapped: Set[str] = set()
            for n in ast.walk(scope):
                if not isinstance(n, ast.Assign):
                    continue
                tgts, vals = n.targets, [n.value]
                if len(tgts) == 1 and isinstance(tgts[0], ast.Tuple) and \
                        isinstance(n.value, ast.Tuple):
                    tgts, vals = tgts[0].elts, n.value.elts
                for t, v in zip(tgts, vals):
                    if isinstance(t, ast.Name) and \
                            isinstance(v, ast.Attribute) and \
                            v.attr == sp.store_attr:
                        swapped.add(t.id)
            if swapped and any(
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in ("join", "cancel")
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id in swapped
                    for n in ast.walk(scope)):
                return True
        if sp.store_local:
            # a local thread handle with any `.join(` in the same function
            # (the flood battery joins its reader pool in a loop)
            fn = mod.functions.get(sp.spawner_qual)
            if fn is not None and any(
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in ("join", "cancel")
                    for n in ast.walk(fn)):
                return True
        return self._polls_managed_stop(ta, mod, sp)

    def _polls_managed_stop(self, ta: ThreadAnalysis, mod: ModuleInfo,
                            sp: SpawnSite) -> bool:
        """The target polls a stop signal (`X.is_set()` / `X.wait(` /
        `self.<f>` loop flag) that a stop/close path or sibling closure
        sets."""
        if sp.target_key is None or sp.target_key[0] != mod.path:
            return False
        target = mod.functions.get(sp.target_key[1])
        if target is None:
            return False
        names: Set[str] = set()
        self_flags: Set[str] = set()
        for node in ast.walk(target):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("is_set", "wait"):
                recv = node.func.value
                if isinstance(recv, ast.Name):
                    names.add(recv.id)
                elif isinstance(recv, ast.Attribute) and \
                        isinstance(recv.value, ast.Name) and \
                        recv.value.id == "self":
                    self_flags.add(recv.attr)
            elif isinstance(node, ast.While):
                for n in ast.walk(node.test):
                    if isinstance(n, ast.Attribute) and \
                            isinstance(n.value, ast.Name) and \
                            n.value.id == "self":
                        self_flags.add(n.attr)
        if names and sp.spawner_qual in mod.functions:
            # sibling closures of the spawner may own the setter (the
            # client's `unwatch` closure calls `stop.set()`)
            spawner = mod.functions[sp.spawner_qual]
            for node in ast.walk(spawner):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "set" and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id in names:
                    return True
        if self_flags:
            cls = None
            for anc in mod.ancestors(sp.call):
                if isinstance(anc, ast.ClassDef):
                    cls = anc
                    break
            if cls is not None:
                for meth in cls.body:
                    if not isinstance(meth, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                        continue
                    if meth.name not in STOP_METHODS:
                        continue
                    for node in ast.walk(meth):
                        if isinstance(node, ast.Call) and \
                                isinstance(node.func, ast.Attribute) and \
                                node.func.attr == "set" and \
                                isinstance(node.func.value, ast.Attribute) \
                                and isinstance(node.func.value.value,
                                               ast.Name) \
                                and node.func.value.value.id == "self" \
                                and node.func.value.attr in self_flags:
                            return True
                        if isinstance(node, ast.Assign):
                            for t in node.targets:
                                if isinstance(t, ast.Attribute) and \
                                        isinstance(t.value, ast.Name) and \
                                        t.value.id == "self" and \
                                        t.attr in self_flags:
                                    return True
        return False
