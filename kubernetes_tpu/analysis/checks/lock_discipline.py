"""lock-discipline: attributes mutated both under and outside a class's lock.

For every class that owns a threading.Lock/RLock attribute (sim/store.py
ObjectStore, client/informer.py Reflector, metrics/registry.py Counter /
Histogram, utils/compilemon.py CompileMonitor), each ``self.X`` mutation
site is classified as locked (lexically inside ``with self.<lock>``) or
unlocked.  An attribute with BOTH kinds of site is a discipline break: the
unlocked sites race the protected ones.

Helper-method propagation keeps private helpers honest without false
positives: a method whose intra-class call sites are ALL lock-held is
itself treated as lock-held (ObjectStore._emit is only ever called under
``self._lock`` from create/update/delete/bind_pod).  ``__init__`` is
exempt — the object is not shared yet.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from ..core import Finding, ModuleInfo, Project, dotted_name
from ..registry import Check, register_check

MUTATING_METHODS = {"append", "add", "remove", "pop", "popitem", "clear",
                    "update", "extend", "insert", "discard", "setdefault"}
EXEMPT_METHODS = {"__init__", "__new__"}


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """self.X assigned a value whose expression constructs a *Lock()."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        makes_lock = any(
            isinstance(n, ast.Call)
            and dotted_name(n.func).rsplit(".", 1)[-1] in ("Lock", "RLock")
            for n in ast.walk(node.value))
        if not makes_lock:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
                out.add(tgt.attr)
    return out


def _self_attr(node: ast.AST) -> str:
    """'X' when node is self.X (through one optional subscript), else ''."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return ""


class _Site:
    __slots__ = ("attr", "node", "method", "lexically_locked")

    def __init__(self, attr: str, node: ast.AST, method: str,
                 lexically_locked: bool):
        self.attr = attr
        self.node = node
        self.method = method
        self.lexically_locked = lexically_locked


def _lock_wrappers(cls: ast.ClassDef, locks: Set[str]) -> Set[str]:
    """Contextmanager methods that hold the lock for their caller: a
    generator method whose yield sits inside ``with self.<lock>`` (the
    store's _locked_emit pattern) — ``with self.wrapper():`` in another
    method then counts as lock-held."""
    out: Set[str] = set()
    for meth in cls.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(meth):
            if isinstance(node, ast.With) and any(
                    _self_attr(i.context_expr) in locks
                    for i in node.items):
                if any(isinstance(n, (ast.Yield, ast.YieldFrom))
                       for n in ast.walk(node)):
                    out.add(meth.name)
                    break
    return out


def _under_lock(mod: ModuleInfo, node: ast.AST, locks: Set[str],
                stop: ast.AST, wrappers: Set[str] = frozenset()) -> bool:
    cur = mod.parents.get(node)
    while cur is not None and cur is not stop:
        if isinstance(cur, ast.With):
            for item in cur.items:
                expr = item.context_expr
                if _self_attr(expr) in locks:
                    return True
                if isinstance(expr, ast.Call) and \
                        _self_attr(expr.func) in wrappers:
                    return True
        cur = mod.parents.get(cur)
    return False


def _method_of(mod: ModuleInfo, node: ast.AST, cls_qual: str) -> str:
    """Bare name of the class method whose body contains node ('' if not)."""
    scope = mod.scope_of(node)
    if not scope.startswith(cls_qual + "."):
        return ""
    return scope[len(cls_qual) + 1:].split(".", 1)[0]


def _mutation_sites(mod: ModuleInfo, cls: ast.ClassDef, cls_qual: str,
                    locks: Set[str],
                    wrappers: Set[str] = frozenset()) -> List[_Site]:
    sites: List[_Site] = []

    def add(attr: str, node: ast.AST):
        method = _method_of(mod, node, cls_qual)
        if not method or method in EXEMPT_METHODS or attr in locks:
            return
        sites.append(_Site(attr, node, method,
                           _under_lock(mod, node, locks, cls, wrappers)))

    for node in ast.walk(cls):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                attr = _self_attr(tgt)
                if attr:
                    add(attr, node)
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr:
                    add(attr, node)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in MUTATING_METHODS:
            attr = _self_attr(node.func.value)
            if attr:
                add(attr, node)
    return sites


def _intra_class_calls(
        mod: ModuleInfo, cls: ast.ClassDef, cls_qual: str, locks: Set[str],
        wrappers: Set[str] = frozenset()
) -> Dict[str, List[Tuple[str, bool, ast.Call]]]:
    """method -> [(caller, lexically_locked, call_node)] for self.m() calls."""
    methods = {n.name for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    calls: Dict[str, List[Tuple[str, bool, ast.Call]]] = {m: [] for m in methods}
    for node in ast.walk(cls):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == "self" and node.func.attr in methods:
            caller = _method_of(mod, node, cls_qual)
            if caller:
                calls[node.func.attr].append(
                    (caller, _under_lock(mod, node, locks, cls, wrappers),
                     node))
    return calls


def _always_locked_methods(
        calls: Dict[str, List[Tuple[str, bool, ast.Call]]]) -> Set[str]:
    """Fixed point: methods whose every intra-class call site is lock-held
    (lexically, or inside an already always-locked method)."""
    locked: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for m, sites in calls.items():
            if m in locked or not sites:
                continue
            if all(lex or caller in locked for caller, lex, _ in sites):
                locked.add(m)
                changed = True
    return locked


@register_check
class LockDisciplineCheck(Check):
    name = "lock-discipline"
    description = ("attributes of lock-owning classes mutated both under "
                   "and outside the lock")

    def run(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for mod in project.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    findings.extend(self._scan_class(mod, node))
        return findings

    def _scan_class(self, mod: ModuleInfo,
                    cls: ast.ClassDef) -> Iterable[Finding]:
        locks = _lock_attrs(cls)
        if not locks:
            return
        # scope_of(cls) is the scope INSIDE the class body (its qualname)
        cls_qual = mod.scope_of(cls) or cls.name
        wrappers = _lock_wrappers(cls, locks)
        sites = _mutation_sites(mod, cls, cls_qual, locks, wrappers)
        calls = _intra_class_calls(mod, cls, cls_qual, locks, wrappers)
        propagated = _always_locked_methods(calls)
        lock_desc = "/".join(sorted(locks))

        # mixed-helper-call: a helper that mutates state and is reached
        # both under the lock and without it — its mutations are only
        # protected on SOME paths (client/informer.py's _apply_relist
        # called from the locked error path AND from run()).
        mutating_methods = {s.method for s in sites}
        for method, call_sites in sorted(calls.items()):
            if method not in mutating_methods or method in propagated:
                continue
            locked_calls = [c for c in call_sites
                            if c[1] or c[0] in propagated]
            unlocked_calls = [c for c in call_sites
                              if not (c[1] or c[0] in propagated)]
            if not locked_calls or not unlocked_calls:
                continue
            for caller, _, node in unlocked_calls:
                yield mod.finding(
                    self.name, "mixed-helper-call", node,
                    f"`self.{method}()` mutates state and is called under "
                    f"`self.{lock_desc}` elsewhere, but WITHOUT it here in "
                    f"`{caller}` — the helper's writes are unprotected on "
                    f"this path ({cls.name})")

        by_attr: Dict[str, List[_Site]] = {}
        for s in sites:
            by_attr.setdefault(s.attr, []).append(s)
        for attr, attr_sites in sorted(by_attr.items()):
            locked = [s for s in attr_sites
                      if s.lexically_locked or s.method in propagated]
            unlocked = [s for s in attr_sites
                        if not (s.lexically_locked or s.method in propagated)]
            if not locked or not unlocked:
                continue
            for s in unlocked:
                yield mod.finding(
                    self.name, "mixed-lock-use", s.node,
                    f"`self.{attr}` is mutated under `self.{lock_desc}` at "
                    f"{len(locked)} site(s) but WITHOUT it here in "
                    f"`{s.method}` — unlocked writes race the protected "
                    f"ones ({cls.name})")
